"""Throughput benchmark — prints ONE JSON line with the judged metric
(BASELINE.json: images/sec/chip for VGG-F training).

Runs the full jitted DP train step (forward, loss+wd, backward, pmean all-reduce,
SGD-momentum apply — one XLA computation) on synthetic data so device step time is
isolated from host input (SURVEY.md §4 throughput harness).

`vs_baseline`: the reference publishes no numbers (BASELINE.json `published: {}`,
SURVEY.md §6), so the ratio is computed against `benchmarks/baseline.json` —
frozen from this framework's first measured round — and 1.0 when absent.
"""

from __future__ import annotations

import argparse
import dataclasses
import io
import json
import os
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=1024)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--model", default="vggf")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--update-baseline", action="store_true",
                        help="freeze this run's value as benchmarks/baseline.json")
    args = parser.parse_args()

    import jax

    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, ModelConfig, OptimConfig, TrainConfig)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    num_chips = jax.device_count()
    batch = args.batch_size * max(1, num_chips)

    cfg = ExperimentConfig(
        name=f"bench_{args.model}",
        model=ModelConfig(name=args.model, num_classes=1000,
                          compute_dtype="bfloat16"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=batch),
        data=DataConfig(name="synthetic", image_size=args.image_size,
                        global_batch_size=batch),
        train=TrainConfig(steps=args.steps, log_every=10_000, seed=0),
    )
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = trainer.init_state()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=batch, image_size=args.image_size,
                          num_classes=1000, seed=0, fixed=True,
                          image_dtype="bfloat16")
    sharded = trainer.shard(next(ds))

    # NOTE: sync via a value fetch, not block_until_ready — on this machine's
    # tunneled TPU backend block_until_ready does not synchronize, which would
    # time only async dispatch.
    for _ in range(args.warmup):
        state, metrics = trainer.train_step(state, sharded, rng)
    float(jax.device_get(metrics["loss"]))

    t0 = time.monotonic()
    for _ in range(args.steps):
        state, metrics = trainer.train_step(state, sharded, rng)
    float(jax.device_get(metrics["loss"]))
    elapsed = time.monotonic() - t0

    images_per_sec = batch * args.steps / elapsed
    per_chip = images_per_sec / num_chips

    metric = f"{args.model}_train_images_per_sec_per_chip"
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "benchmarks", "baseline.json")
    # baseline.json maps metric name -> frozen entry, so per-model baselines
    # coexist (a legacy single-entry file is migrated on read).
    baselines = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            data = json.load(f)
        baselines = {data["metric"]: data} if "metric" in data else data
    vs_baseline = 1.0
    if args.update_baseline:
        baselines[metric] = {"metric": metric, "value": per_chip,
                             "platform": jax.devices()[0].platform,
                             "device_kind": jax.devices()[0].device_kind}
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        with open(baseline_path, "w") as f:
            json.dump(baselines, f)
    elif baselines.get(metric, {}).get("value"):
        vs_baseline = per_chip / baselines[metric]["value"]

    print(json.dumps({
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
