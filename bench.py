"""Throughput benchmark — prints ONE JSON line with the judged metric
(BASELINE.json: images/sec/chip for VGG-F training).

Two modes:

- default (device bench): the full jitted DP train step (forward, loss+wd,
  backward, pmean all-reduce, SGD-momentum apply — one XLA computation) on a
  resident synthetic batch, isolating device step time from host input
  (SURVEY.md §4 throughput harness). Adds `mfu_est`: ANALYTIC jaxpr-counted
  matmul/conv FLOPs (utils/flops.py) per step / step time / the chip's bf16
  peak, with XLA's per-partition cost-analysis figure as the `mfu_est_xla`
  cross-check.
- `--pipeline imagenet` (end-to-end bench): the same train step driven through
  the REAL input path — fake 224-px JPEG TFRecords generated locally once,
  decoded by data/imagenet.py's tf.data pipeline, device-prefetched
  (data/prefetch.py). Reports end-to-end img/s/chip plus `device_only`,
  `host_pipeline` img/s/chip and the `infeed_stall_fraction` — SURVEY.md §7
  names the host path as where the ≥90 % scaling-efficiency target is won or
  lost, so this is the number that bounds real training.

`vs_baseline`: the reference publishes no numbers (BASELINE.json
`published: {}`, SURVEY.md §6), so the ratio is computed against
`benchmarks/baseline.json` — frozen from this framework's first measured
round per metric — and 1.0 when absent.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))

def _peak_flops_for(device_kind: str) -> float | None:
    """bf16 peak FLOP/s for the MFU estimate — one table, owned by
    utils/mxu_model (simplify r5: this file used to carry its own copy)."""
    from distributed_vgg_f_tpu.utils.mxu_model import (
        DEVICE_KIND_TO_CHIP, _peak)
    try:
        return _peak(DEVICE_KIND_TO_CHIP[device_kind])
    except KeyError:
        return None


def _last_good_path() -> str:
    return os.environ.get("DVGGF_LAST_GOOD",
                          os.path.join(REPO, "benchmarks", "last_good.json"))


def _registry_key(metric: str, batch_size, model_extra: dict | None) -> str:
    """Registry key = metric + full distinguishing config. A metric name
    alone is ambiguous — the session protocol runs the same model at
    several batch sizes and --model-extra variants, and a batch-1024 or
    s2d-stem number cited as "last good" for the DEFAULT config would be a
    wrong number wearing a right label (code-review r4)."""
    key = f"{metric}|bs={batch_size}"
    if model_extra:
        key += "|" + ",".join(f"{k}={model_extra[k]}"
                              for k in sorted(model_extra))
    return key


def _read_last_good(key: str) -> dict | None:
    try:
        with open(_last_good_path()) as f:
            data = json.load(f)
        return data.get(key) if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


def _record_last_good(key: str, entry: dict) -> None:
    """Registry of the most recent HEALTHY on-chip measurement per exact
    config, committed with the session artifacts — what failure records
    cite."""
    path = _last_good_path()
    try:
        data = {}
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
        if not isinstance(data, dict):   # corrupted/hand-edited registry:
            data = {}                    # start over rather than crash
        data[key] = entry
        with open(path, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
    except (OSError, ValueError):
        pass   # recording is best-effort; never fail a bench over it


def _emit_failure(metric: str, err: dict,
                  registry_key: str | None = None) -> dict:
    """The failure counterpart of the contract line: same keys, value null,
    plus an ``error`` tag the driver can parse instead of a stack trace.

    When the committed registry holds a previous healthy measurement for
    this exact config (`registry_key`; see _registry_key), the record
    embeds it as ``last_committed`` with ``stale: true`` — so a
    wedged-tunnel round end degrades to "stale number, clearly labeled"
    instead of pure null (VERDICT r3 #2). The ``value`` field stays null
    on purpose: reporting a stale number as THE measurement would be
    gaming, not measuring. Returns the record so the caller can pick its
    exit code from what was actually emitted (the watchdog exits 0 when a
    stale payload made the line a usable result — BENCH_r05: an rc=1 with
    the payload attached still failed the whole run)."""
    rec = {"metric": metric, "value": None,
           "unit": "images/sec/chip", "vs_baseline": None, **err}
    last = _read_last_good(registry_key) if registry_key else None
    if last is not None:
        rec["last_committed"] = last
        rec["stale"] = True
        # how stale, precomputed: BENCH_r05 showed a stale:true payload
        # with no age, forcing readers to do ISO-date math by hand
        age = _age_days(last.get("ts"))
        if age is not None:
            rec["last_committed_age_days"] = age
        # r11 staleness hygiene: cite the cited run's ingest-autotune
        # settled-state explicitly — a future TPU-grant comparison against
        # this number must know whether it was a hand-pinned or a
        # controller-settled (or, worse, mid-convergence) rate. Entries
        # predating the field read as {"enabled": null} = "unknown", never
        # as a silent "off".
        rec["last_committed_autotune"] = last.get(
            "autotune", {"enabled": None})
    print(json.dumps(rec), flush=True)
    return rec


def _age_days(ts: str | None) -> float | None:
    """Days elapsed since an ISO-8601 timestamp (the registry's `ts`
    field), or None when the payload predates the field or is malformed —
    an unparseable stale record must still be emitted, just without the
    convenience."""
    if not isinstance(ts, str):
        return None
    import datetime
    try:
        then = datetime.datetime.fromisoformat(ts)
    except ValueError:
        return None
    if then.tzinfo is None:  # naive timestamps are UTC by registry contract
        then = then.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return round(max(0.0, (now - then).total_seconds()) / 86400.0, 2)


def _run_with_watchdog(metric: str, budget_s: float,
                       registry_key: str | None = None) -> None:
    """Run the real bench as a CHILD process; the parent only watches the
    clock and the driver-facing stdout contract.

    Why this shape (round-2/3 postmortem, .claude/skills/verify/SKILL.md):
    this machine's TPU is a single-grant tunnel with a client QUEUE. A client
    killed while waiting for the grant becomes a dead queue entry, and when
    the grant frees it can be assigned to that dead client — wedging the
    tunnel for a full lease per dead entry. Round 2's bench hung >300 s
    inside backend init and the driver recorded rc=1 with no JSON; probing
    first doesn't help, because the probe and the bench are separate clients
    and the bench can still land behind a dead entry (observed this round).

    So: on budget expiry the parent prints a machine-readable failure line
    and exits nonzero — but deliberately does NOT kill the child. An alive
    waiting client is harmless (it eventually gets the grant, runs a few
    steps, and exits); a killed waiting client is exactly what wedges the
    next run. The child's output keeps streaming to the log files named in
    the failure record for post-mortem.
    """
    fd_out, out_path = tempfile.mkstemp(prefix="bench_child_", suffix=".out")
    fd_err, err_path = tempfile.mkstemp(prefix="bench_child_", suffix=".err")
    if os.environ.get("DVGGF_BENCH_CHILD_ARGV"):  # test hook
        child_argv = json.loads(os.environ["DVGGF_BENCH_CHILD_ARGV"])
    else:
        child_argv = ([sys.executable, os.path.abspath(__file__)]
                      + sys.argv[1:] + ["--no-watchdog"])
    with os.fdopen(fd_out, "wb") as out_f, os.fdopen(fd_err, "wb") as err_f:
        child = subprocess.Popen(child_argv, stdout=out_f, stderr=err_f,
                                 cwd=REPO)
    deadline = time.monotonic() + budget_s
    while child.poll() is None and time.monotonic() < deadline:
        time.sleep(1.0)
    if child.poll() is None:
        # The child may have PRINTED its result and then wedged in backend
        # teardown/grant release — the judged number exists; forward it
        # rather than reporting a failed run.
        try:
            with open(out_path) as f:
                for line in f:
                    if not line.startswith("{"):
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if "metric" in rec and rec.get("value") is not None:
                        print(line.rstrip(), flush=True)
                        for p in (out_path, err_path):
                            try:  # rescued result: logs served their purpose
                                os.unlink(p)
                            except OSError:
                                pass
                        sys.exit(0)
        except OSError:
            pass
        rec = _emit_failure(metric, {
            "error": "tpu_unavailable",
            "detail": f"bench child (pid {child.pid}) made no result within "
                      f"{budget_s:.0f}s — single-grant tunnel busy or "
                      f"wedged; child left ALIVE on purpose (killing a "
                      f"waiting client wedges the next run)",
            "child_stdout": out_path, "child_stderr": err_path},
            registry_key=registry_key)
        # A stale-but-labeled payload IS the round's result line for a
        # wedged tunnel: exit 0 so the session driver records it instead of
        # failing the run (the record still says error=tpu_unavailable,
        # value=null, stale=true — nothing is promoted). With no committed
        # last-good for this exact config there is nothing usable: exit 1.
        sys.exit(0 if "last_committed" in rec else 1)
    with open(out_path) as f:
        sys.stdout.write(f.read())
    sys.stdout.flush()
    with open(err_path) as f:
        sys.stderr.write(f.read()[-4000:])
    for p in (out_path, err_path):  # keep them only on budget expiry,
        try:                        # where the failure record names them
            os.unlink(p)
        except OSError:
            pass
    sys.exit(child.returncode)


def _make_trainer(args, data_cfg, model_extra=None):
    from distributed_vgg_f_tpu.config import (
        ExperimentConfig, ModelConfig, OptimConfig, TrainConfig,
        apply_overrides)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = ExperimentConfig(
        name=f"bench_{args.model}",
        model=ModelConfig(name=args.model, num_classes=1000,
                          compute_dtype="bfloat16",
                          extra=model_extra or {}),
        optim=OptimConfig(base_lr=0.01,
                          reference_batch_size=data_cfg.global_batch_size),
        data=data_cfg,
        train=TrainConfig(steps=args.steps, log_every=10_000, seed=0),
    )
    # --set KEY=VALUE (r13): dotted overrides through the SAME folding as
    # the trainer CLI (config.fold_override_items) — how the session
    # scripts bench augment/ZeRO-1 on/off pairs (e.g.
    # --set data.augment.enabled=true, --set mesh.shard_opt_state=true)
    # without a flag per knob.
    from distributed_vgg_f_tpu.config import fold_override_items
    try:
        overrides = fold_override_items(getattr(args, "set", None))
    except ValueError as e:
        raise SystemExit(f"--set: {e}")
    if overrides:
        cfg = apply_overrides(cfg, overrides)
    return Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))


def _parsed_model_extra(args) -> dict:
    """--model-extra KEY=VALUE entries as a typed dict (config's rules)."""
    from distributed_vgg_f_tpu.config import parse_extra_value

    extra = {}
    for kv in getattr(args, "model_extra", []) or []:
        key, sep, value = kv.partition("=")
        if not sep or not key:
            raise SystemExit(f"--model-extra needs KEY=VALUE, got {kv!r}")
        extra[key] = parse_extra_value(value)
    return extra


def _emit(metric, per_chip, *, update_baseline=False, extra=None,
          registry_key=None):
    """Print the contract JSON line, with vs_baseline from the frozen
    per-metric baseline file (see module docstring)."""
    import jax

    baseline_path = os.path.join(REPO, "benchmarks", "baseline.json")
    baselines = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            data = json.load(f)
        baselines = {data["metric"]: data} if "metric" in data else data
    vs_baseline = 1.0
    if update_baseline:
        baselines[metric] = {"metric": metric, "value": per_chip,
                             "platform": jax.devices()[0].platform,
                             "device_kind": jax.devices()[0].device_kind}
        if extra and extra.get("model_extra"):
            # a variant config must be visible in the frozen record — a
            # baseline silently redefined by a --model-extra run would make
            # every later default-config ratio a lie (code-review r3)
            baselines[metric]["model_extra"] = extra["model_extra"]
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        with open(baseline_path, "w") as f:
            json.dump(baselines, f)
    elif baselines.get(metric, {}).get("value"):
        vs_baseline = per_chip / baselines[metric]["value"]

    record = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }
    record.update(extra or {})
    print(json.dumps(record))

    if jax.devices()[0].platform == "tpu" and registry_key:
        # refresh the committed last-known-good registry (what failure
        # records cite when the tunnel is wedged) — real-chip runs only, so
        # CPU test invocations never pollute it
        import datetime
        # ingest-autotune state of THIS run (r11): the trainer registers
        # its controller with the exporter module when armed; a bench run
        # without one records enabled=false. Future stale-payload citations
        # surface this so grant-to-grant comparisons are apples-to-apples.
        from distributed_vgg_f_tpu.telemetry import exporter as _exp
        at = _exp.autotune_payload()
        at_state = ({"enabled": True, "settled": bool(at.get("settled")),
                     "actuations_total": at.get("actuations_total")}
                    if at.get("enabled") else {"enabled": False})
        _record_last_good(registry_key, {
            "value": record["value"], "unit": record["unit"],
            "autotune": at_state,
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"),
            # provenance: the run artifact this number will be committed
            # under (tpu_session.sh exports it per invocation); the registry
            # itself is only the fallback pointer
            "artifact": os.environ.get("DVGGF_BENCH_ARTIFACT",
                                       "benchmarks/last_good.json"),
            **({"model_extra": extra["model_extra"]}
               if extra and extra.get("model_extra") else {}),
        })


def _step_flops(trainer, state, batch, rng):
    """(analytic, xla, views) for one train step (whole mesh).

    `analytic` is the shape-exact matmul/conv FLOP total, counted before
    XLA optimization — the validated MFU basis (VERDICT r2 #8:
    cost_analysis can double-count fused recomputation). It is derived
    from the SAME single trace that yields the roofline GEMM `views`
    (utils/mxu_model.views_from_jaxpr shares the FLOP counter's
    walk_matmul_eqns and per-op formulas, so the sum is identical to
    utils/flops.jaxpr_flops — one make_jaxpr instead of two,
    code-review r5). `xla` is the compiled-program cost analysis, kept
    as a cross-check. Any element may be None/empty on failure."""
    analytic = xla = None
    views = []
    try:
        from distributed_vgg_f_tpu.utils.mxu_model import views_from_jaxpr
        views = views_from_jaxpr(trainer.train_step, state, batch, rng)
        val = sum(v.flops for v in views)
        analytic = val if val > 0 else None
    except Exception:
        views = []
    try:
        compiled = trainer.train_step.lower(state, batch, rng).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        xla = flops if flops > 0 else None
    except Exception:
        pass
    return analytic, xla, views


def run_device_bench(args) -> None:
    """Device-only step throughput on a resident synthetic batch."""
    import jax

    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset

    num_chips = jax.device_count()
    batch = args.batch_size * max(1, num_chips)
    from distributed_vgg_f_tpu.config import supports_space_to_depth

    # VGG-F takes the 4x4 space-to-depth input layout (data.space_to_depth):
    # the host packs once, the device skips the stem relayout (+3.7% at batch
    # 2048 on v5e). --raw-input benches the (S, S, 3) contract instead.
    s2d = supports_space_to_depth(args.model, args.image_size) \
        and not args.raw_input
    model_extra = _parsed_model_extra(args)
    trainer = _make_trainer(args, DataConfig(
        name="synthetic", image_size=args.image_size, global_batch_size=batch,
        space_to_depth=s2d), model_extra)
    state = trainer.init_state()
    rng = trainer.base_rng()
    # the host packs only when the trainer's resolved config says so: with
    # the fused augmentation enabled (--set data.augment.enabled=true) the
    # step packs AFTER augmenting and expects unpacked batches
    # (DataConfig.host_space_to_depth — the r13 ordering contract)
    ds = SyntheticDataset(batch_size=batch, image_size=args.image_size,
                          num_classes=1000, seed=0, fixed=True,
                          image_dtype="bfloat16",
                          space_to_depth=trainer.cfg.data.host_space_to_depth)
    sharded = trainer.shard(next(ds))
    flops, flops_xla, gemm_views = _step_flops(trainer, state, sharded, rng)

    # NOTE: sync via a value fetch, not block_until_ready — on this machine's
    # tunneled TPU backend block_until_ready does not synchronize, which would
    # time only async dispatch.
    for _ in range(args.warmup):
        state, metrics = trainer.train_step(state, sharded, rng)
    if args.warmup:
        float(jax.device_get(metrics["loss"]))

    # min-of-N on step TIME (= best-of-N on rate): each repeat is an
    # independent timed window; the best window is the least host-noise-
    # contaminated sample and median/spread quantify the noise (VERDICT r3
    # #4 — a 1-vCPU host needs variance data before any ratio means much).
    rates = []
    for _ in range(max(1, args.repeats)):
        t0 = time.monotonic()
        for _ in range(args.steps):
            state, metrics = trainer.train_step(state, sharded, rng)
        float(jax.device_get(metrics["loss"]))
        rates.append(batch * args.steps / (time.monotonic() - t0) / num_chips)

    per_chip = max(rates)
    extra = {}
    if args.repeats > 1:
        import statistics
        med = statistics.median(rates)
        extra["repeats"] = args.repeats
        extra["median"] = round(med, 2)
        extra["spread"] = round((max(rates) - min(rates)) / med, 4)
    peak = _peak_flops_for(jax.devices()[0].device_kind)
    step_time = batch / (per_chip * num_chips)   # best window's sec/step
    if flops and peak:
        extra["mfu_est"] = round(flops / num_chips / step_time / peak, 4)
        extra["mfu_basis"] = "analytic_jaxpr"
    if flops_xla and peak:
        # cost_analysis is PER-PARTITION for SPMD executables (measured:
        # mesh=8 reports ~1/8 of mesh=1) — already a per-chip figure
        extra["mfu_est_xla"] = round(flops_xla / step_time / peak, 4)
    try:
        # the measured MFU's own derived ceiling, from the same trace that
        # produced `flops` (utils/mxu_model per-op roofline): [no-overlap,
        # overlap] matmul-only bounds — the measurement should sit below
        # the upper edge; how far below is the non-matmul + bubble share
        from distributed_vgg_f_tpu.utils.mxu_model import (
            DEVICE_KIND_TO_CHIP, achievable_mfu, serial_mfu)
        chip = DEVICE_KIND_TO_CHIP[jax.devices()[0].device_kind]
        if gemm_views:
            extra["mfu_bound_roofline"] = [
                round(serial_mfu(gemm_views, chip=chip), 4),
                round(achievable_mfu(gemm_views, chip=chip), 4)]
    except Exception:
        pass   # bounds are annotation, never a bench failure
    if model_extra:
        # variant runs must be distinguishable from default-config runs in
        # the emitted artifact (and in any baseline they freeze)
        extra["model_extra"] = model_extra
    metric = f"{args.model}_train_images_per_sec_per_chip"
    _emit(metric, per_chip, update_baseline=args.update_baseline, extra=extra,
          registry_key=_registry_key(metric, args.batch_size, model_extra))


# ---------------------------------------------------------------------------
# End-to-end pipeline bench
# ---------------------------------------------------------------------------

def _ensure_fake_imagenet(data_dir: str, *, num_files: int, per_file: int,
                          source_hw=(320, 256)) -> None:
    """Generate fake ImageNet-like JPEG TFRecords once (no network on this
    machine — SURVEY.md §0); reused across runs via the directory cache."""
    import numpy as np

    if any(f.startswith("train-") for f in
           (os.listdir(data_dir) if os.path.isdir(data_dir) else [])):
        return
    import tensorflow as tf
    os.makedirs(data_dir, exist_ok=True)
    # (callers encode num_files/per_file into data_dir, so a cached dir always
    # matches the requested dataset size)
    rng = np.random.default_rng(0)
    h, w = source_hw
    for i in range(num_files):
        path = os.path.join(data_dir, f"train-{i:05d}-of-{num_files:05d}")
        with tf.io.TFRecordWriter(path) as writer:
            for _ in range(per_file):
                img = rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img, quality=90).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(
                            value=[int(rng.integers(1, 1001))])),
                }))
                writer.write(ex.SerializeToString())


def run_pipeline_bench(args) -> None:
    """End-to-end throughput through the real tf.data JPEG path."""
    import jax

    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data.prefetch import maybe_prefetch

    num_chips = jax.device_count()
    batch = args.batch_size * max(1, num_chips)
    # per-size cache subdir: rerunning with different --num-files/--per-file
    # must not silently reuse a differently-sized cached dataset
    data_dir = os.path.join(args.data_dir,
                            f"{args.num_files}x{args.per_file}")
    _ensure_fake_imagenet(data_dir, num_files=args.num_files,
                          per_file=args.per_file)
    from distributed_vgg_f_tpu.config import supports_space_to_depth

    # match the production vggf config: packed space-to-depth train batches
    # (free in the native loader; a tf.nn.space_to_depth map in tf.data)
    s2d = supports_space_to_depth(args.model, args.image_size) \
        and not args.raw_input
    data_cfg = DataConfig(name="imagenet", data_dir=data_dir,
                          image_size=args.image_size, global_batch_size=batch,
                          shuffle_buffer=min(2048, args.num_files * args.per_file),
                          image_dtype="bfloat16",
                          native_jpeg=args.host_pipeline == "native",
                          space_to_depth=s2d,
                          wire=args.wire)
    model_extra = _parsed_model_extra(args)
    trainer = _make_trainer(args, data_cfg, model_extra)
    state = trainer.init_state()
    rng = trainer.base_rng()

    host_ds = trainer.make_dataset("train")
    # report what actually ran: the native loader silently falls back to
    # tf.data when its build is unavailable
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator
    actual_host_pipeline = ("native"
                            if isinstance(host_ds, NativeJpegTrainIterator)
                            else "tfdata")
    # what actually shipped: data.wire='u8' falls back to the host wire
    # when the native u8 path is refused — the artifact must say which
    # wire the measured number rode (mislabeling is worse than fallback).
    # The loader's image_dtype is the receipt; tf.data fallbacks carry no
    # attribute, so the config's resolved host dtype stands in.
    from distributed_vgg_f_tpu.data.dtypes import resolve_wire_dtype
    shipped_dtype = getattr(
        host_ds, "image_dtype",
        resolve_wire_dtype(data_cfg.wire, data_cfg.image_dtype))
    actual_wire = ("u8" if shipped_dtype == "uint8"
                   else "host_bf16" if shipped_dtype == "bfloat16"
                   else "host_f32")

    def one_rep(state, *, warmup: int):
        """One full measurement triple (e2e, device-only, host-alone) on a
        fresh prefetch worker around the shared host stream. Every host-
        sensitive metric is repeated `--repeats` times and aggregated
        min-of-N-time (VERDICT r3 #4): on a 1-vCPU host a single window
        cannot distinguish a regression from a busy neighbor."""
        ds = maybe_prefetch(host_ds, trainer.mesh, buffer_size=2)
        # warmup: compile (first rep) + fill prefetch (every rep)
        st, metrics = state, None
        for _ in range(max(1, warmup)):
            st, metrics = trainer.train_step(st, next(ds), rng)
        float(jax.device_get(metrics["loss"]))

        # NOTE: up to ~2 prefetched + ~2 tf.data-internal batches were
        # produced before t0, so the measured rate reads high by <=
        # ~4/steps — the default step count keeps that bias under ~8%;
        # raise --steps to shrink it.
        t0 = time.monotonic()
        last_batch = None
        for _ in range(args.steps):
            last_batch = next(ds)
            st, metrics = trainer.train_step(st, last_batch, rng)
        float(jax.device_get(metrics["loss"]))
        e2e_elapsed = time.monotonic() - t0

        # Stop the prefetch worker: it must not keep decoding in the
        # background (stealing host CPU, racing the host-alone loop on the
        # same iterator) while the device-only and host-only phases run.
        if hasattr(ds, "close"):
            ds.close()

        # device-only on the final resident batch — same shapes, no host
        for _ in range(2):
            st, metrics = trainer.train_step(st, last_batch, rng)
        float(jax.device_get(metrics["loss"]))
        t0 = time.monotonic()
        for _ in range(args.steps):
            st, metrics = trainer.train_step(st, last_batch, rng)
        float(jax.device_get(metrics["loss"]))
        dev_elapsed = time.monotonic() - t0

        # host pipeline alone (decode+augment+batch, no device work).
        # tf.data's internal prefetch/AUTOTUNE workers kept producing during
        # the untimed device-only phase above; drain those pre-decoded
        # batches so t0 starts against a cold buffer (residual bias from
        # mid-flight work is < 1/steps).
        for _ in range(4):
            next(host_ds)
        t0 = time.monotonic()
        for _ in range(args.steps):
            next(host_ds)
        host_elapsed = time.monotonic() - t0
        return st, (e2e_elapsed, dev_elapsed, host_elapsed)

    reps = []
    for i in range(max(1, args.repeats)):
        state, triple = one_rep(state, warmup=args.warmup if i == 0 else 2)
        reps.append(triple)

    n_img = batch * args.steps
    e2e_per_chip = n_img / min(r[0] for r in reps) / num_chips
    dev_per_chip = n_img / min(r[1] for r in reps) / num_chips
    host_per_sec = n_img / min(r[2] for r in reps)
    # stall from the SAME rep (best e2e window), not a cross-rep mix
    best = min(reps, key=lambda r: r[0])
    stall = max(0.0, 1.0 - best[1] / best[0])
    extra = {
        "device_only_images_per_sec_per_chip": round(dev_per_chip, 2),
        "host_pipeline_images_per_sec": round(host_per_sec, 2),
        "infeed_stall_fraction": round(stall, 4),
        "host_vcpus": os.cpu_count(),
        "host_pipeline": actual_host_pipeline,
        "wire": actual_wire,
    }
    if args.repeats > 1:
        import statistics
        med = statistics.median(n_img / r[0] / num_chips for r in reps)
        extra["repeats"] = args.repeats
        extra["median"] = round(med, 2)
        extra["spread"] = round((e2e_per_chip - min(
            n_img / r[0] / num_chips for r in reps)) / med, 4)
        extra["host_pipeline_median_images_per_sec"] = round(
            statistics.median(n_img / r[2] for r in reps), 2)
    if model_extra:
        extra["model_extra"] = model_extra
    metric = f"{args.model}_e2e_imagenet_images_per_sec_per_chip"
    _emit(metric, e2e_per_chip, update_baseline=args.update_baseline,
          extra=extra,
          registry_key=_registry_key(metric, args.batch_size, model_extra))


def main(as_script: bool = False) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=None,
                        help="per-chip batch (default: 2048 device bench, "
                             "256 pipeline bench)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--model", default="vggf")
    parser.add_argument("--model-extra", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="model.extra entries for the benched config, "
                        "e.g. --model-extra attention_layout=flash")
    parser.add_argument("--steps", type=int, default=None)
    parser.add_argument("--warmup", type=int, default=None)
    parser.add_argument("--repeats", type=int, default=None,
                        help="independent timed windows; the reported value "
                             "is the best window (min total time) with "
                             "median/spread recorded. Default: 3 for the "
                             "host-sensitive --pipeline imagenet bench, 1 "
                             "for the device bench")
    parser.add_argument("--pipeline", choices=("none", "imagenet"),
                        default="none",
                        help="'imagenet': end-to-end bench through the real "
                             "tf.data JPEG path on locally generated fake "
                             "TFRecords")
    parser.add_argument("--data-dir", default="/tmp/dvggf_bench_imagenet",
                        help="fake-TFRecord cache dir for --pipeline imagenet")
    parser.add_argument("--host-pipeline", choices=("native", "tfdata"),
                        default="native",
                        help="host decode path for --pipeline imagenet: the "
                             "production default (native TFRecord index + "
                             "libjpeg) or the tf.data fallback")
    parser.add_argument("--wire", choices=("auto", "host_f32", "host_bf16",
                                           "u8"),
                        default="auto",
                        help="--pipeline imagenet ingest wire (data.wire): "
                             "'u8' ships raw uint8 pixels and finishes "
                             "normalize/cast/space-to-depth on device "
                             "(data/device_ingest.py); the emitted artifact "
                             "records the wire that ACTUALLY ran (u8 falls "
                             "back to the host wire when refused)")
    parser.add_argument("--num-files", type=int, default=8)
    parser.add_argument("--per-file", type=int, default=256)
    parser.add_argument("--raw-input", action="store_true",
                        help="device bench: feed (S, S, 3) images instead of "
                             "the space-to-depth packed layout VGG-F "
                             "defaults to")
    parser.add_argument("--update-baseline", action="store_true",
                        help="freeze this run's value into "
                             "benchmarks/baseline.json")
    parser.add_argument("--no-watchdog", action="store_true",
                        help="run the bench directly in this process (the "
                             "watchdog child mode; also for CPU test "
                             "runners)")
    parser.add_argument("--budget", type=float, default=900.0,
                        help="watchdog wall-clock budget (seconds) before "
                             "emitting a machine-readable failure record")
    parser.add_argument("--set", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="dotted config override applied to the bench "
                             "trainer (config.apply_overrides semantics), "
                             "e.g. --set data.augment.enabled=true or "
                             "--set mesh.shard_opt_state=false — the r13 "
                             "session script's augment/ZeRO-1 on-off pairs")
    args = parser.parse_args()

    if args.pipeline == "imagenet":
        args.batch_size = args.batch_size or 256
        args.steps = args.steps if args.steps is not None else 48
        args.warmup = args.warmup if args.warmup is not None else 2
        args.repeats = args.repeats if args.repeats is not None else 3
        metric = f"{args.model}_e2e_imagenet_images_per_sec_per_chip"
        bench_fn = run_pipeline_bench
    else:
        # 2048/chip measured fastest on v5e: 512 → 19.6k, 1024 → 20.0k,
        # 2048 → 20.9k, 3072 → 20.9k, 4096 → 20.2k img/s/chip (idle host).
        args.batch_size = args.batch_size or 2048
        args.steps = args.steps if args.steps is not None else 30
        args.warmup = args.warmup if args.warmup is not None else 5
        args.repeats = args.repeats if args.repeats is not None else 1
        metric = f"{args.model}_train_images_per_sec_per_chip"
        bench_fn = run_device_bench

    # Config validation must fail fast (< ~1 s), BEFORE the watchdog spawns
    # anything that queues on the single-grant tunnel — a typo'd
    # --model-extra discovered inside the child would burn the whole budget
    # first (caught driving this path with the tunnel down). Constructing
    # the Flax module validates model name AND extra KEYS; the
    # jax.eval_shape pass traces the full init abstractly — no device, no
    # backend client — so invalid VALUES that only raise inside __call__
    # (e.g. attention_layout='flashh') are caught here too (ADVICE r3).
    # Everything concrete stays INSIDE the traced lambda: a real
    # jax.random.key() out here would instantiate the (possibly wedged)
    # backend.
    try:
        import jax

        from distributed_vgg_f_tpu.config import ModelConfig
        from distributed_vgg_f_tpu.models import build_model
        model = build_model(ModelConfig(name=args.model, num_classes=1000,
                                        compute_dtype="bfloat16",
                                        extra=_parsed_model_extra(args)))
        size = args.image_size

        def _abstract_init():
            import jax.numpy as jnp
            return model.init(jax.random.key(0),
                              jnp.zeros((1, size, size, 3), jnp.float32),
                              train=False)

        jax.eval_shape(_abstract_init)
        reg_key = _registry_key(metric, args.batch_size,
                                _parsed_model_extra(args))
    except (SystemExit, KeyError, TypeError, ValueError) as e:
        _emit_failure(metric, {"error": "bad_config",
                               "detail": f"{type(e).__name__}: {e}"[:400]})
        sys.exit(1)

    # Watchdog wrapper: the driver-facing invocation (`python bench.py`) must
    # produce a result or a machine-readable failure within --budget, and
    # must never hang on a wedged TPU grant. Engaged only for script
    # invocations (`as_script=True` from the __main__ block): callers that
    # import bench and call main() directly (the CPU-forced test runners)
    # have configured the platform in-process and must run inline. NOTE:
    # "jax" in sys.modules cannot distinguish these — this machine's
    # sitecustomize imports jax in EVERY interpreter.
    if as_script and not args.no_watchdog:
        _run_with_watchdog(metric, args.budget, registry_key=reg_key)  # exits

    try:
        bench_fn(args)
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # incl. SystemExit from deep libs
        _emit_failure(metric, {"error": "bench_failed",
                               "detail": f"{type(e).__name__}: {e}"[:400]},
                      registry_key=reg_key)
        sys.exit(1)


if __name__ == "__main__":
    main(as_script=True)
