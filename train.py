"""CLI entry point — thin wrapper over distributed_vgg_f_tpu.cli (the
reference's `python train.py --flags` equivalent; SURVEY.md §1 CLI layer).
Kept at the repo root so the checkout works exactly like the reference repo;
`pip install .` exposes the same surface as the `dvggf-train` script."""

from __future__ import annotations

import sys

from distributed_vgg_f_tpu.cli import main

if __name__ == "__main__":
    main(sys.argv[1:])
