"""CLI entry point — the reference's `python train.py --flags` equivalent
(SURVEY.md §1 CLI layer).

    python train.py --config vggf_cifar10_smoke --set train.steps=100
"""

from __future__ import annotations

import sys


def main(argv=None) -> None:
    from distributed_vgg_f_tpu.config import parse_cli
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = parse_cli(argv)
    logger = MetricLogger(jsonl_path=(f"{cfg.train.checkpoint_dir}/metrics.jsonl"
                                      if cfg.train.checkpoint_dir else None),
                          tensorboard_dir=cfg.train.tensorboard_dir or None)
    trainer = Trainer(cfg, logger=logger)
    eval_ds = None
    try:
        eval_ds = trainer.make_dataset("eval")
    except Exception:
        pass
    trainer.fit(eval_dataset=eval_ds)


if __name__ == "__main__":
    main(sys.argv[1:])
