"""Loss and metric values on fixed tensors (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_vgg_f_tpu.ops.losses import l2_regularization, softmax_cross_entropy
from distributed_vgg_f_tpu.ops.metrics import topk_correct


def test_softmax_ce_matches_numpy():
    rng = np.random.default_rng(0)
    logits = rng.standard_normal((8, 5)).astype(np.float32)
    labels = rng.integers(0, 5, size=(8,))
    got = float(softmax_cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    shifted = logits - logits.max(-1, keepdims=True)
    logp = shifted - np.log(np.exp(shifted).sum(-1, keepdims=True))
    want = float(-logp[np.arange(8), labels].mean())
    assert abs(got - want) < 1e-5


def test_label_smoothing_increases_loss_on_confident_preds():
    logits = jnp.asarray([[10.0, -10.0], [10.0, -10.0]])
    labels = jnp.asarray([0, 0])
    plain = float(softmax_cross_entropy(logits, labels))
    smoothed = float(softmax_cross_entropy(logits, labels, label_smoothing=0.1))
    assert smoothed > plain


def test_l2_regularization_decays_kernels_not_biases():
    params = {
        "conv1": {"kernel": jnp.ones((3, 3, 1, 2)), "bias": jnp.ones((2,)) * 100},
        "bn": {"scale": jnp.ones((2,)) * 100, "bias": jnp.ones((2,)) * 100},
    }
    wd = 0.1
    got = float(l2_regularization(params, wd))
    want = 0.5 * wd * 18.0  # only conv kernel: 3*3*1*2 ones
    assert abs(got - want) < 1e-6
    assert float(l2_regularization(params, 0.0)) == 0.0


def test_topk_correct():
    logits = jnp.asarray([
        [0.1, 0.9, 0.0, 0.0],   # top1 = 1
        [0.5, 0.1, 0.4, 0.0],   # top1 = 0, top2 = {0,2}
        [0.0, 0.0, 0.1, 0.9],   # top1 = 3
    ])
    labels = jnp.asarray([1, 2, 0])
    assert int(topk_correct(logits, labels, 1)) == 1
    assert int(topk_correct(logits, labels, 2)) == 2
    assert int(topk_correct(logits, labels, 4)) == 3


def test_topk_under_jit():
    f = jax.jit(lambda l, y: topk_correct(l, y, 5))
    logits = jnp.eye(10) * 5.0
    labels = jnp.arange(10)
    assert int(f(logits, labels)) == 10
