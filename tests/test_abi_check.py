"""The ctypes<->C-ABI contract checker (tools/abi_check.py) — r15
correctness tooling plane.

Mutation-style acceptance: the checker must pass GREEN on the committed v9
surface and CATCH each seeded drift class in a mutated copy of the real
sources — an argtypes width mismatch, a missing export, an undeclared new
export, an ABI-version constant drift, and a stale declaration. Mutations
run against copies of the ACTUAL shipping sources, so the fixtures can
never drift from the real ABI shape.
"""

import os
import re
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import abi_check  # noqa: E402


@pytest.fixture()
def mutant_repo(tmp_path):
    """A minimal copy of the checked surface (3 .cc + 3 bindings) that
    tests mutate freely."""
    for lib_cfg in abi_check.LIBRARIES:
        for rel in (lib_cfg["src"], lib_cfg["binding"]):
            dst = tmp_path / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy2(os.path.join(REPO, rel), dst)
    return tmp_path


def _edit(root, rel, old, new, count=1):
    path = os.path.join(root, rel)
    text = open(path).read()
    assert old in text, f"mutation anchor not found in {rel}: {old!r}"
    open(path, "w").write(text.replace(old, new, count))


JPEG_BINDING = "distributed_vgg_f_tpu/data/native_jpeg.py"
JPEG_SRC = "native/jpeg_loader.cc"


def test_committed_surface_is_green():
    errors = abi_check.run(REPO)
    assert errors == [], "\n".join(errors)


def test_export_inventory_is_complete():
    """Every extern "C" symbol in the real sources is visible to the
    parser — a regex miss would silently shrink the checked surface. The
    jpeg library's v9 surface is 30+ exports; pin the exact floor so a
    parser regression can't drop exports unnoticed."""
    exports = abi_check.parse_c_exports(os.path.join(REPO, JPEG_SRC))
    assert len(exports) >= 34, sorted(exports)
    # spot-check the hairy signatures parse to the right arity
    assert len(exports["dvgg_jpeg_loader_create_ranged"]["params"]) == 20
    assert len(exports["dvgg_jpeg_decode_single"]["params"]) == 13
    assert exports["dvgg_jpeg_loader_abi_version"]["abi_literal"] == 9
    data = abi_check.parse_c_exports(
        os.path.join(REPO, "native/dataloader.cc"))
    assert set(data) == {"dvgg_loader_create", "dvgg_loader_next",
                         "dvgg_loader_destroy", "dvgg_abi_version"}
    tfr = abi_check.parse_c_exports(
        os.path.join(REPO, "native/tfrecord_index.cc"))
    assert len(tfr) == 7


def test_catches_argtypes_width_mismatch(mutant_repo):
    _edit(mutant_repo, JPEG_BINDING,
          "lib.dvgg_jpeg_loader_seek.argtypes = [ctypes.c_void_p, "
          "ctypes.c_int64]",
          "lib.dvgg_jpeg_loader_seek.argtypes = [ctypes.c_void_p, "
          "ctypes.c_int]")
    errors = abi_check.run(str(mutant_repo))
    assert any("dvgg_jpeg_loader_seek" in e and "c_int" in e
               for e in errors), errors


def test_catches_arity_mismatch(mutant_repo):
    _edit(mutant_repo, JPEG_BINDING,
          "lib.dvgg_jpeg_set_simd.argtypes = [ctypes.c_int]",
          "lib.dvgg_jpeg_set_simd.argtypes = [ctypes.c_int, ctypes.c_int]")
    errors = abi_check.run(str(mutant_repo))
    assert any("dvgg_jpeg_set_simd" in e and "arity" in e
               for e in errors), errors


def test_catches_missing_export(mutant_repo):
    """The C side drops an export the binding still declares (the v-next
    refactor hazard: cdecl would fail only at call time, deep in a run)."""
    _edit(mutant_repo, JPEG_SRC,
          """int dvgg_jpeg_loader_hflip(void* handle) {
  return handle ? static_cast<JpegLoader*>(handle)->hflip() : -1;
}""", "")
    errors = abi_check.run(str(mutant_repo))
    assert any("dvgg_jpeg_loader_hflip" in e and "stale" in e
               for e in errors), errors


def test_catches_undeclared_new_export(mutant_repo):
    """A new export lands without ctypes declarations — the exact v9->v10
    churn this tool exists for."""
    _edit(mutant_repo, JPEG_SRC, '}  // extern "C"',
          'int dvgg_jpeg_new_knob(int64_t x) { return (int)x; }\n'
          '}  // extern "C"')
    errors = abi_check.run(str(mutant_repo))
    assert any("dvgg_jpeg_new_knob" in e and "no ctypes declaration" in e
               for e in errors), errors


def test_catches_abi_version_drift(mutant_repo):
    _edit(mutant_repo, JPEG_BINDING, "JPEG_ABI_VERSION = 9",
          "JPEG_ABI_VERSION = 8")
    errors = abi_check.run(str(mutant_repo))
    assert any("ABI version drift" in e and "JPEG_ABI_VERSION" in e
               for e in errors), errors


def test_catches_literal_load_gate(mutant_repo):
    """The load gate must consume the *_ABI_VERSION constant — a frozen
    literal gate plus a bumped constant would pass every static check
    while the runtime gate mismatches and silently disables the native
    path (caller falls back to the slow pipeline)."""
    _edit(mutant_repo, "distributed_vgg_f_tpu/data/native_tfrecord.py",
          '"dvgg_tfrecord_index_abi_version",\n'
          '                               TFRECORD_ABI_VERSION)',
          '"dvgg_tfrecord_index_abi_version", 1)')
    errors = abi_check.run(str(mutant_repo))
    assert any("load gate uses a literal" in e
               and "native_tfrecord" in e for e in errors), errors


def test_catches_missing_restype(mutant_repo):
    _edit(mutant_repo, JPEG_BINDING,
          "        lib.dvgg_jpeg_choose_scale.restype = ctypes.c_int\n", "")
    errors = abi_check.run(str(mutant_repo))
    assert any("dvgg_jpeg_choose_scale" in e and "restype" in e
               for e in errors), errors


def test_catches_void_restype_drift(mutant_repo):
    _edit(mutant_repo, JPEG_BINDING,
          "lib.dvgg_jpeg_profile_reset.restype = None",
          "lib.dvgg_jpeg_profile_reset.restype = ctypes.c_int")
    errors = abi_check.run(str(mutant_repo))
    assert any("dvgg_jpeg_profile_reset" in e and "void" in e
               for e in errors), errors


def test_unknown_c_type_fails_loudly(mutant_repo):
    """A param type outside the compatibility table must be an explicit
    error, never a silent pass — widening the table is a deliberate act."""
    _edit(mutant_repo, JPEG_SRC,
          "int dvgg_jpeg_set_simd(int enable) {",
          "int dvgg_jpeg_set_simd(size_t enable) {")
    errors = abi_check.run(str(mutant_repo))
    assert any("size_t" in e and "compatibility table" in e
               for e in errors), errors


def test_cli_green_on_committed_tree():
    out = subprocess.run([sys.executable, "tools/abi_check.py"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert re.search(r"OK \(\d+ exports", out.stdout)


def test_cli_exits_nonzero_on_drift(mutant_repo):
    _edit(mutant_repo, JPEG_BINDING, "JPEG_ABI_VERSION = 9",
          "JPEG_ABI_VERSION = 7")
    out = subprocess.run(
        [sys.executable, "tools/abi_check.py", "--repo", str(mutant_repo)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "ABI version drift" in out.stderr
