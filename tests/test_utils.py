"""Throughput meter with a fake clock (SURVEY.md §4: the judged metric's
measurement code is itself tested) + metric logger JSONL round-trip."""

import io
import json

from distributed_vgg_f_tpu.utils.logging import MetricLogger
from distributed_vgg_f_tpu.utils.meter import ThroughputMeter


def test_throughput_meter_fake_clock():
    t = [0.0]
    meter = ThroughputMeter(num_chips=4, clock=lambda: t[0])
    t[0] = 2.0
    meter.update(512)
    meter.update(512)
    assert abs(meter.images_per_sec - 512.0) < 1e-9
    assert abs(meter.images_per_sec_per_chip - 128.0) < 1e-9
    assert abs(meter.steps_per_sec - 1.0) < 1e-9
    meter.reset()
    t[0] = 3.0
    meter.update(100)
    assert abs(meter.images_per_sec - 100.0) < 1e-9


def test_metric_logger_jsonl(tmp_path):
    path = str(tmp_path / "log" / "metrics.jsonl")
    stream = io.StringIO()
    logger = MetricLogger(jsonl_path=path, stream=stream)
    logger.log("train", {"step": 1, "loss": 2.5})
    logger.log("eval", {"step": 1, "eval_top1": 0.1})
    logger.close()
    lines = [json.loads(l) for l in open(path)]
    assert lines[0] == {"event": "train", "step": 1, "loss": 2.5}
    assert lines[1]["event"] == "eval"
    assert "loss=2.5" in stream.getvalue()


def test_metric_logger_tensorboard(tmp_path):
    tb_dir = str(tmp_path / "tb")
    logger = MetricLogger(stream=io.StringIO(), tensorboard_dir=tb_dir)
    logger.log("train", {"step": 3, "loss": 1.25, "note": "text-skipped"})
    logger.log("start", {"config": "x"})  # no step → no TB write, no crash
    logger.close()

    import os
    event_files = [f for f in os.listdir(tb_dir) if "tfevents" in f]
    assert event_files, "no TensorBoard event file written"
    from tensorflow.python.summary.summary_iterator import summary_iterator
    tags = {}
    for ev in summary_iterator(os.path.join(tb_dir, event_files[0])):
        for v in ev.summary.value:
            tags[v.tag] = ev.step
    assert tags.get("train/loss") == 3
