"""Throughput meter with a fake clock (SURVEY.md §4: the judged metric's
measurement code is itself tested) + metric logger JSONL round-trip."""

import io
import json
import math

import pytest

from distributed_vgg_f_tpu.utils.logging import MetricLogger
from distributed_vgg_f_tpu.utils.meter import ThroughputMeter


def test_throughput_meter_fake_clock():
    t = [0.0]
    meter = ThroughputMeter(num_chips=4, clock=lambda: t[0])
    t[0] = 2.0
    meter.update(512)
    meter.update(512)
    assert abs(meter.images_per_sec - 512.0) < 1e-9
    assert abs(meter.images_per_sec_per_chip - 128.0) < 1e-9
    assert abs(meter.steps_per_sec - 1.0) < 1e-9
    meter.reset()
    t[0] = 3.0
    meter.update(100)
    assert abs(meter.images_per_sec - 100.0) < 1e-9


def test_throughput_meter_rolling_window():
    """The rolling rate must track the RECENT cadence while the cumulative
    rate averages a stall away — the stalls are exactly what the telemetry
    layer attributes, so the meter must be able to see them."""
    t = [0.0]
    meter = ThroughputMeter(num_chips=1, clock=lambda: t[0], window=2)
    assert meter.window_images_per_sec is None          # no updates yet
    for _ in range(10):                                 # steady 100 img/s
        t[0] += 1.0
        meter.update(100)
    assert meter.window_images_per_sec == pytest.approx(100.0)
    t[0] += 10.0                                        # a 10 s stall
    meter.update(100)
    # window (last 2 updates: 200 images over 11 s) craters; cumulative
    # (1100 images over 20 s) barely moves
    assert meter.window_images_per_sec == pytest.approx(200 / 11)
    assert meter.images_per_sec == pytest.approx(1100 / 20)
    assert meter.snapshot()["window_images_per_sec"] == \
        pytest.approx(200 / 11)
    # recovery: two fast updates push the stall out of the window
    t[0] += 1.0
    meter.update(100)
    t[0] += 1.0
    meter.update(100)
    assert meter.window_images_per_sec == pytest.approx(100.0)


def test_metric_logger_jsonl(tmp_path):
    path = str(tmp_path / "log" / "metrics.jsonl")
    stream = io.StringIO()
    logger = MetricLogger(jsonl_path=path, stream=stream)
    logger.log("train", {"step": 1, "loss": 2.5})
    logger.log("eval", {"step": 1, "eval_top1": 0.1})
    logger.close()
    lines = [json.loads(l) for l in open(path)]
    # every record carries the r10 schema_version stamp (telemetry/schema.py)
    from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION
    assert lines[0] == {"event": "train", "schema_version": SCHEMA_VERSION,
                        "step": 1, "loss": 2.5}
    assert lines[1]["event"] == "eval"
    assert "loss=2.5" in stream.getvalue()
    # ...but the stamp stays off the compact stdout mirror
    assert "schema_version" not in stream.getvalue()


def test_metric_logger_nonfinite_floats_stay_json_legal(tmp_path):
    """ISSUE 4 satellite: json.dumps writes bare NaN/Infinity for non-finite
    floats — JSON-illegal, breaks strict parsers. The logger serializes
    them as null plus a `<key>_nonfinite` string (the resilience layer logs
    NaN losses on purpose, so this path is load-bearing). Nested mappings
    (stall/counters payloads) get the same treatment."""
    path = str(tmp_path / "m.jsonl")
    logger = MetricLogger(jsonl_path=path, stream=io.StringIO())
    logger.log("train", {"step": 1, "loss": float("nan"),
                         "grad_norm": float("inf"),
                         "counters": {"g": float("-inf"), "ok": 2}})
    logger.close()
    text = open(path).read()
    assert "NaN" not in text and "Infinity" not in text

    def reject(tok):
        raise AssertionError(f"bare {tok}")

    rec = json.loads(text, parse_constant=reject)   # strict parse passes
    assert rec["loss"] is None and rec["loss_nonfinite"] == "nan"
    assert rec["grad_norm"] is None and rec["grad_norm_nonfinite"] == "inf"
    assert rec["counters"]["g"] is None
    assert rec["counters"]["g_nonfinite"] == "-inf"
    assert rec["counters"]["ok"] == 2


def test_metric_logger_context_manager_crash_flush(tmp_path):
    """ISSUE 4 satellite: the JSONL file is complete after a simulated
    mid-run crash (context-manager exit flushes+closes), and close() is
    exactly-once — the TB writer must not be closed twice by the trainer
    finally path plus the caller's exit."""
    path = str(tmp_path / "m.jsonl")
    with pytest.raises(RuntimeError, match="simulated crash"):
        with MetricLogger(jsonl_path=path, stream=io.StringIO()) as logger:
            for step in range(5):
                logger.log("train", {"step": step, "loss": 0.5})
            raise RuntimeError("simulated crash")
    lines = [json.loads(l) for l in open(path)]     # every line parses
    assert [r["step"] for r in lines] == list(range(5))

    closes = {"n": 0}

    class FakeTB:
        def flush(self):
            pass

        def close(self):
            closes["n"] += 1

    logger = MetricLogger(stream=io.StringIO())
    logger._tb = FakeTB()
    logger.close()
    logger.close()                                  # idempotent
    with logger:                                    # CM exit also closes
        pass
    assert closes["n"] == 1


def test_metric_logger_tensorboard(tmp_path):
    tb_dir = str(tmp_path / "tb")
    logger = MetricLogger(stream=io.StringIO(), tensorboard_dir=tb_dir)
    logger.log("train", {"step": 3, "loss": 1.25, "note": "text-skipped"})
    logger.log("start", {"config": "x"})  # no step → no TB write, no crash
    logger.close()

    import os
    event_files = [f for f in os.listdir(tb_dir) if "tfevents" in f]
    assert event_files, "no TensorBoard event file written"
    from tensorflow.python.summary.summary_iterator import summary_iterator
    tags = {}
    for ev in summary_iterator(os.path.join(tb_dir, event_files[0])):
        for v in ev.summary.value:
            tags[v.tag] = ev.step
    assert tags.get("train/loss") == 3
