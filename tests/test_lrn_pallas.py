"""Pallas LRN kernel vs the reduce_window fp32 oracle.

Runs in the Pallas interpreter on the 8-virtual-CPU test platform (SURVEY.md §4:
all TPU-kernel logic must be testable without hardware); on a real TPU run the
same assertions hold for the compiled kernel.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_vgg_f_tpu.ops.lrn_pallas as lrn_pallas
from distributed_vgg_f_tpu.ops.lrn import (
    local_response_norm,
    local_response_norm_matmul,
    lrn,
    set_lrn_impl,
)
from distributed_vgg_f_tpu.ops.lrn_pallas import local_response_norm_pallas


@pytest.fixture(autouse=True)
def _interpret_mode():
    prev = lrn_pallas.INTERPRET
    lrn_pallas.INTERPRET = jax.default_backend() != "tpu"
    yield
    lrn_pallas.INTERPRET = prev


@pytest.mark.parametrize("shape", [(2, 6, 6, 64), (4, 3, 3, 96)])
@pytest.mark.parametrize("alpha_scaled", [False, True])
def test_pallas_forward_matches_oracle(shape, alpha_scaled):
    x = jax.random.normal(jax.random.key(0), shape, jnp.float32) * 3.0
    want = local_response_norm(x, alpha_scaled=alpha_scaled)
    got = local_response_norm_pallas(x, alpha_scaled=alpha_scaled)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_matmul_forward_matches_oracle():
    x = jax.random.normal(jax.random.key(1), (2, 5, 5, 64), jnp.float32) * 2.0
    want = local_response_norm(x)
    got = local_response_norm_matmul(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("impl_fn", [local_response_norm_pallas,
                                     local_response_norm_matmul])
def test_gradient_matches_oracle(impl_fn):
    """The custom VJP (pallas) and autodiff of the matmul form must both equal
    autodiff of the reduce_window oracle."""
    x = jax.random.normal(jax.random.key(2), (2, 4, 4, 64), jnp.float32)
    cot = jax.random.normal(jax.random.key(3), x.shape, jnp.float32)

    def loss(fn, x):
        return jnp.vdot(fn(x).astype(jnp.float32), cot)

    want = jax.grad(lambda x: loss(local_response_norm, x))(x)
    got = jax.grad(lambda x: loss(impl_fn, x))(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-6)


def test_pallas_bf16_close_to_fp32_oracle():
    x = (jax.random.normal(jax.random.key(4), (2, 4, 4, 64), jnp.float32)
         .astype(jnp.bfloat16))
    want = local_response_norm(x.astype(jnp.float32))
    got = local_response_norm_pallas(x).astype(jnp.float32)
    # bf16 storage of in/out bounds the error at ~bf16 resolution.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_pallas_partial_tile():
    """M not divisible by the kernel tile: padded rows must not corrupt output."""
    prev = lrn_pallas._TILE_BYTES
    lrn_pallas._TILE_BYTES = 8 * 4 * 128  # tile of 8 rows
    try:
        x = jax.random.normal(jax.random.key(5), (3, 1, 7, 64), jnp.float32)
        want = local_response_norm(x)
        got = local_response_norm_pallas(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)
    finally:
        lrn_pallas._TILE_BYTES = prev


def test_dispatcher_override():
    x = jax.random.normal(jax.random.key(6), (1, 2, 2, 8), jnp.float32)
    try:
        set_lrn_impl("reduce_window")
        a = lrn(x)
        set_lrn_impl("matmul")
        b = lrn(x)
    finally:
        set_lrn_impl(None)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-6)
