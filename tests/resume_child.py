"""Child process for the mid-epoch SIGKILL + position-exact-resume chaos
drill (r18; tests/test_resilience.py). Trains VGG-F on a tiny imagefolder
ImageNet layout through the REAL native u8-wire ingest stack, with the
production `sigkill@N` fault injector (resilience/faults.py) arming a real
un-catchable mid-epoch death.

Usage:
    python resume_child.py CKPT_DIR RESULT_PATH STEPS DATA_DIR MODE \
        [FAULT_SPEC] [SNAPSHOT_DIR]

MODE selects the grid cell: `local` (native u8), `warm` (native u8 +
snapshot cache rooted at SNAPSHOT_DIR), `service` (two in-process
position-keyed decode workers — they die with the SIGKILL, the restarted
incarnation spawns fresh ones; the stream is a pure function of position,
so the handoff is exact by construction).

On clean completion writes RESULT_PATH:
    {"start_step", "final_step", "fingerprint", "losses",
     "iterator_state_restored", "replayed_batches", "transplanted_items"}
"""

import hashlib
import json
import sys

from _child_bootstrap import bootstrap

jax = bootstrap(8)

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    ServiceConfig, SnapshotCacheConfig, TrainConfig)
from distributed_vgg_f_tpu.train.trainer import Trainer  # noqa: E402
from distributed_vgg_f_tpu.utils.logging import MetricLogger  # noqa: E402

N_ITEMS = 40
BATCH = 8


def main() -> None:
    ckpt_dir, result_path = sys.argv[1], sys.argv[2]
    total_steps, data_dir, mode = int(sys.argv[3]), sys.argv[4], sys.argv[5]
    fault = sys.argv[6] if len(sys.argv) > 6 else ""
    snapshot_dir = sys.argv[7] if len(sys.argv) > 7 else ""

    snapshot = SnapshotCacheConfig(enabled=(mode == "warm"),
                                   dir=snapshot_dir)
    service = ServiceConfig()
    workers = []
    data = DataConfig(name="imagenet", data_dir=data_dir, image_size=32,
                      global_batch_size=BATCH,
                      num_train_examples=N_ITEMS, wire="u8",
                      snapshot_cache=snapshot)
    cfg = ExperimentConfig(
        name="resume_chaos",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=BATCH),
        data=data,
        mesh=MeshConfig(num_data=8),
        train=TrainConfig(steps=total_steps, seed=0, log_every=1,
                          checkpoint_dir=ckpt_dir,
                          checkpoint_every_steps=3,
                          track_best_eval=False,
                          fault_injection=fault),
    )
    if mode == "service":
        # two in-process position-keyed decode workers: killed with this
        # process by design — every incarnation spawns its own fleet, and
        # the position-exact handoff is what the drill proves
        from distributed_vgg_f_tpu.data import ingest_service as isvc
        workers = [isvc.serve_from_config(cfg, worker_index=i,
                                          num_workers=2)
                   for i in range(2)]
        import dataclasses
        service = ServiceConfig(
            enabled=True,
            workers=tuple(w.endpoint for w in workers))
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, service=service))

    records = []
    logger = MetricLogger()
    orig = logger.log

    def log(event, metrics):
        records.append({"event": event, **dict(metrics)})
        return orig(event, metrics)

    logger.log = log

    trainer = Trainer(cfg, logger=logger)
    state = trainer.restore_or_init()
    start_step = int(jax.device_get(state.step))
    print(f"CHILD_START {start_step}", flush=True)
    try:
        state = trainer.fit(state)
    finally:
        for w in workers:
            w.close()
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    restore = next((r for r in records
                    if r["event"] == "iterator_state_restore"), None)
    losses = {str(r["step"]): r["loss"] for r in records
              if r["event"] == "train" and "loss" in r}
    with open(result_path, "w") as f:
        json.dump({
            "start_step": start_step,
            "final_step": int(jax.device_get(state.step)),
            "fingerprint": h.hexdigest(),
            "losses": losses,
            "iterator_state_restored": restore is not None,
            "replayed_batches": (restore or {}).get("replayed_batches"),
            "transplanted_items": (restore or {}).get("transplanted_items"),
        }, f)


if __name__ == "__main__":
    main()
