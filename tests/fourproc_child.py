"""Child for the FOUR-process distributed test (VERDICT r2 #7): N=2 leaves
edge-room that N=4 closes — a host with ZERO eval data padding from the
start, mixed exhaustion order, a decode-error allgather where most hosts
contribute 0, and stop-consensus where the SIGTERM'd host is neither first
nor last rank.

Phases (all in one child run to amortize Gloo/compile startup):
  A. 2-step synchronous DP training — params bit-identical on all 4 ranks.
  B. Exact eval with shards 21/9/0/35 (rank 2 has NO data and pads from
     batch one; ranks exhaust in mixed order) — exactly 65 scored.
  C. 2-step fit with a decode-error-reporting dataset (counts 0/3/0/5) —
     rank 0's log must show the cross-host total 8.
  C2. Ring attention (einsum AND ring × flash, interpreted kernels) over
     the 4-process mesh — K/V blocks and the flash backward's dK/dV
     accumulators transit THROUGH intermediate hosts (multi-hop), with
     forward exactness vs the oracle and all-cotangent finiteness checked.
  D. "Infinite" fit (log_every=1e6); the parent SIGTERMs RANK 2; all four
     ranks must stop at the same step with a durable forced checkpoint.

Usage: python fourproc_child.py PORT NPROC PID RESULT CKPT_DIR JSONL
"""

import io
import json
import sys

from _child_bootstrap import bootstrap

PORT, NPROC, PID, OUT, CKPT, JSONL = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    sys.argv[5], sys.argv[6])

jax = bootstrap(2, coordinator_port=PORT, num_processes=NPROC,
                process_id=PID)

import dataclasses  # noqa: E402
import hashlib  # noqa: E402

import numpy as np  # noqa: E402

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable  # noqa: E402
from distributed_vgg_f_tpu.train.trainer import Trainer  # noqa: E402
from distributed_vgg_f_tpu.utils.logging import MetricLogger  # noqa: E402

EVAL_SHARD = {0: 21, 1: 9, 2: 0, 3: 35}
DECODE_ERRS = {0: 0, 1: 3, 2: 0, 3: 5}


class ErrReportingDataset:
    """Synthetic stream that reports a fixed decode-error count — exercises
    the cross-host decode-error allgather with most ranks contributing 0."""

    def __init__(self, inner, count: int):
        self._inner = inner
        self._count = count

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._inner)

    def decode_errors(self) -> int:
        return self._count


def _fingerprint(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


def main() -> None:
    assert jax.process_count() == NPROC
    base = ExperimentConfig(
        name="fourproc",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        mesh=MeshConfig(num_data=2 * NPROC),
        train=TrainConfig(steps=2, seed=0, log_every=1),
    )
    logger = MetricLogger(jsonl_path=JSONL) if PID == 0 else \
        MetricLogger(stream=io.StringIO())

    # --- phase A: 4-rank synchronous DP
    trainer = Trainer(base, logger=logger)
    state = trainer.fit(trainer.init_state())
    fingerprint = _fingerprint(state)

    # --- phase B: exact eval, shards 21/9/0/35 (rank 2 pads from the start)
    shard_n = EVAL_SHARD[PID]
    rng = np.random.default_rng(7 + PID)
    images = rng.standard_normal((shard_n, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=(shard_n,)).astype(np.int32)

    def epoch(images=images, labels=labels, shard_n=shard_n):
        for i in range(0, shard_n, 16):
            yield {"image": images[i:i + 16], "label": labels[i:i + 16]}

    uneven = FiniteEvalIterable(epoch, 16, (32, 32, 3), np.float32)
    exact = trainer.evaluate(state, uneven)

    # --- phase C: decode-error allgather (counts 0/3/0/5 → total 8)
    err_ds = ErrReportingDataset(trainer.make_dataset("train"),
                                 DECODE_ERRS[PID])
    trainer2 = Trainer(dataclasses.replace(base, name="fourproc_err"),
                       logger=logger)
    trainer2.fit(trainer2.init_state(), dataset=err_ds)

    # --- phase C2: ring attention across FOUR processes — K/V blocks (and
    # the flash backward's dK/dV accumulators) transit THROUGH intermediate
    # hosts on their way around the ring, a multi-hop pattern the 2-process
    # test cannot produce. Shared implementation: _child_bootstrap.
    from _child_bootstrap import run_ring_phase
    ring_flags = run_ring_phase(jax, NPROC, PID, 2, seed=21, batch=1)

    # --- phase D: preemption stop-consensus, SIGTERM lands on rank 2 only
    cfg_d = dataclasses.replace(
        base, name="fourproc_preempt",
        train=TrainConfig(steps=100_000, log_every=1_000_000, seed=0,
                          checkpoint_dir=CKPT,
                          checkpoint_every_steps=1_000_000))
    trainer3 = Trainer(cfg_d, logger=logger)
    orig_step = trainer3.train_step
    touched = {"done": False}

    def stepping(state, batch, rng):
        out_state, metrics = orig_step(state, batch, rng)
        if not touched["done"]:
            # sync THIS rank's first step to completion before touching the
            # sentinel: train_step returns at dispatch time, and the parent
            # must not SIGTERM until every rank is inside the loop with the
            # SIGTERM handler installed (a signal before that kills the rank
            # via the default action and crashes the whole job)
            jax.device_get(metrics["loss"])
            open(OUT + ".stepped", "a").close()
            touched["done"] = True
        return out_state, metrics

    trainer3.train_step = stepping
    state_d = trainer3.fit()

    with open(OUT, "w") as f:
        json.dump({"pid": PID,
                   "step": int(jax.device_get(state.step)),
                   "fingerprint": fingerprint,
                   "exact_eval_examples": int(exact["eval_examples"]),
                   **ring_flags,
                   "preempt_step": int(jax.device_get(state_d.step)),
                   "latest_ckpt": trainer3.checkpoints.latest_step()}, f)


if __name__ == "__main__":
    main()
