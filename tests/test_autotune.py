"""Closed-loop ingest autotuner suite (r11, data/autotune.py): controller
dynamics under a fake clock (hysteresis, cooldown, rails, oscillation
guard), the runtime knob surfaces (native pool resize, host/device prefetch
depths), the three receipt trails (registry counters, trainer JSONL
`autotune` block, /autotunez + flight black box), the DVGGF_AUTOTUNE=0
kill-switch's controller-absent equivalence, the regression sentinel's
settled-state refusal, and the pins-stay-bench-artifacts invariant (no
runtime module reads HOST_DECODE_RATE_R*)."""

import io
import json
import os
import re
import urllib.request

import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import AutotuneConfig
from distributed_vgg_f_tpu.data import autotune as at
from distributed_vgg_f_tpu.telemetry import schema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INFEED = {"verdict": "infeed_bound"}
COMPUTE = {"verdict": "compute_bound"}
CKPT = {"verdict": "checkpoint_bound"}
GUARD = {"verdict": "guard_stalled"}


class FakeKnobTarget:
    """A settable integer with a refusal switch — the unit the controller
    actuates in these tests."""

    def __init__(self, value=1, refuse=False):
        self.value = value
        self.refuse = refuse
        self.calls = []

    def get(self):
        return self.value

    def apply(self, n):
        self.calls.append(n)
        if self.refuse:
            return None
        self.value = n
        return n


def _cfg(**kw):
    base = dict(enabled=True, k_windows=2, cooldown_windows=1,
                settled_after_windows=3)
    base.update(kw)
    return AutotuneConfig(**base)


def _tuner(cfg, targets):
    knobs = [at.Knob(name, t.get, t.apply, lo, hi, geometric=geo)
             for name, t, lo, hi, geo in targets]
    clock = {"t": 0.0}

    def fake_clock():
        clock["t"] += 1.0
        return clock["t"]

    reg = telemetry.TelemetryRegistry()

    class _NullFlight:
        def record_actuation(self, act):
            pass

    return at.IngestAutotuner(cfg, knobs, registry=reg,
                              flight=_NullFlight(), clock=fake_clock), reg


# ------------------------------------------------------------- dynamics
def test_no_actuation_below_k_verdicts():
    t = FakeKnobTarget(1)
    tuner, reg = _tuner(_cfg(k_windows=3),
                        [("host_prefetch", t, 1, 8, False)])
    for i in range(2):
        rec = tuner.observe(INFEED)
        assert "actuations" not in rec and rec["blocked"] == "hysteresis"
    assert t.value == 1
    rec = tuner.observe(INFEED)  # third consecutive verdict actuates
    assert rec["actuations"][0] == {
        "window": 3, "knob": "host_prefetch", "from": 1, "to": 2,
        "direction": "up", "verdict": "infeed_bound",
        "ts_unix": rec["actuations"][0]["ts_unix"]}
    assert reg.counter_value("autotune/blocked_hysteresis") == 2
    assert reg.counter_value("autotune/actuations") == 1


def test_streak_resets_on_verdict_change():
    t = FakeKnobTarget(1)
    tuner, _ = _tuner(_cfg(k_windows=2), [("host_prefetch", t, 1, 8, False)])
    tuner.observe(INFEED)
    tuner.observe(COMPUTE)   # breaks the streak
    tuner.observe(INFEED)    # streak restarts at 1
    assert t.value == 1


def test_cooldown_blocks_after_actuation():
    t = FakeKnobTarget(1)
    tuner, reg = _tuner(_cfg(k_windows=1, cooldown_windows=3),
                        [("host_prefetch", t, 1, 8, False)])
    assert tuner.observe(INFEED)["actuations"]        # k=1: immediate
    for _ in range(3):
        rec = tuner.observe(INFEED)
        assert rec.get("blocked") == "cooldown"
    assert tuner.observe(INFEED)["actuations"]        # cooldown expired
    assert reg.counter_value("autotune/blocked_cooldown") == 3
    assert t.value == 3


def test_rail_clamping_and_bounded_actuation_count():
    """An infeed-bound synthetic workload must stop actuating within a
    bounded window count: the rails bound the actuation count, and every
    later window reports blocked: rail — never a value past the rail."""
    t = FakeKnobTarget(1)
    tuner, reg = _tuner(_cfg(k_windows=1, cooldown_windows=0),
                        [("native_threads", t, 1, 8, True)])
    for _ in range(20):
        tuner.observe(INFEED)
    assert t.value == 8                        # 1->2->4->8, clamped
    assert tuner.actuations_total == 3         # bounded by the rails
    assert reg.counter_value("autotune/blocked_rail") > 0
    assert all(n <= 8 for n in t.calls)
    assert tuner.settled                       # quiet since the last move


def test_compute_bound_produces_zero_actuations():
    t = FakeKnobTarget(2)
    tuner, _ = _tuner(_cfg(k_windows=1, cooldown_windows=0),
                      [("host_prefetch", t, 1, 8, False)])
    for stall in (COMPUTE, COMPUTE, CKPT, GUARD, COMPUTE, None):
        tuner.observe(stall)
    assert tuner.actuations_total == 0
    assert t.value == 2
    assert tuner.settled


def test_alternating_verdicts_converge_to_noop():
    """The oscillation acceptance case: synthetic alternating verdicts must
    converge to no-op, not thrash — hysteresis never accumulates K
    same-direction windows under alternation."""
    t = FakeKnobTarget(1)
    tuner, _ = _tuner(_cfg(k_windows=2, cooldown_windows=0,
                           relax_after_windows=2),
                      [("host_prefetch", t, 1, 8, False)])
    for i in range(30):
        tuner.observe(INFEED if i % 2 == 0 else COMPUTE)
    assert tuner.actuations_total == 0
    assert t.value == 1


def test_oscillation_guard_freezes_flipping_knob():
    """With relax enabled and verdicts swinging slowly enough to pass
    hysteresis both ways, the direction-flip counter must freeze the knob
    instead of letting it thrash forever."""
    t = FakeKnobTarget(2)
    tuner, reg = _tuner(_cfg(k_windows=1, cooldown_windows=0,
                             relax_after_windows=1, freeze_after_flips=2),
                        [("host_prefetch", t, 1, 8, False)])
    phase = [INFEED, COMPUTE]
    for i in range(40):
        tuner.observe(phase[(i // 1) % 2])
    knob = tuner.knobs[0]
    assert knob.frozen
    assert reg.counter_value("autotune/oscillation_freezes") == 1
    frozen_at = t.value
    for _ in range(6):
        tuner.observe(INFEED)
    assert t.value == frozen_at   # frozen knobs never move again


def test_relax_steps_back_down_to_baseline_only():
    t = FakeKnobTarget(2)
    tuner, _ = _tuner(_cfg(k_windows=1, cooldown_windows=0,
                           relax_after_windows=2, freeze_after_flips=99),
                      [("host_prefetch", t, 1, 8, False)])
    tuner.observe(INFEED)
    tuner.observe(INFEED)
    raised = t.value
    assert raised == 4            # 2 -> 3 -> 4
    for _ in range(20):
        tuner.observe(COMPUTE)
    assert t.value == 2           # back to baseline, NEVER below
    for _ in range(10):
        tuner.observe(COMPUTE)
    assert t.value == 2


def test_relax_geometric_never_overshoots_baseline():
    """A geometric knob relaxing from a railed value must land ON the
    baseline, not halve past it (8 // 2 = 4 below a baseline of 5 would
    leave the pipeline slower than its hand-pinned start)."""
    t = FakeKnobTarget(5)
    tuner, _ = _tuner(_cfg(k_windows=1, cooldown_windows=0,
                           relax_after_windows=1, freeze_after_flips=99),
                      [("native_threads", t, 1, 8, True)])
    tuner.observe(INFEED)          # 5 -> 8 (10 clamped to the rail)
    assert t.value == 8
    for _ in range(6):
        tuner.observe(COMPUTE)
    assert t.value == 5            # 8//2=4 clamped UP to the baseline


def test_rails_validator_rejects_zero_prefetch_rails():
    with pytest.raises(ValueError, match="min_prefetch"):
        AutotuneConfig(enabled=True, max_prefetch=0)
    with pytest.raises(ValueError, match="min_prefetch_to_device"):
        AutotuneConfig(enabled=True, max_prefetch_to_device=0)
    AutotuneConfig(enabled=True, max_threads=0)   # 0=auto: threads only


def test_escalation_order_and_refused_knob_skipped():
    first = FakeKnobTarget(1, refuse=True)   # refuses every apply
    second = FakeKnobTarget(1)
    tuner, _ = _tuner(_cfg(k_windows=1, cooldown_windows=0),
                      [("native_threads", first, 1, 8, False),
                       ("host_prefetch", second, 1, 8, False)])
    rec = tuner.observe(INFEED)
    # the refused knob is marked unavailable and the NEXT knob actuates in
    # the same window — an actuation that silently did nothing would let
    # the controller believe it fixed the stall
    assert rec["actuations"][0]["knob"] == "host_prefetch"
    assert not tuner.knobs[0].available
    assert second.value == 2


def test_settled_flag_timing():
    t = FakeKnobTarget(1)
    tuner, reg = _tuner(_cfg(k_windows=1, cooldown_windows=0,
                             settled_after_windows=3),
                        [("host_prefetch", t, 1, 2, False)])
    assert tuner.observe(INFEED)["actuations"]      # window 1: actuate
    assert not tuner.observe(COMPUTE)["settled"]    # 1 quiet window
    assert not tuner.observe(COMPUTE)["settled"]    # 2
    assert tuner.observe(COMPUTE)["settled"]        # 3 -> settled
    assert reg.gauge("autotune/settled") == 1


# ------------------------------------------------------------- receipts
def test_observe_record_and_describe_schema_validate():
    t = FakeKnobTarget(1)
    tuner, _ = _tuner(_cfg(k_windows=1, cooldown_windows=0),
                      [("host_prefetch", t, 1, 4, False)])
    for stall in (INFEED, INFEED, COMPUTE):
        rec = tuner.observe(stall)
        errors = []
        schema.validate_autotune_block(rec, "record", errors)
        assert not errors, errors
    errors = []
    schema.validate_autotune_receipt(tuner.describe(), "artifact", errors)
    assert not errors, errors
    # the whole thing must survive strict JSON (no NaN, no numpy types)
    json.loads(json.dumps(tuner.describe(), allow_nan=False))


def test_flight_recorder_carries_actuations():
    from distributed_vgg_f_tpu.telemetry.flight import FlightRecorder
    fr = FlightRecorder(max_windows=8)
    t = FakeKnobTarget(1)
    cfg = _cfg(k_windows=1, cooldown_windows=0)
    reg = telemetry.TelemetryRegistry()
    tuner = at.IngestAutotuner(
        cfg, [at.Knob("host_prefetch", t.get, t.apply, 1, 4)],
        registry=reg, flight=fr)
    tuner.observe(INFEED)
    tuner.observe(INFEED)
    box = fr.build_black_box(process=0, config_fingerprint="sha256:x",
                             config_name="t", versions={})
    assert len(box["autotune_actuations"]) == 2
    assert box["autotune_actuations"][0]["knob"] == "host_prefetch"
    assert schema.validate_flight_record(box) == []
    fr.clear()
    assert fr.actuations() == []


def test_autotunez_endpoint_serves_registered_controller():
    from distributed_vgg_f_tpu.telemetry.exporter import (
        TelemetryExporter, set_autotune_source)
    t = FakeKnobTarget(1)
    tuner, _ = _tuner(_cfg(k_windows=1, cooldown_windows=0),
                      [("host_prefetch", t, 1, 4, False)])
    tuner.observe(INFEED)
    exp = TelemetryExporter()
    port = exp.start()
    try:
        set_autotune_source(tuner.describe)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/autotunez", timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] is True
        assert payload["actuations_total"] == 1
        assert payload["knobs"][0]["name"] == "host_prefetch"
        set_autotune_source(None)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/autotunez", timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] is False
    finally:
        set_autotune_source(None)
        exp.stop()


def test_registry_counters_and_gauges_registered():
    t = FakeKnobTarget(3)
    tuner, reg = _tuner(_cfg(), [("native_threads", t, 1, 8, False)])
    snap = reg.snapshot()
    for name in ("autotune/windows", "autotune/actuations",
                 "autotune/blocked_hysteresis", "autotune/blocked_cooldown",
                 "autotune/blocked_rail", "autotune/oscillation_freezes"):
        assert name in snap, name
    assert snap["autotune/native_threads"] == 3    # bound knob: real value
    assert snap["autotune/host_prefetch"] == -1    # unbound: -1 sentinel


# ---------------------------------------------------------- kill-switch
def test_env_kill_switch_predicate(monkeypatch):
    cfg = _cfg()
    assert at.autotune_active(cfg)
    monkeypatch.setenv("DVGGF_AUTOTUNE", "0")
    assert not at.autotune_active(cfg)
    monkeypatch.delenv("DVGGF_AUTOTUNE")
    assert not at.autotune_active(AutotuneConfig(enabled=False))


# ------------------------------------------------------- knob surfaces
def test_device_prefetch_ring_resize(devices8):
    from distributed_vgg_f_tpu.data.prefetch import DevicePrefetchIterator
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
    mesh = build_mesh(MeshSpec(("data",), (8,)), devices=devices8)
    src = SyntheticDataset(batch_size=16, image_size=8, num_classes=10,
                           seed=0)
    pre = DevicePrefetchIterator(src, mesh, buffer_size=1)
    try:
        assert pre.buffer_size == 1
        assert pre.set_buffer_size(3) == 3
        for _ in range(4):
            next(pre)
        assert pre.set_buffer_size(1) == 1      # shrink never drops batches
        for _ in range(4):
            next(pre)
        knob = at.device_ring_knob(pre, max_value=4)
        assert knob is not None and knob.get() == 1
        assert knob.apply(2) == 2 and pre.buffer_size == 2
    finally:
        pre.close()


def test_host_prefetch_iterator_order_resize_and_refusal():
    from distributed_vgg_f_tpu.data.prefetch import HostPrefetchIterator

    def src(n=16):
        for i in range(n):
            yield {"image": np.full((2, 4, 4, 3), i, np.float32),
                   "label": np.full((2,), i, np.int32)}

    hp = HostPrefetchIterator(src(), depth=1)
    seen = []
    for i, b in enumerate(hp):
        seen.append(int(b["label"][0]))
        if i == 3:
            assert hp.set_depth(4) == 4
    assert seen == list(range(16))     # order preserved across the resize

    class _Ring:
        reuses_output_buffers = True

        def __iter__(self):
            return self

    with pytest.raises(ValueError, match="caller-owned"):
        HostPrefetchIterator(_Ring())

    def broken():
        yield {"x": 1}
        raise RuntimeError("boom")

    hp2 = HostPrefetchIterator(broken())
    next(hp2)
    with pytest.raises(RuntimeError, match="boom"):
        next(hp2)


def test_fanout_knob_unbound_at_default_rail():
    assert at.fanout_knob(max_value=1) is None


def test_wire_knob_actuates_through_hook():
    state = {"u8": 0}
    knob = at.wire_knob(lambda: state["u8"],
                        lambda v: state.__setitem__("u8", v) or v)
    tuner = at.IngestAutotuner(_cfg(k_windows=1, cooldown_windows=0),
                               [knob],
                               registry=telemetry.TelemetryRegistry())
    rec = tuner.observe(INFEED)
    assert rec["actuations"][0]["knob"] == "wire_u8"
    assert state["u8"] == 1
    # at the u8 rail there is nowhere further up
    assert tuner.observe(INFEED).get("blocked") == "rail"


# --------------------------------------------------- native pool resize
def _native_or_skip():
    from distributed_vgg_f_tpu.data import native_jpeg
    if native_jpeg.load_native_jpeg() is None:
        pytest.skip("native jpeg loader unavailable")
    return native_jpeg


def _jpeg_files(tmp_path, n=24):
    from PIL import Image
    rng = np.random.default_rng(0)
    files, labels = [], []
    for i in range(n):
        p = tmp_path / f"{i}.jpg"
        Image.fromarray(rng.integers(0, 256, size=(64, 64, 3))
                        .astype(np.uint8)).save(str(p), "JPEG", quality=90)
        files.append(str(p))
        labels.append(i % 4)
    return files, labels


def test_native_pool_resize_stream_byte_identical(tmp_path):
    """The determinism contract survives live grow/shrink: the stream is a
    pure function of (seed, batch index) at ANY worker count, so resizing
    mid-stream must change nothing but wall-clock."""
    nj = _native_or_skip()
    if not nj.thread_resize_enabled():
        pytest.skip("thread resize compiled out or kill-switched")
    files, labels = _jpeg_files(tmp_path)
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)

    def stream(threads, plan=None, n=9):
        it = nj.NativeJpegTrainIterator(files, labels, batch=8,
                                        image_size=48, seed=11, mean=mean,
                                        std=std, num_threads=threads)
        out = []
        try:
            for b in range(n):
                if plan and b in plan:
                    assert it.set_num_threads(plan[b]) == plan[b]
                batch = next(it)
                out.append((batch["image"].copy(), batch["label"].copy()))
        finally:
            it.close()
        return out

    ref = stream(3)
    got = stream(1, plan={2: 4, 4: 8, 6: 2})
    for (ri, rl), (gi, gl) in zip(ref, got):
        np.testing.assert_array_equal(ri, gi)
        np.testing.assert_array_equal(rl, gl)


def test_native_thread_knob_and_kill_switch(tmp_path):
    nj = _native_or_skip()
    if not nj.thread_resize_supported():
        pytest.skip("thread resize compiled out")
    files, labels = _jpeg_files(tmp_path, n=8)
    it = nj.NativeJpegTrainIterator(files, labels, batch=4, image_size=32,
                                    seed=0, mean=np.zeros(3, np.float32),
                                    std=np.ones(3, np.float32),
                                    num_threads=2)
    try:
        nj.set_thread_resize(True)
        knob = at.thread_knob(it, max_value=4)
        assert knob is not None
        assert knob.get() == 2
        assert knob.apply(4) == 4 and it.num_threads() == 4
        # runtime kill-switch: the knob factory refuses to bind, and a live
        # set returns None (never a silent no-op "success")
        nj.set_thread_resize(False)
        assert it.set_num_threads(2) is None
        assert at.thread_knob(it, max_value=4) is None
    finally:
        nj.set_thread_resize(True)
        it.close()


# ------------------------------------------------- trainer integration
def _tiny_autotune_cfg(**overrides):
    from distributed_vgg_f_tpu import config as C
    cfg = C.get_config("vggf_synthetic")
    base = {
        "data.global_batch_size": 8, "data.image_size": 32,
        "model.num_classes": 10, "train.steps": 4, "train.log_every": 2,
        "data.autotune.enabled": True,
        "data.autotune.k_windows": 1,
        "data.autotune.cooldown_windows": 0,
        "data.autotune.settled_after_windows": 1,
    }
    base.update(overrides)
    return C.apply_overrides(cfg, base)


def test_trainer_emits_schema_valid_autotune_blocks(tmp_path, devices8):
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    path = str(tmp_path / "log.jsonl")
    with MetricLogger(jsonl_path=path, stream=io.StringIO()) as logger:
        Trainer(_tiny_autotune_cfg(), logger=logger).fit()
    recs = [json.loads(l) for l in open(path)]
    blocks = [r["autotune"] for r in recs
              if r.get("event") == "train" and "autotune" in r]
    assert blocks, "no autotune blocks in the train JSONL"
    # bound knobs on the synthetic pipeline: the two prefetch depths (no
    # native loader, no restart path)
    assert set(blocks[0]["knobs"]) == {"host_prefetch",
                                      "prefetch_to_device"}
    assert any(r.get("event") == "autotune_armed" for r in recs)
    assert schema.validate_metrics_jsonl(path) == []
    # post-fit: /autotunez serves a plain-data FINAL snapshot (live=false)
    # — readable after the run, but never a later run's live state and
    # never a pin on the closed pipeline object graph
    from distributed_vgg_f_tpu.telemetry import exporter
    payload = exporter.autotune_payload()
    assert payload["enabled"] is True and payload["live"] is False


def test_trainer_kill_switch_is_controller_absent(tmp_path, devices8,
                                                  monkeypatch):
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    monkeypatch.setenv("DVGGF_AUTOTUNE", "0")
    path = str(tmp_path / "log.jsonl")
    with MetricLogger(jsonl_path=path, stream=io.StringIO()) as logger:
        trainer = Trainer(_tiny_autotune_cfg(), logger=logger)
        trainer.fit()
    assert trainer.autotuner is None
    recs = [json.loads(l) for l in open(path)]
    assert not any("autotune" in r for r in recs
                   if r.get("event") == "train")
    assert not any(r.get("event") == "autotune_armed" for r in recs)
    from distributed_vgg_f_tpu.telemetry import exporter
    assert exporter.autotune_payload()["enabled"] is False


# ------------------------------------------------------------- sentinel
def _settled_artifact(settled: bool) -> dict:
    return {
        "schema_version": schema.SCHEMA_VERSION,
        "metric": "host_native_decode_images_per_sec_per_core",
        "value": 1200.0,
        "autotune": {"enabled": True, "settled": settled,
                     "actuations_total": 5},
        "layouts": [{"layout": "tfrecord", "mode": "decode_bench",
                     "images_per_sec_per_core": 1200.0, "wire": "u8",
                     "space_to_depth": True, "restart_kind": "restart",
                     "source": {"source_hw": [320, 256],
                                "source_kind": "noise",
                                "restart_interval": 1}}],
    }


def test_sentinel_refuses_unsettled_autotune_artifact():
    from distributed_vgg_f_tpu.telemetry import regress
    errors, report = regress.check_artifact(_settled_artifact(False), REPO)
    assert any("REFUSED" in e and "mid-convergence" in e for e in errors)
    # a settled artifact proceeds to normal basis matching/gating instead
    errors2, report2 = regress.check_artifact(_settled_artifact(True), REPO)
    assert not any("REFUSED" in e for e in errors2)
    assert report2.get("autotune", {}).get("settled") is True


def test_autotune_receipt_schema_gate():
    bad = _settled_artifact(True)
    del bad["autotune"]["settled"]
    errs = schema.validate_bench_artifact(bad)
    assert any("settled" in e for e in errs)


# -------------------------------------- pins stay bench artifacts only
def test_no_runtime_code_path_reads_decode_rate_pins():
    """r11 acceptance: HOST_DECODE_RATE_R* are bench artifacts, never
    runtime inputs. The pins may live in utils/scaling_model.py (the
    provisioning model) and be read by telemetry/regress.py (the sentinel
    over committed receipts) — every RUNTIME subsystem (data, train,
    parallel, resilience, checkpoint, models, ops, cli.py, config.py)
    must neither name them nor import the scaling model.

    Since r15 the scan lives in the unified invariant linter as the
    `scaling-model-isolation` rule (tools/lint/rules.py) — this test keeps
    the original tier-1 coverage through the framework; the rule's
    catch-a-seeded-violation proof is tests/test_lint.py."""
    import sys
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.lint import RepoContext, get_rule
    violations = get_rule("scaling-model-isolation").check(RepoContext(REPO))
    assert violations == [], "\n".join(
        f"{v}" for v in violations) + (
        " — provisioning constants are receipts, not config inputs "
        "(the autotuner is the runtime mechanism)")
