"""Fleet observability plane (r22): the central collector (discovery,
quorum verdicts, degradation to `stale`, aggregated /metrics + /fleetz),
cross-process trace stitching (client get → owning worker decode, serving
request → engine flush), per-window critical-path attribution, and the
HELP/TYPE exposition contract."""

import io
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import apply_overrides, get_config
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.data.ingest_service import (
    IngestWorker, SequentialReplayProducer)
from distributed_vgg_f_tpu.data.service_client import ServiceIngestClient
from distributed_vgg_f_tpu.telemetry import collector as collector_mod
from distributed_vgg_f_tpu.telemetry import exporter as exporter_mod
from distributed_vgg_f_tpu.telemetry import flight as flight_mod
from distributed_vgg_f_tpu.telemetry import schema
from distributed_vgg_f_tpu.telemetry import stall as stall_mod
from distributed_vgg_f_tpu.telemetry import stitch as stitch_mod
from distributed_vgg_f_tpu.telemetry.collector import (
    FleetCollector, discover_sidecar_endpoints, fleet_verdict,
    parse_static_endpoint)
from distributed_vgg_f_tpu.telemetry.exporter import (
    TelemetryExporter, prometheus_name)
from distributed_vgg_f_tpu.telemetry.flight import FlightRecorder
from distributed_vgg_f_tpu.telemetry.metric_help import help_for
from distributed_vgg_f_tpu.telemetry.registry import TelemetryRegistry
from distributed_vgg_f_tpu.telemetry.spans import SpanRecorder


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    flight_mod.get_flight().clear()
    telemetry.configure(enabled=True)
    yield
    exporter_mod.stop_exporter()
    telemetry.reset()
    flight_mod.get_flight().clear()
    telemetry.configure(enabled=True)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.read()


def _synthetic_cfg(**over):
    cfg = get_config("vggf_synthetic")
    return apply_overrides(cfg, {
        "data.global_batch_size": 8, "data.image_size": 32, **over})


def _factory(data_cfg, seed=3):
    return lambda: build_dataset(data_cfg, "train", seed=seed,
                                 num_classes=1000)


def _replay_workers(data_cfg, n, seed=3, recorders=None):
    return [IngestWorker(SequentialReplayProducer(_factory(data_cfg, seed)),
                         worker_index=i, num_workers=n,
                         receipt={"seed": seed, "shard_index": 0,
                                  "num_shards": 1},
                         recorder=None if recorders is None
                         else recorders[i])
            for i in range(n)]


def _mk_process(role, *, infeed_s=0.0, ckpt_s=0.0, step=5):
    """One simulated fleet member: private registry/recorder/flight with a
    real classify() verdict in the flight ring, served by an exporter."""
    reg = TelemetryRegistry()
    rec = SpanRecorder()
    fl = FlightRecorder()
    verdict = stall_mod.classify(1.0, infeed_wait_s=infeed_s,
                                 checkpoint_wait_s=ckpt_s)
    fl.record_window(step=step, wall_s=1.0, stall=verdict,
                     counters={"prefetch/batches": 4},
                     spans={"infeed": infeed_s})
    reg.inc("prefetch/batches", 4)
    exp = TelemetryExporter(registry=reg, recorder=rec, flight=fl,
                            role=role)
    exp.start()
    exp.heartbeat(step)
    return exp


# ------------------------------------------------------- endpoint parsing

def test_parse_static_endpoint_formats():
    ep = parse_static_endpoint("127.0.0.1:9100", default_ident=4)
    assert (ep.role, ep.ident, ep.host, ep.port) == \
        ("proc", 4, "127.0.0.1", 9100)
    ep = parse_static_endpoint("trainer@10.0.0.2:9100")
    assert (ep.role, ep.ident, ep.port) == ("trainer", 0, 9100)
    ep = parse_static_endpoint("worker[3]@127.0.0.1:9101")
    assert (ep.role, ep.ident) == ("worker", 3)
    assert ep.key == ("worker", 3)
    assert ep.address == "127.0.0.1:9101"
    for garbage in ("nonsense", "worker@nohost", "a@b:notaport", ""):
        with pytest.raises(ValueError):
            parse_static_endpoint(garbage)


# ------------------------------------------------------------ quorum rule

def test_fleet_verdict_quorum_names_stragglers():
    v = fleet_verdict({("trainer", 0): "compute_bound",
                       ("worker", 1): "compute_bound",
                       ("worker", 2): "infeed_bound"})
    assert v["verdict"] == "compute_bound"
    assert (v["quorum"], v["of"]) == (2, 3)
    assert v["stragglers"] == {"worker[2]": "infeed_bound"}
    assert "worker[2]" in v["detail"] and "2/3" in v["detail"]


def test_fleet_verdict_tie_breaks_by_severity_and_empty_fleet():
    # 1-1 tie: the SEVERER verdict (VERDICTS order) wins the fleet label
    v = fleet_verdict({("a", 0): "compute_bound",
                       ("b", 0): "checkpoint_bound"})
    assert v["verdict"] == "checkpoint_bound"
    assert v["stragglers"] == {"a[0]": "compute_bound"}
    empty = fleet_verdict({})
    assert empty["verdict"] is None and empty["of"] == 0
    assert empty["detail"] == "no live processes"


# --------------------------------------------- live fleet, quorum verdict

def test_collector_quorum_over_live_exporters(tmp_path):
    """The acceptance shape: three live processes with 2-vs-1 verdicts →
    the fleet verdict is the majority with the minority NAMED, the fleet
    JSONL validates, and the aggregated /metrics carries {role,ident}
    labels plus per-process up rows."""
    exps = [_mk_process("trainer"),
            _mk_process("worker", step=7),
            _mk_process("worker", infeed_s=0.9, step=3)]
    log = str(tmp_path / "fleet.jsonl")
    col = FleetCollector(
        endpoints=[f"trainer[0]@127.0.0.1:{exps[0].port}",
                   f"worker[1]@127.0.0.1:{exps[1].port}",
                   f"worker[2]@127.0.0.1:{exps[2].port}"],
        interval_s=0.05, fleet_log=log)
    try:
        record = col.collect_once()
        assert record["fleet"]["verdict"] == "compute_bound"
        assert (record["fleet"]["quorum"], record["fleet"]["of"]) == (2, 3)
        assert record["fleet"]["stragglers"] == \
            {"worker[2]": "infeed_bound"}
        statuses = {(p["role"], p["ident"]): p["status"]
                    for p in record["processes"]}
        assert statuses == {("trainer", 0): "live", ("worker", 1): "live",
                            ("worker", 2): "live"}
        steps = {(p["role"], p["ident"]): p["last_step"]
                 for p in record["processes"]}
        assert steps[("worker", 1)] == 7
        assert schema.validate_fleet_record(record) == []
        assert schema.validate_fleet_jsonl(log) == []

        # the served surfaces agree with the returned record
        port = col.start()
        payload = json.loads(_get(port, "/fleetz")[1])
        assert payload["fleet"]["verdict"] == "compute_bound"
        assert payload["cycles"] >= 1
        text = _get(port, "/metrics")[1].decode()
        for role, ident in (("trainer", 0), ("worker", 1), ("worker", 2)):
            assert (f'dvggf_fleet_process_up{{role="{role}",'
                    f'ident="{ident}"}} 1') in text
        # per-process samples re-emitted under {role,ident} labels
        assert ('dvggf_prefetch_batches{role="worker",ident="2"}'
                in text)
        # 404 contract for unknown paths, collector stays up
        with pytest.raises(urllib.error.HTTPError):
            _get(port, "/nope")
        assert json.loads(_get(port, "/healthz")[1])["status"] == "ok"
    finally:
        col.close()
        for e in exps:
            e.stop()


def _help_type_families(text):
    helped = {line.split()[2] for line in text.splitlines()
              if line.startswith("# HELP ")}
    typed = {line.split()[2] for line in text.splitlines()
             if line.startswith("# TYPE ")}
    sampled = {line.split("{")[0].split()[0]
               for line in text.splitlines() if line and line[0] != "#"}
    return helped, typed, sampled


def test_prometheus_help_and_type_cover_every_family():
    """Satellite (a): no family is exposed without # HELP and # TYPE —
    on the per-process exporter AND on the collector aggregate."""
    exp = _mk_process("trainer")
    col = FleetCollector(
        endpoints=[f"trainer[0]@127.0.0.1:{exp.port}"], interval_s=0.05)
    try:
        col.collect_once()
        for text in (_get(exp.port, "/metrics")[1].decode(),
                     col.render_fleet_metrics()):
            helped, typed, sampled = _help_type_families(text)
            assert sampled, text
            assert sampled <= helped, sampled - helped
            assert sampled <= typed, sampled - typed
        # the shared help table is the source: a known family's HELP line
        # carries its registered text, not a placeholder
        fleet_text = col.render_fleet_metrics()
        assert (f"# HELP {prometheus_name('collector/scrapes')} "
                f"{help_for('collector/scrapes')}") in fleet_text
    finally:
        col.close()
        exp.stop()


# ------------------------------------------------------------- degradation

def test_collector_degrades_never_crashes(tmp_path):
    """Satellite (c): a dead endpoint, a hanging endpoint and a garbage
    endpoint each degrade to a `stale` entry + collector/scrape_errors;
    the fleet verdict comes from the survivors."""
    live = _mk_process("trainer")

    # dead: bind a port, then close it — connection refused
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    # hanging: accepts the connection and never answers
    hang = socket.socket()
    hang.bind(("127.0.0.1", 0))
    hang.listen(1)
    hang_port = hang.getsockname()[1]

    # garbage: answers with bytes that are neither HTTP nor JSON
    garb = socket.socket()
    garb.bind(("127.0.0.1", 0))
    garb.listen(1)
    garb_port = garb.getsockname()[1]

    def _serve_garbage():
        try:
            conn, _ = garb.accept()
            conn.recv(1024)
            conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 9\r\n"
                         b"\r\nnot json!")
            conn.close()
        except OSError:
            pass

    threading.Thread(target=_serve_garbage, daemon=True).start()

    log = str(tmp_path / "fleet.jsonl")
    col = FleetCollector(
        endpoints=[f"trainer[0]@127.0.0.1:{live.port}",
                   f"dead[1]@127.0.0.1:{dead_port}",
                   f"hang[2]@127.0.0.1:{hang_port}",
                   f"garbage[3]@127.0.0.1:{garb_port}"],
        interval_s=0.05, scrape_timeout_s=0.3, fleet_log=log)
    try:
        record = col.collect_once()
        statuses = {(p["role"], p["ident"]): p["status"]
                    for p in record["processes"]}
        assert statuses[("trainer", 0)] == "live"
        for key in (("dead", 1), ("hang", 2), ("garbage", 3)):
            assert statuses[key] == "stale", key
        # verdict is computed over the survivors only
        assert record["fleet"]["verdict"] == "compute_bound"
        assert (record["fleet"]["quorum"], record["fleet"]["of"]) == (1, 1)
        assert col.registry.counter_value(
            "collector/scrape_errors", 0) >= 3
        assert schema.validate_fleet_jsonl(log) == []
        # a second cycle still works — the loop survived all three faults
        record2 = col.collect_once()
        assert record2["cycle"] == record["cycle"] + 1
    finally:
        col.close()
        live.stop()
        hang.close()
        garb.close()


def test_collector_chaos_worker_kill_degrades_to_stale():
    """Satellite (c) chaos: the `worker@N` kill token takes a live ingest
    worker down mid-stream; its fleet entry degrades to `stale` with age
    while the survivor keeps the quorum."""
    from distributed_vgg_f_tpu.resilience import faults
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    exps = [_mk_process("worker", step=i) for i in range(2)]
    col = FleetCollector(
        endpoints=[f"worker[{i}]@127.0.0.1:{exps[i].port}"
                   for i in range(2)],
        interval_s=0.05, stale_after_s=0.05)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16)
    plan = faults.FaultPlan.parse("worker@2")
    wrapped = plan.wrap_iterator(client)
    try:
        record = col.collect_once()
        assert all(p["status"] == "live" for p in record["processes"])
        for _ in range(4):
            next(wrapped)
        deadline = time.monotonic() + 10
        dead = []
        while time.monotonic() < deadline and not dead:
            dead = [i for i, w in enumerate(workers)
                    if w._closed.is_set()]
            time.sleep(0.02)
        assert len(dead) == 1  # the token killed exactly one worker
        # the worker process died: its exporter goes down with it
        exps[dead[0]].stop()
        time.sleep(0.12)
        record = col.collect_once()
        by_ident = {p["ident"]: p for p in record["processes"]}
        assert by_ident[dead[0]]["status"] == "stale"
        assert by_ident[dead[0]]["age_s"] is not None
        assert by_ident[1 - dead[0]]["status"] == "live"
        assert (record["fleet"]["quorum"], record["fleet"]["of"]) == (1, 1)
        assert schema.validate_fleet_record(record) == []
    finally:
        col.close()
        client.close()
        for w in workers:
            w.close()
        for e in exps:
            e.stop()


# -------------------------------------------------------- sidecar discovery

def test_sidecar_discovery_filters_dead_pids(tmp_path):
    """Satellite (f): sidecar entries carry role + start time; a sidecar
    whose pid no longer exists is filtered by the liveness probe instead
    of being scraped forever."""
    d = tmp_path / "sidecars"
    d.mkdir()
    alive = {"event": "telemetry_exporter", "host": "127.0.0.1",
             "port": 9100, "pid": os.getpid(), "role": "trainer_rank0",
             "start_unix": 123.0}
    (d / "exporter_p00000.jsonl").write_text(json.dumps(alive) + "\n")
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()  # reaped: the pid is gone
    stale = dict(alive, pid=proc.pid, port=9101, role="worker")
    (d / "exporter_p00001.jsonl").write_text(json.dumps(stale) + "\n")
    (d / "exporter_p00002.jsonl").write_text("not json\n")  # tolerated

    reg = TelemetryRegistry()
    eps = discover_sidecar_endpoints(str(d), registry=reg)
    assert [(e.role, e.ident, e.port) for e in eps] == \
        [("trainer_rank0", 0, 9100)]
    assert eps[0].pid == os.getpid()
    assert eps[0].start_unix == 123.0
    assert reg.counter_value("collector/stale_sidecars", 0) == 1


def test_exporter_sidecar_carries_role_and_start(tmp_path):
    """The exporter's own describe()/sidecar record now names the role and
    birth time the collector's discovery needs."""
    exp = _mk_process("worker_rank3")
    try:
        desc = exp.describe()
        assert desc["role"] == "worker_rank3"
        assert desc["pid"] == os.getpid()
        assert isinstance(desc["start_unix"], float)
    finally:
        exp.stop()


# ------------------------------------------------------------- CLI surface

def test_collector_cli_smoke(tmp_path, capsys):
    exp = _mk_process("trainer")
    log = str(tmp_path / "fleet.jsonl")
    try:
        rc = collector_mod.main([
            "--endpoint", f"trainer[0]@127.0.0.1:{exp.port}",
            "--interval", "0.05", "--cycles", "2",
            "--fleet-log", log, "--port", "0"])
        assert rc == 0
        line = json.loads(capsys.readouterr().out.splitlines()[0])
        assert line["event"] == "fleet_collector"
        assert schema.validate_fleet_jsonl(log) == []
        assert sum(1 for _ in open(log)) == 2
    finally:
        exp.stop()


def test_collector_cli_requires_a_discovery_source():
    with pytest.raises(SystemExit):
        collector_mod.main(["--cycles", "1"])


# -------------------------------------------------------- trace stitching

def test_stitch_links_client_get_to_owning_worker_decode(tmp_path):
    """The acceptance link: the trainer-side `service_get` span flows to
    the decode span of the worker that SERVED that cursor, across three
    per-process traces merged into one Perfetto-loadable file."""
    telemetry.set_process_label("trainer_rank0")
    cfg = _synthetic_cfg()
    recs = [SpanRecorder(), SpanRecorder()]
    workers = _replay_workers(cfg.data, 2, recorders=recs)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16)
    try:
        for _ in range(6):
            next(client)
    finally:
        client.close()
        for w in workers:
            w.close()

    paths = [str(tmp_path / "trainer.trace.json"),
             str(tmp_path / "worker0.trace.json"),
             str(tmp_path / "worker1.trace.json")]
    traces = [telemetry.get_recorder().to_chrome_trace(),
              recs[0].to_chrome_trace(process_name="ingest_worker0"),
              recs[1].to_chrome_trace(process_name="ingest_worker1")]
    for p, t in zip(paths, traces):
        with open(p, "w") as f:
            json.dump(t, f)
    out = str(tmp_path / "stitched.trace.json")
    manifest_path = str(tmp_path / "stitched.manifest.json")
    manifest = stitch_mod.stitch_to_files(paths, out, manifest_path)
    stitched = json.load(open(out))

    assert schema.validate_chrome_trace(stitched) == []
    assert schema.validate_stitch_manifest(manifest) == []
    assert schema.validate_stitch_manifest_file(manifest_path) == []
    names = {i["process_name"]: i["pid"] for i in manifest["inputs"]}
    assert names["trainer_rank0"] == 1  # module label → process_name meta
    assert {"ingest_worker0", "ingest_worker1"} <= set(names)

    # every get flows trainer → exactly one worker, and it is the OWNING
    # worker: the worker whose decode span recorded the same trace id
    decode_owner = {}
    for i, rec in enumerate(recs):
        for _name, _cat, _s0, _dur, _tid, args in rec.snapshot():
            decode_owner[args["trace_id"]] = names[f"ingest_worker{i}"]
    get_flows = [f for f in manifest["flows"]
                 if f["src"]["name"] == "service_get"]
    assert len(get_flows) >= 6  # ≥: the client may prefetch ahead
    for f in get_flows:
        assert f["src"]["pid"] == names["trainer_rank0"]
        assert [d["name"] for d in f["dst"]] == ["service_decode"]
        assert f["dst"][0]["pid"] == decode_owner[f["trace_id"]]
    assert {f["dst"][0]["pid"] for f in get_flows} == \
        {names["ingest_worker0"], names["ingest_worker1"]}  # both shards

    # the merged trace carries the flow events and per-input metadata
    phs = {}
    for ev in stitched["traceEvents"]:
        phs.setdefault(ev["ph"], 0)
        phs[ev["ph"]] += 1
    assert phs.get("s", 0) >= 6 and phs.get("f", 0) >= 6
    meta_names = {ev["args"]["name"] for ev in stitched["traceEvents"]
                  if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert {"trainer_rank0", "ingest_worker0", "ingest_worker1"} <= \
        meta_names


class _StubEngine:
    """The smallest engine PredictServer will accept — no jax, no AOT."""

    model_name = "vggf"
    image_size = 8
    num_classes = 4
    buckets = (1, 2)

    def warmup(self):
        return None

    def run(self, images):
        n = images.shape[0]
        probs = np.full((n, self.num_classes), 1.0 / self.num_classes,
                        dtype=np.float32)
        return probs, self.buckets[-1]


def test_stitch_links_serving_request_to_engine_flush(tmp_path):
    from distributed_vgg_f_tpu.config import ServingConfig
    from distributed_vgg_f_tpu.serving.server import PredictServer
    telemetry.set_process_label("serving_frontend")
    cfg = ServingConfig(enabled=True, max_batch=2, buckets=(1, 2),
                        controller=False, warmup=False)
    server = PredictServer(cfg)
    server.add_engine(_StubEngine())
    port = server.start()
    trace_id = "req-deadbeef1234"
    try:
        image = np.zeros((8, 8, 3), np.uint8)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/predict/vggf",
            data=image.tobytes(), method="POST",
            headers={"X-DVGGF-Trace-Id": trace_id})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    finally:
        server.close()

    path = str(tmp_path / "serving.trace.json")
    with open(path, "w") as f:
        json.dump(telemetry.get_recorder().to_chrome_trace(), f)
    manifest = stitch_mod.stitch_to_files(
        [path], str(tmp_path / "out.json"),
        str(tmp_path / "out.manifest.json"))
    assert schema.validate_stitch_manifest(manifest) == []
    flows = {f["trace_id"]: f for f in manifest["flows"]}
    assert trace_id in flows
    f = flows[trace_id]
    assert f["src"]["name"] == "serving_request"
    assert [d["name"] for d in f["dst"]] == ["serving_flush_vggf"]


def test_stitch_tolerates_absent_ids_and_rejects_garbage(tmp_path):
    # spans with no trace ids stitch into a flowless (but valid) trace
    rec = SpanRecorder()
    rec.record("plain", "compute", 0, 1000)
    p = str(tmp_path / "t.json")
    with open(p, "w") as f:
        json.dump(rec.to_chrome_trace(process_name="p0"), f)
    out = stitch_mod.stitch_traces([p])
    assert out["manifest"]["flows"] == []
    assert schema.validate_stitch_manifest(out["manifest"]) == []
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("not a trace")
    with pytest.raises(ValueError):
        stitch_mod.stitch_traces([bad])


def test_chrome_trace_carries_process_and_thread_metadata():
    rec = SpanRecorder()
    rec.record("step", "compute", 1000, 2000, {"k": "v"})
    trace = rec.to_chrome_trace(process_name="trainer_rank0")
    assert schema.validate_chrome_trace(trace) == []
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "process_name"
               and e["args"]["name"] == "trainer_rank0" for e in meta)
    tids = {e["tid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    named = {e["tid"] for e in meta if e["name"] == "thread_name"}
    assert tids <= named  # every emitting thread is labelled
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert spans[0]["args"] == {"k": "v"}


# -------------------------------------------- critical-path attribution

def test_critical_path_block_in_live_trainer_window(tmp_path):
    """The tentpole's third leg, end to end: a real fit() writes a
    critical_path split into every rank-0 window record, the parts sum to
    the window wall-clock, and the schema validator holds the line."""
    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, ModelConfig, OptimConfig,
        TelemetryConfig, TrainConfig)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    cfg = ExperimentConfig(
        name="critical_path_smoke",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=128),
        train=TrainConfig(steps=8, log_every=2, seed=0),
        telemetry=TelemetryConfig(),
    )
    jsonl = str(tmp_path / "metrics.jsonl")
    with MetricLogger(jsonl_path=jsonl, stream=io.StringIO()) as logger:
        tr = Trainer(cfg, logger=logger)
        tr.fit(tr.init_state())
    assert schema.validate_metrics_jsonl(jsonl) == []
    windows = [json.loads(line) for line in open(jsonl)]
    cps = [w["critical_path"] for w in windows
           if w.get("event") == "train" and "critical_path" in w]
    assert cps, "no window carried a critical_path block"
    parts = ("infeed_s", "device_s", "checkpoint_s", "exchange_s")
    for cp in cps:
        total = sum(cp[p] for p in parts)
        assert abs(total - cp["window_s"]) <= \
            max(1e-3, 1e-3 * cp["window_s"]), cp
        assert cp["dominant"] in ("infeed", "device", "checkpoint",
                                  "exchange")
        assert all(cp[p] >= 0.0 for p in parts)
    # a synthetic-data CPU run spends its windows in device or infeed,
    # never in checkpointing it isn't doing
    assert all(cp["checkpoint_s"] == 0.0 for cp in cps)


def test_critical_path_schema_rejects_bad_blocks():
    base = {"event": "train", "step": 2, "loss": 1.0,
            "critical_path": {"window_s": 1.0, "infeed_s": 0.25,
                              "device_s": 0.75, "checkpoint_s": 0.0,
                              "exchange_s": 0.0, "dominant": "device"}}
    assert schema.validate_metrics_record(base) == []
    bad_sum = json.loads(json.dumps(base))
    bad_sum["critical_path"]["device_s"] = 0.5
    assert any("parts sum" in e
               for e in schema.validate_metrics_record(bad_sum))
    bad_dom = json.loads(json.dumps(base))
    bad_dom["critical_path"]["dominant"] = "gremlins"
    assert any("dominant" in e
               for e in schema.validate_metrics_record(bad_dom))
    negative = json.loads(json.dumps(base))
    negative["critical_path"]["infeed_s"] = -0.1
    assert schema.validate_metrics_record(negative) != []


# --------------------------------------------------- fleet schema guards

def test_fleet_schema_rejects_malformed_records():
    good = {"event": "fleet_window", "schema_version": "1.0",
            "t_unix": 1.0, "cycle": 1,
            "fleet": {"verdict": "compute_bound", "quorum": 1, "of": 1,
                      "stragglers": {}, "detail": "compute_bound by "
                      "quorum 1/1"},
            "processes": [{"role": "trainer", "ident": 0,
                           "endpoint": "127.0.0.1:9100",
                           "status": "live",
                           "verdict": "compute_bound", "age_s": 0.0}]}
    assert schema.validate_fleet_record(good) == []
    for mutate, needle in (
            (lambda r: r["fleet"].update(quorum=5), "quorum"),
            (lambda r: r["processes"][0].update(status="zombie"),
             "status"),
            (lambda r: r["processes"][0].update(verdict="gremlins"),
             "verdict"),
            (lambda r: r.pop("schema_version"), "schema_version"),
            (lambda r: r.update(cycle=0), "cycle")):
        bad = json.loads(json.dumps(good))
        mutate(bad)
        errs = schema.validate_fleet_record(bad)
        assert any(needle in e for e in errs), (needle, errs)
