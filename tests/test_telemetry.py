"""Telemetry spine (distributed_vgg_f_tpu/telemetry/): span ring buffer +
Chrome-trace export, counter registry with pollers and per-consumer deltas,
stall-attribution taxonomy, schema validators, the import-isolation
contract, and the integration seams — chaos-suite fault counters, a
synthetic slow iterator attributed infeed_bound, and a trainer smoke run
whose step records carry a verdict plus decode/prefetch/resilience counters
in one JSONL stream (ISSUE 4 acceptance)."""

import io
import json
import os
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    OptimConfig,
    TelemetryConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.telemetry import schema
from distributed_vgg_f_tpu.telemetry.registry import TelemetryRegistry
from distributed_vgg_f_tpu.telemetry.spans import SpanRecorder
from distributed_vgg_f_tpu.telemetry.stall import (
    VERDICTS,
    StallAttributor,
    classify,
    occupancy_from_spans,
)
from distributed_vgg_f_tpu.utils.logging import MetricLogger


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """The default recorder/registry are process-global: re-baseline around
    every test so counter assertions see only their own activity."""
    telemetry.reset()
    telemetry.configure(enabled=True)
    yield
    telemetry.reset()
    telemetry.configure(enabled=True)


def _cfg(steps=3, tmp=None, **train_kw):
    tele = {}
    if tmp is not None:
        tele = {"trace_export": str(tmp / "trace.json"),
                "sidecar_dir": str(tmp / "sidecars")}
    return ExperimentConfig(
        name="telemetry_test",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        train=TrainConfig(steps=steps, log_every=1, seed=0, **train_kw),
        telemetry=TelemetryConfig(**tele),
    )


# ------------------------------------------------------------------- spans
def test_span_ring_bounds_and_thread_safety():
    rec = SpanRecorder(capacity=64)
    threads = [threading.Thread(
        target=lambda: [rec.record("s", "host", i, 10) for i in range(100)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = rec.snapshot()
    assert len(spans) == 64                       # bounded
    assert rec.recorded == 400
    assert rec.dropped == 400 - 64                # evictions counted
    assert {s[4] for s in spans} <= {t.ident for t in threads}


def test_span_disabled_records_nothing():
    rec = SpanRecorder(enabled=False)
    with rec.span("x", "infeed"):
        pass
    rec.record("y", "host", 0, 5)
    assert rec.snapshot() == [] and rec.recorded == 0


def test_chrome_trace_export_validates(tmp_path):
    rec = SpanRecorder()
    with rec.span("load", "infeed"):
        time.sleep(0.001)
    rec.record("save", "checkpoint", time.monotonic_ns(), 5_000)
    path = str(tmp_path / "trace.json")
    trace = rec.export_chrome_trace(path, process_name="p0")
    assert schema.validate_chrome_trace(trace) == []
    assert schema.validate_trace_file(path) == []
    events = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in events} == {"load", "save"}
    assert all(e["dur"] >= 0 and e["ts"] > 0 for e in events)
    # µs conversion: the 1 ms sleep must be visible in the dur
    assert max(e["dur"] for e in events) >= 1_000


# ----------------------------------------------------------------- registry
def test_registry_counters_gauges_and_consumer_deltas():
    reg = TelemetryRegistry()
    reg.counter("a/zero")                 # pre-created → visible as 0
    reg.inc("a/n", 3)
    reg.set_gauge("g/depth", 2)
    snap = reg.snapshot()
    assert snap == {"a/zero": 0, "a/n": 3, "g/depth": 2}
    assert reg.delta("c1") == {"a/zero": 0, "a/n": 3, "g/depth": 2}
    reg.inc("a/n", 2)
    reg.set_gauge("g/depth", 0)
    # deltas are per-consumer: c1 sees only the new increments, a fresh
    # consumer sees the lifetime total; gauges stay absolute everywhere
    assert reg.delta("c1") == {"a/zero": 0, "a/n": 2, "g/depth": 0}
    assert reg.delta("c2")["a/n"] == 5


def test_registry_pollers_cumulative_and_errors():
    reg = TelemetryRegistry()
    state = {"images": 10}
    reg.register_poller("decode", lambda: {
        "images": state["images"], "scale_histogram": {4: 2, 8: 1}})
    assert reg.snapshot()["decode/images"] == 10
    assert reg.snapshot()["decode/scale_histogram/4"] == 2
    reg.delta("c")
    state["images"] = 25
    assert reg.delta("c")["decode/images"] == 15   # cumulative → delta'd
    reg.register_poller("bad", lambda: 1 / 0)
    snap = reg.snapshot()                          # must not raise
    assert snap["telemetry/poller_errors"] >= 1
    assert "bad" not in "".join(k.split("/")[0] for k in snap
                                if k.startswith("bad/"))


def test_registry_delta_survives_transient_poller_failure():
    """A poller that fails for one window must not reset its baseline: the
    next successful window's delta is the WINDOW's change, never the
    process-lifetime total (code-review r8)."""
    reg = TelemetryRegistry()
    state = {"images": 1000, "fail": False}

    def poll():
        if state["fail"]:
            raise RuntimeError("transient")
        return {"images": state["images"]}

    reg.register_poller("decode", poll)
    reg.delta("c")                                  # baseline at 1000
    state["fail"] = True
    assert "decode/images" not in reg.delta("c")    # failed window: absent
    state["fail"] = False
    state["images"] = 1010
    assert reg.delta("c")["decode/images"] == 10    # not 1010


def test_registry_has_poller_and_direct_gauge_read():
    """reset() drops pollers, so registration guards must key on
    has_poller (a stale module flag would sever the subsystem's counters
    for the process lifetime — code-review r8); gauge() reads one value
    without sweeping the pollers."""
    reg = TelemetryRegistry()
    assert not reg.has_poller("decode")
    reg.register_poller("decode", lambda: {"images": 1})
    assert reg.has_poller("decode")
    reg.reset()
    assert not reg.has_poller("decode")
    calls = {"n": 0}

    def poll():
        calls["n"] += 1
        return {"x": 1}

    reg.register_poller("p", poll)
    reg.set_gauge("prefetch/queue_depth", 2)
    assert reg.gauge("prefetch/queue_depth") == 2
    assert reg.gauge("missing", -1) == -1
    assert calls["n"] == 0          # no poller sweep on the direct read
    split = reg.snapshot_split()
    assert split["counters"]["p/x"] == 1
    assert split["gauges"] == {"prefetch/queue_depth": 2}


def test_registry_disabled_drops_writes():
    reg = TelemetryRegistry(enabled=False)
    reg.inc("a/n")
    reg.set_gauge("g", 1)
    assert reg.snapshot() == {}


# -------------------------------------------------------------------- stall
def test_stall_taxonomy_priorities():
    assert classify(1.0, 0.05, 0.0)["verdict"] == "compute_bound"
    assert classify(1.0, 0.5, 0.0)["verdict"] == "infeed_bound"
    assert classify(1.0, 0.1, 0.4)["verdict"] == "checkpoint_bound"
    # guard beats everything: a run skipping updates isn't training no
    # matter where its wall time goes
    assert classify(1.0, 0.9, 0.9, guard_skips=1)["verdict"] \
        == "guard_stalled"
    # checkpoint vs infeed: the LARGER blocked fraction wins, checkpoint
    # winning exact ties (it usually CAUSES the infeed gap)
    assert classify(1.0, 0.4, 0.4)["verdict"] == "checkpoint_bound"
    assert classify(1.0, 0.6, 0.3)["verdict"] == "infeed_bound"
    # candidacy is per-bucket: an infeed fraction BELOW its own (raised)
    # threshold must not veto a checkpoint fraction above its threshold
    assert classify(1.0, 0.35, 0.30,
                    infeed_threshold=0.4)["verdict"] == "checkpoint_bound"
    assert set(VERDICTS) == {"guard_stalled", "checkpoint_bound",
                             "infeed_bound", "compute_bound"}


def test_occupancy_merges_overlapping_spans():
    spans = [("a", "infeed", 0, 100, 1), ("b", "infeed", 50, 100, 2),
             ("c", "checkpoint", 300, 50, 1), ("d", "infeed", 1000, 100, 1)]
    occ = occupancy_from_spans(spans, 0, 400)
    # [0,150) union, not 200 sum; the span at 1000 is outside the window
    assert occ["infeed"] == pytest.approx(150e-9)
    assert occ["checkpoint"] == pytest.approx(50e-9)


def test_slow_iterator_attributed_infeed_bound(devices8):
    """ISSUE 4 satellite: a synthetic slow loader must come back
    infeed_bound from stall.py, driven end-to-end through the REAL
    device-prefetch spans (no hand-fed fractions)."""
    from distributed_vgg_f_tpu.data.prefetch import DevicePrefetchIterator
    from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(("data",), (8,)))

    def slow_source():
        while True:
            time.sleep(0.05)  # decode 20 img/s-slow
            yield {"image": np.zeros((16, 8, 8, 3), np.float32),
                   "label": np.zeros((16,), np.int32)}

    pre = DevicePrefetchIterator(slow_source(), mesh)
    attributor = StallAttributor(registry=telemetry.get_registry(),
                                 recorder=telemetry.get_recorder())
    try:
        t0 = time.monotonic_ns()
        for _ in range(4):
            next(pre)
        t1 = time.monotonic_ns()
    finally:
        pre.close()
    verdict = attributor.window_from_spans(t0, t1)
    assert verdict["verdict"] == "infeed_bound"
    assert verdict["infeed_fraction"] > 0.5
    # the corroborating gauge: a starved consumer sees an empty queue
    assert verdict["queue_depth"] == 0


# --------------------------------------------------- chaos-suite integration
def test_fault_injectors_increment_matching_counters(devices8):
    """ISSUE 4 satellite: every train.fault_injection fault type announces
    itself in the fault/ registry namespace, and the guard's skip rides the
    resilience/ namespace — one fit exercising nan+stall+preempt, one
    exercising crash."""
    from distributed_vgg_f_tpu.resilience import InjectedFault
    from distributed_vgg_f_tpu.train.trainer import Trainer

    quiet = MetricLogger(stream=io.StringIO())
    tr = Trainer(_cfg(steps=4,
                      fault_injection="nan@1,stall@2:0.05,preempt@3"),
                 logger=quiet)
    tr.fit(tr.init_state())
    counters = telemetry.get_registry().snapshot()
    assert counters["fault/nan"] == 1
    assert counters["fault/stall"] == 1
    assert counters["fault/preempt"] == 1
    assert counters["resilience/nonfinite_skips"] == 1

    tr2 = Trainer(_cfg(steps=4, fault_injection="crash@2"), logger=quiet)
    with pytest.raises(InjectedFault):
        tr2.fit(tr2.init_state())
    assert telemetry.get_registry().snapshot()["fault/crash"] == 1


# ------------------------------------------------------------ trainer smoke
def test_trainer_smoke_one_jsonl_stream(devices8, tmp_path):
    """ISSUE 4 acceptance: a CPU smoke run produces step records carrying a
    stall-attribution verdict plus decode/prefetch/resilience counters in
    ONE JSONL stream, the stream validates against the schema, and the
    exported span file validates as Chrome trace-event JSON."""
    from distributed_vgg_f_tpu.train.trainer import Trainer

    path = str(tmp_path / "metrics.jsonl")
    with MetricLogger(jsonl_path=path, stream=io.StringIO()) as logger:
        tr = Trainer(_cfg(steps=3, tmp=tmp_path), logger=logger)
        tr.fit(tr.init_state())
    assert schema.validate_metrics_jsonl(path) == []
    records = [json.loads(l) for l in open(path)]
    train_records = [r for r in records if r["event"] == "train"]
    assert len(train_records) == 3
    for r in train_records:
        assert r["stall"]["verdict"] in VERDICTS
        counters = r["counters"]
        assert counters["prefetch/batches"] == 1          # log_every=1
        assert "resilience/nonfinite_skips" in counters
        assert "decode/errors_total" in counters
        assert "prefetch/queue_depth" in counters
        assert "window_images_per_sec" in r               # rolling meter
    # the span file: Chrome trace-event JSON with the wired categories
    trace_path = str(tmp_path / "trace.json")
    assert schema.validate_trace_file(trace_path) == []
    cats = {e.get("cat") for e in
            json.load(open(trace_path))["traceEvents"]}
    assert {"infeed", "dispatch"} <= cats
    # sidecar + aggregate written (single process: 1)
    agg = json.load(open(tmp_path / "sidecars" /
                         "telemetry_aggregate.json"))
    assert agg["processes"] == 1
    assert agg["counters"]["prefetch/batches"] >= 3
    # gauges are per-rank in the aggregate, never summed across ranks
    assert "prefetch/queue_depth" in agg["gauges_by_process"]
    assert set(agg["gauges_by_process"]["prefetch/queue_depth"]) == {"0"}


def test_telemetry_disabled_is_silent(devices8, tmp_path):
    """enabled=false is a real kill-switch: no stall/counters in the step
    records, nothing recorded into the ring."""
    from distributed_vgg_f_tpu.train.trainer import Trainer

    path = str(tmp_path / "metrics.jsonl")
    cfg = _cfg(steps=2)
    cfg = ExperimentConfig(**{**cfg.__dict__,
                              "telemetry": TelemetryConfig(enabled=False)})
    with MetricLogger(jsonl_path=path, stream=io.StringIO()) as logger:
        tr = Trainer(cfg, logger=logger)
        tr.fit(tr.init_state())
    train_records = [json.loads(l) for l in open(path)
                     if json.loads(l)["event"] == "train"]
    assert train_records and all(
        "stall" not in r and "counters" not in r for r in train_records)
    assert telemetry.get_recorder().snapshot() == []


# ------------------------------------------------------------------- schema
def test_schema_catches_drift(tmp_path):
    assert schema.validate_metrics_record({"event": "train", "loss": 1.0}) \
        == []
    assert schema.validate_metrics_record({"loss": 1.0})    # no event
    assert schema.validate_metrics_record([1, 2])           # not an object
    # bare NaN tokens — JSON-illegal, the exact drift the validator exists
    # to catch (json.loads alone would ACCEPT them)
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "train", "loss": NaN}\n')
    assert schema.validate_metrics_jsonl(str(bad))
    ok = tmp_path / "ok.jsonl"
    ok.write_text('{"event": "train", "loss": null, '
                  '"loss_nonfinite": "nan"}\n')
    assert schema.validate_metrics_jsonl(str(ok)) == []
    # trace drift
    assert schema.validate_chrome_trace({"traceEvents": [
        {"name": "x", "ph": "X", "ts": "soon", "dur": 1,
         "pid": 1, "tid": 1, "cat": "host"}]})
    assert schema.validate_chrome_trace({"events": []})


def test_schema_validates_committed_bench_artifacts():
    """Record-shape drift in the committed run archives fails fast."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    checked = 0
    for name in sorted(os.listdir(repo)):
        if name.startswith("BENCH_r") and name.endswith(".json"):
            errors = schema.validate_bench_artifact_file(
                os.path.join(repo, name))
            assert errors == [], f"{name}: {errors}"
            checked += 1
    runs = os.path.join(repo, "benchmarks", "runs")
    for dirpath, _, files in os.walk(runs):
        for f in files:
            if f.endswith(".json"):
                path = os.path.join(dirpath, f)
                with open(path) as fh:
                    try:
                        obj = json.load(fh)
                    except ValueError:
                        obj = None
                if not isinstance(obj, dict):
                    continue
                if obj.get("kind") == "perf_trajectory":
                    # the r10 sentinel's trajectory file has its own shape
                    errors = schema.validate_trajectory(obj)
                    assert errors == [], f"{path}: {errors}"
                    checked += 1
                elif "metric" in obj:
                    errors = schema.validate_bench_artifact_file(path)
                    assert errors == [], f"{path}: {errors}"
                    checked += 1
    assert checked > 0


# ------------------------------------------------------------ schema_version
def test_schema_version_field_rules():
    """ISSUE 8 satellite: absent = legal (pre-versioned archives), known
    major = legal at any minor, unknown major = rejected, non-string =
    rejected — same rule on metrics records and bench artifacts."""
    ok = {"event": "train", "loss": 1.0}
    assert schema.validate_metrics_record(ok) == []
    assert schema.validate_metrics_record(
        {**ok, "schema_version": schema.SCHEMA_VERSION}) == []
    assert schema.validate_metrics_record(
        {**ok, "schema_version": "1.7"}) == []          # future minor: fine
    assert any("major" in e for e in schema.validate_metrics_record(
        {**ok, "schema_version": "2.0"}))
    assert schema.validate_metrics_record(
        {**ok, "schema_version": 1})                     # not a string
    assert schema.validate_metrics_record(
        {**ok, "schema_version": "one.oh"})
    art = {"metric": "m", "value": 1.0}
    assert schema.validate_bench_artifact(
        {**art, "schema_version": schema.SCHEMA_VERSION}) == []
    assert any("major" in e for e in schema.validate_bench_artifact(
        {**art, "schema_version": "3.0"}))


def test_metric_logger_stamps_schema_version(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricLogger(jsonl_path=path, stream=io.StringIO()) as logger:
        logger.log("train", {"step": 1, "loss": 0.5})
    record = json.loads(open(path).readline())
    assert record["schema_version"] == schema.SCHEMA_VERSION
    assert schema.validate_metrics_jsonl(path) == []


# ------------------------------------------------- counter-namespace guard
def _normalize_buckets(name: str) -> str:
    """Histogram bucket keys (decode/scale_histogram/8) document as one
    `<m>` placeholder row."""
    import re
    return re.sub(r"^(decode/scale_histogram)/\d+$", r"\1/<m>", name)


def test_counter_table_matches_runtime(devices8):
    """ISSUE 8 satellite — counter-namespace drift guard: the README table
    is cross-checked against (a) every counter/gauge name literal in the
    package source (the registration sites: prefetch, snapshot cache,
    resilience, checkpoint, trainer, exporter, ...) and (b) the native
    decode poller's ACTUAL runtime keys. Undocumented runtime names and
    stale documented names both fail.

    Since r15 half (a) — the static literal scan and table parse — lives
    in the unified invariant linter (`counter-namespace-drift`,
    tools/lint/rules.py); this test runs that rule and keeps the RUNTIME
    half the linter cannot see: the decode poller's dynamically-registered
    keys, reconciled against the table's `decode/` rows."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.lint import RepoContext, get_rule
    from tools.lint.rules import (
        package_counter_literals,
        readme_documented_counters,
    )
    ctx = RepoContext(repo)

    # (a) the static half, through the framework rule
    violations = get_rule("counter-namespace-drift").check(ctx)
    assert violations == [], "\n".join(str(v) for v in violations)

    namespaces, documented, errs = readme_documented_counters(ctx)
    assert errs == []
    assert {"decode", "prefetch", "resilience", "checkpoint", "fault",
            "exporter", "telemetry"} <= namespaces
    runtime = set(package_counter_literals(ctx))

    # (b) the native decode poller's real keys, when the decoder exists on
    # this host (it does in CI; the literal half still guards without it)
    from distributed_vgg_f_tpu.data.native_jpeg import (
        load_native_jpeg,
        register_decode_poller,
    )
    native = load_native_jpeg() is not None
    if native:
        # decode ONE image first so the scale histogram carries a bucket —
        # a fresh process's empty histogram would make the documented
        # `scale_histogram/<m>` row read as stale
        from distributed_vgg_f_tpu.data.native_jpeg import (
            decode_single_image)
        from PIL import Image
        import io as _io
        buf = _io.BytesIO()
        Image.fromarray(np.zeros((48, 48, 3), np.uint8)).save(
            buf, "JPEG", quality=90)
        decode_single_image(buf.getvalue(), 32,
                            np.zeros(3, np.float32),
                            np.ones(3, np.float32))
        register_decode_poller()
        snap = telemetry.get_registry().snapshot()
        runtime |= {k for k in snap if k.startswith("decode/")}

    runtime = {_normalize_buckets(n) for n in runtime
               if n.split("/", 1)[0] in namespaces}
    if not native:
        keep = {"decode/errors_total"}  # the trainer-side literal
        documented = {n for n in documented
                      if not n.startswith("decode/") or n in keep}
    undocumented = sorted(runtime - documented)
    stale = sorted(documented - runtime)
    assert not undocumented, (
        f"counters registered at runtime but missing from the README "
        f"table: {undocumented}")
    assert not stale, (
        f"README table documents counters nothing registers (stale "
        f"entries): {stale}")


# --------------------------------------------------------- import isolation
def test_import_pulls_no_heavy_deps():
    """ISSUE 4 satellite (extended in ISSUE 8 to the live-observability
    modules): importing telemetry — including the exporter, flight
    recorder, and regression engine — must pull in neither TensorFlow, nor
    jax/numpy, nor the native .so (an import that triggers a g++ build of
    the decoder would make telemetry a correctness dependency of the thing
    it observes)."""
    code = (
        "import sys, distributed_vgg_f_tpu.telemetry\n"
        "import distributed_vgg_f_tpu.telemetry.exporter\n"
        "import distributed_vgg_f_tpu.telemetry.flight\n"
        "import distributed_vgg_f_tpu.telemetry.regress\n"
        "heavy = [m for m in ('tensorflow', 'jax', 'numpy')\n"
        "         if m in sys.modules]\n"
        "assert not heavy, f'telemetry imported {heavy}'\n"
        "import os\n"
        "if os.path.exists('/proc/self/maps'):\n"
        "    maps = open('/proc/self/maps').read()\n"
        "    assert 'libdvgg' not in maps, 'native .so loaded'\n"
        "print('ISOLATED')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], cwd=repo,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "ISOLATED" in out.stdout
