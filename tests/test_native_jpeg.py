"""Native libjpeg training loader (native/jpeg_loader.cc via
data/native_jpeg.py): determinism regardless of thread count, O(1) exact seek
resume, bf16 output, corrupt-image fallback, and imagefolder integration."""

import os

import numpy as np
import pytest

pytest.importorskip("tensorflow")

from distributed_vgg_f_tpu.data.native_jpeg import (  # noqa: E402
    NativeJpegTrainIterator,
    load_native_jpeg,
)

if load_native_jpeg() is None:  # pragma: no cover — g++/libjpeg exist here
    pytest.skip("native jpeg loader unavailable", allow_module_level=True)

MEAN = np.array([123.68, 116.78, 103.94], np.float32)
STD = np.array([58.393, 57.12, 57.375], np.float32)


@pytest.fixture(scope="module")
def jpeg_files(tmp_path_factory):
    import tensorflow as tf
    root = tmp_path_factory.mktemp("jpegs")
    rng = np.random.default_rng(0)
    files, labels = [], []
    for i in range(24):
        p = str(root / f"img_{i:03d}.jpg")
        img = rng.integers(0, 256, size=(96, 128, 3)).astype(np.uint8)
        with open(p, "wb") as f:
            f.write(tf.io.encode_jpeg(img, quality=90).numpy())
        files.append(p)
        labels.append(i % 10)
    return files, labels


def _make(files, labels, **kw):
    kw.setdefault("seed", 7)
    kw.setdefault("mean", MEAN)
    kw.setdefault("std", STD)
    return NativeJpegTrainIterator(files, labels, 8, 64, **kw)


def test_shapes_normalization_and_no_errors(jpeg_files):
    it = _make(*jpeg_files)
    b = next(it)
    assert b["image"].shape == (8, 64, 64, 3)
    assert b["image"].dtype == np.float32
    assert b["label"].shape == (8,) and b["label"].dtype == np.int32
    assert abs(float(b["image"].mean())) < 2.0
    assert float(np.asarray(b["image"], np.float32).std()) > 0.2
    assert it.decode_errors() == 0
    it.close()


def test_deterministic_regardless_of_thread_count(jpeg_files):
    files, labels = jpeg_files
    a = _make(files, labels, num_threads=1)
    b = _make(files, labels, num_threads=4)
    for _ in range(8):  # crosses an epoch boundary (24 imgs / batch 8)
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
    a.close()
    b.close()


def test_seek_resume_bit_identical(jpeg_files):
    files, labels = jpeg_files
    ref = _make(files, labels, num_threads=2)
    batches = [next(ref) for _ in range(9)]
    resumed = _make(files, labels, num_threads=3)
    assert resumed.supports_state
    assert resumed.restore_state(5)
    for i in range(5, 9):
        b = next(resumed)
        np.testing.assert_array_equal(b["image"], batches[i]["image"])
        np.testing.assert_array_equal(b["label"], batches[i]["label"])
    # seeking after the stream started must refuse (position already consumed)
    assert resumed.restore_state(2) is False
    ref.close()
    resumed.close()


def test_bf16_output(jpeg_files):
    import ml_dtypes
    it = _make(*jpeg_files, image_dtype="bfloat16")
    assert next(it)["image"].dtype == np.dtype(ml_dtypes.bfloat16)
    it.close()


def test_corrupt_image_zero_fills_and_counts(jpeg_files, tmp_path):
    files, labels = jpeg_files
    bad = str(tmp_path / "corrupt.jpg")
    with open(bad, "wb") as f:
        f.write(b"\xff\xd8\xffnot a real jpeg at all")
    it = NativeJpegTrainIterator([bad] * 4, [1, 2, 3, 4], 4, 32,
                                 seed=0, mean=MEAN, std=STD)
    b = next(it)
    assert (np.asarray(b["image"], np.float32) == 0).all()
    # The 3-slot ring decodes ahead: by the time the first batch is consumed
    # the workers may have decoded up to 3 batches (4 items each), so the
    # error counter reads 4..12 depending on scheduling — an exact ==4 here
    # was a timing flake (first seen when a cold compile cache slowed the
    # consumer enough for the ring to fill).
    errs = it.decode_errors()
    assert 4 <= errs <= 12, errs
    it.close()


def test_imagefolder_native_toggle(tmp_path):
    import tensorflow as tf

    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset

    rng = np.random.default_rng(1)
    for cls in ("n01", "n02"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = rng.integers(0, 256, size=(48, 56, 3)).astype(np.uint8)
            with open(d / f"{cls}_{i}.JPEG", "wb") as f:
                f.write(tf.io.encode_jpeg(img).numpy())

    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path), image_size=32,
                     global_batch_size=4, shuffle_buffer=8)
    ds = build_dataset(cfg, "train", seed=0)
    assert isinstance(ds, NativeJpegTrainIterator)
    b = next(ds)
    assert b["image"].shape == (4, 32, 32, 3)
    assert set(b["label"].tolist()) <= {0, 1}
    ds.close()

    import dataclasses
    cfg_tf = dataclasses.replace(cfg, native_jpeg=False)
    ds_tf = build_dataset(cfg_tf, "train", seed=0)
    assert not isinstance(ds_tf, NativeJpegTrainIterator)
    b = next(ds_tf)
    assert b["image"].shape == (4, 32, 32, 3)

# ---------------------------------------------------------------------------
# r7 scale-selection logic (ISSUE 3): the pure-Python mirror
# (expected_scale_denom) must agree with the native ABI's reported choice
# across source sizes and crop modes, the chooser must only pick libjpeg-
# turbo's SIMD IDCT scales, and it must never upscale.
# ---------------------------------------------------------------------------

SOURCE_SIZES = (224, 256, 320, 448, 512, 1024)


def _eval_crop_side(w, h, out_size):
    """Mirror of the native eval center-crop geometry (jpeg_loader.cc):
    side = min(W, H) * out / 256, clamped to the image."""
    side = max(1, round(min(w, h) * out_size / 256.0))
    return min(side, min(w, h))


def test_scale_chooser_mirror_matches_native_abi():
    """dvgg_jpeg_choose_scale == expected_scale_denom across the announced
    source-size grid x train/eval crop modes. Train crops are represented
    by their extremes and a sweep of interior sizes (the chooser only sees
    the crop geometry, not the RNG that produced it)."""
    from distributed_vgg_f_tpu.data.native_jpeg import (
        choose_scale, expected_scale_denom)

    for src in SOURCE_SIZES:
        for out_size in (224, 96):
            # eval mode: the deterministic center crop
            side = _eval_crop_side(src, src, out_size)
            assert choose_scale(side, side, out_size) == \
                expected_scale_denom(side, side, out_size), (src, out_size)
            # train mode: area in [0.08, 1.0] -> linear crop in
            # [~0.28, 1.0] x src, aspect in [3/4, 4/3]; sweep the span
            for frac_num in range(28, 101, 6):
                cw = max(1, src * frac_num // 100)
                for ch in (cw, max(1, cw * 3 // 4), min(src, cw * 4 // 3)):
                    assert choose_scale(cw, ch, out_size) == \
                        expected_scale_denom(cw, ch, out_size), \
                        (src, out_size, cw, ch)


def test_scale_chooser_invariants():
    """Never-upscale: the chosen scale's output still covers out_size in
    both dims, or it is 8/8 (the crop itself is smaller than the target —
    the resample upscales true full-resolution pixels, never scale-decoded
    ones). And only power-of-two scales (libjpeg-turbo's SIMD IDCT sizes)
    are ever chosen — 5/8..7/8 run a slower plain-C IDCT and measured
    net-slower than full decode."""
    from distributed_vgg_f_tpu.data.native_jpeg import (
        SCALE_CANDIDATES, choose_scale)

    for src in SOURCE_SIZES:
        for out_size in (224, 96):
            for cw in range(out_size // 3, src + 1,
                            max(1, src // 17)):
                ch = min(src, max(1, cw * 4 // 3))
                m = choose_scale(cw, ch, out_size)
                assert m in SCALE_CANDIDATES, (cw, ch, out_size, m)
                covered = (cw * m) // 8 >= out_size and \
                          (ch * m) // 8 >= out_size
                assert covered or m == 8, (cw, ch, out_size, m)
                # minimality within the candidate set: no smaller
                # power-of-two scale would also have covered
                for smaller in [c for c in SCALE_CANDIDATES if c < m]:
                    assert not ((cw * smaller) // 8 >= out_size
                                and (ch * smaller) // 8 >= out_size), \
                        (cw, ch, out_size, m, smaller)


def test_chooser_matches_decoded_scale_histogram():
    """The chooser's prediction must match what the decoder actually DID:
    decode a 512px eval image (center crop 448 -> 4/8 scaled decode when
    the scaled path is on) and read the choice back from the decode-stats
    receipt, not from the chooser."""
    import io

    from PIL import Image

    from distributed_vgg_f_tpu.data.native_jpeg import (
        decode_single_image, decode_stats, expected_scale_denom, scaled_kind,
        set_scaled)

    if scaled_kind() != "scaled":
        pytest.skip("scaled decode disabled (kill-switch or -DDVGGF_"
                    "NO_SCALED build) — no scaled choice to observe")
    rng = np.random.default_rng(5)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 256, size=(512, 512, 3))
                    .astype(np.uint8)).save(buf, "JPEG", quality=90)
    side = _eval_crop_side(512, 512, 224)
    expect_m = expected_scale_denom(side, side, 224)
    assert expect_m == 4  # 448-crop to 224: exactly the half-scale decode
    before = set_scaled(True)
    try:
        decode_stats(reset=True)
        img = decode_single_image(buf.getvalue(), 224, MEAN, STD,
                                  eval_mode=True)
        assert img is not None
        stats = decode_stats()
        assert stats["scale_histogram"] == {expect_m: 1}, stats
        assert stats["images"] == 1
    finally:
        set_scaled(before == "scaled")
