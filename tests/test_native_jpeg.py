"""Native libjpeg training loader (native/jpeg_loader.cc via
data/native_jpeg.py): determinism regardless of thread count, O(1) exact seek
resume, bf16 output, corrupt-image fallback, and imagefolder integration."""

import os

import numpy as np
import pytest

pytest.importorskip("tensorflow")

from distributed_vgg_f_tpu.data.native_jpeg import (  # noqa: E402
    NativeJpegTrainIterator,
    load_native_jpeg,
)

if load_native_jpeg() is None:  # pragma: no cover — g++/libjpeg exist here
    pytest.skip("native jpeg loader unavailable", allow_module_level=True)

MEAN = np.array([123.68, 116.78, 103.94], np.float32)
STD = np.array([58.393, 57.12, 57.375], np.float32)


@pytest.fixture(scope="module")
def jpeg_files(tmp_path_factory):
    import tensorflow as tf
    root = tmp_path_factory.mktemp("jpegs")
    rng = np.random.default_rng(0)
    files, labels = [], []
    for i in range(24):
        p = str(root / f"img_{i:03d}.jpg")
        img = rng.integers(0, 256, size=(96, 128, 3)).astype(np.uint8)
        with open(p, "wb") as f:
            f.write(tf.io.encode_jpeg(img, quality=90).numpy())
        files.append(p)
        labels.append(i % 10)
    return files, labels


def _make(files, labels, **kw):
    kw.setdefault("seed", 7)
    kw.setdefault("mean", MEAN)
    kw.setdefault("std", STD)
    return NativeJpegTrainIterator(files, labels, 8, 64, **kw)


def test_shapes_normalization_and_no_errors(jpeg_files):
    it = _make(*jpeg_files)
    b = next(it)
    assert b["image"].shape == (8, 64, 64, 3)
    assert b["image"].dtype == np.float32
    assert b["label"].shape == (8,) and b["label"].dtype == np.int32
    assert abs(float(b["image"].mean())) < 2.0
    assert float(np.asarray(b["image"], np.float32).std()) > 0.2
    assert it.decode_errors() == 0
    it.close()


def test_deterministic_regardless_of_thread_count(jpeg_files):
    files, labels = jpeg_files
    a = _make(files, labels, num_threads=1)
    b = _make(files, labels, num_threads=4)
    for _ in range(8):  # crosses an epoch boundary (24 imgs / batch 8)
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
    a.close()
    b.close()


def test_seek_resume_bit_identical(jpeg_files):
    files, labels = jpeg_files
    ref = _make(files, labels, num_threads=2)
    batches = [next(ref) for _ in range(9)]
    resumed = _make(files, labels, num_threads=3)
    assert resumed.supports_state
    assert resumed.restore_state(5)
    for i in range(5, 9):
        b = next(resumed)
        np.testing.assert_array_equal(b["image"], batches[i]["image"])
        np.testing.assert_array_equal(b["label"], batches[i]["label"])
    # seeking after the stream started must refuse (position already consumed)
    assert resumed.restore_state(2) is False
    ref.close()
    resumed.close()


def test_bf16_output(jpeg_files):
    import ml_dtypes
    it = _make(*jpeg_files, image_dtype="bfloat16")
    assert next(it)["image"].dtype == np.dtype(ml_dtypes.bfloat16)
    it.close()


def test_corrupt_image_zero_fills_and_counts(jpeg_files, tmp_path):
    files, labels = jpeg_files
    bad = str(tmp_path / "corrupt.jpg")
    with open(bad, "wb") as f:
        f.write(b"\xff\xd8\xffnot a real jpeg at all")
    it = NativeJpegTrainIterator([bad] * 4, [1, 2, 3, 4], 4, 32,
                                 seed=0, mean=MEAN, std=STD)
    b = next(it)
    assert (np.asarray(b["image"], np.float32) == 0).all()
    # The 3-slot ring decodes ahead: by the time the first batch is consumed
    # the workers may have decoded up to 3 batches (4 items each), so the
    # error counter reads 4..12 depending on scheduling — an exact ==4 here
    # was a timing flake (first seen when a cold compile cache slowed the
    # consumer enough for the ring to fill).
    errs = it.decode_errors()
    assert 4 <= errs <= 12, errs
    it.close()


def test_imagefolder_native_toggle(tmp_path):
    import tensorflow as tf

    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset

    rng = np.random.default_rng(1)
    for cls in ("n01", "n02"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = rng.integers(0, 256, size=(48, 56, 3)).astype(np.uint8)
            with open(d / f"{cls}_{i}.JPEG", "wb") as f:
                f.write(tf.io.encode_jpeg(img).numpy())

    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path), image_size=32,
                     global_batch_size=4, shuffle_buffer=8)
    ds = build_dataset(cfg, "train", seed=0)
    assert isinstance(ds, NativeJpegTrainIterator)
    b = next(ds)
    assert b["image"].shape == (4, 32, 32, 3)
    assert set(b["label"].tolist()) <= {0, 1}
    ds.close()

    import dataclasses
    cfg_tf = dataclasses.replace(cfg, native_jpeg=False)
    ds_tf = build_dataset(cfg_tf, "train", seed=0)
    assert not isinstance(ds_tf, NativeJpegTrainIterator)
    b = next(ds_tf)
    assert b["image"].shape == (4, 32, 32, 3)
