"""Graceful preemption (train.handle_preemption): SIGTERM mid-training →
finish the in-flight step, force-save a checkpoint, exit cleanly; a restart
resumes from the preemption step. The SIGKILL (no-grace) variant lives in
tests/test_kill_restart.py."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_lines(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


@pytest.mark.slow
def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    metrics = os.path.join(ckpt, "metrics.jsonl")
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    # CPU-pinned wrapper: the test must pass whether or not the TPU tunnel
    # grant happens to be available (preemption semantics are
    # platform-independent)
    cmd = [sys.executable, os.path.join(REPO, "tests", "preempt_child.py"),
           "--config", "vggf_synthetic",
           "--set", "train.steps=100000",          # runs "forever"
           "--set", "train.log_every=1",
           "--set", f"train.checkpoint_dir={ckpt}",
           "--set", "train.checkpoint_every_steps=1000",
           "--set", "data.global_batch_size=8",
           "--set", "data.image_size=32",
           "--set", "model.num_classes=10"]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 600
        while not any(e.get("event") == "train"
                      for e in _train_lines(metrics)):
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                pytest.fail(f"exited before training started:\n{out[-3000:]}")
            if time.monotonic() > deadline:
                pytest.fail("no train step within 600s")
            time.sleep(0.2)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()

    assert proc.returncode == 0, out.decode(errors="replace")[-3000:]
    events = _train_lines(metrics)
    preempts = [e for e in events if e.get("event") == "preempt"]
    assert len(preempts) == 1 and preempts[0]["checkpointed"]
    stop_step = preempts[0]["step"]
    assert stop_step >= 1

    # the preemption checkpoint is durable and a restart resumes from it
    from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager
    mngr = CheckpointManager(ckpt)
    assert mngr.latest_step() == stop_step

    out2 = subprocess.run(
        cmd[:4] + ["--set", f"train.steps={stop_step + 2}"] + cmd[6:],
        env=env, capture_output=True, timeout=600)
    assert out2.returncode == 0, out2.stdout.decode(errors="replace")[-3000:]
    restores = [e for e in _train_lines(metrics) if e.get("event") == "restore"]
    assert restores and restores[-1]["step"] == stop_step
