"""The unified project-invariant linter (tools/lint) — r15 correctness
tooling plane.

Two halves:
  * mutation tests — every rule is proven to CATCH a seeded violation in a
    minimal fixture tree (a rule that cannot fail is not a rule), plus a
    clean-fixture control where the subtlety warrants it;
  * the committed tree is green — `run_rules(REPO) == []` is the tier-1
    form of the static gate (tools/check.sh runs the same rules from the
    CLI for benches/CI).

The ad-hoc drift guards these rules absorbed keep their original coverage:
tests/test_autotune.py (pins stay bench artifacts) and
tests/test_telemetry.py (counter-table drift) now call the framework — the
seeded-violation proofs for those contracts live HERE.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import RepoContext, all_rules, get_rule, run_rules  # noqa: E402


def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))


def _rule_hits(rule_name, root):
    return [v for v in get_rule(rule_name).check(RepoContext(str(root)))
            if v.rule == rule_name]


# --------------------------------------------------------------- framework
def test_all_rules_registered_and_described():
    rules = all_rules()
    names = {r.name for r in rules}
    assert {"counter-namespace-drift", "scaling-model-isolation",
            "schema-version-stamping", "kill-switch-completeness",
            "config-field-docs", "telemetry-import-isolation"} <= names
    for r in rules:
        assert r.description, r.name


def test_committed_tree_is_green():
    """The static gate itself: every invariant holds on this checkout."""
    violations = run_rules(REPO)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_cli_green_and_lists_rules():
    out = subprocess.run([sys.executable, "-m", "tools.lint"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "lint: OK" in out.stdout
    listed = subprocess.run([sys.executable, "-m", "tools.lint", "--list"],
                            cwd=REPO, capture_output=True, text=True,
                            timeout=120)
    assert "counter-namespace-drift" in listed.stdout


# ------------------------------------------------- counter-namespace-drift
_README_TABLE = """\
    # fixture

    ### Counter namespace

    | namespace | source | names |
    |---|---|---|
    | `foo/` | somewhere | `a`, `stale_entry` |

    ### Next section
"""

# the r22 help-registry half of the contract: fixtures carry a matching
# NAMESPACE_HELP table so the original drift cases stay isolated
_HELP_MODULE_SRC = """\
    NAMESPACE_HELP = {
        "foo": "Fixture counters.",
    }
"""


def test_counter_rule_catches_undocumented_and_stale(tmp_path):
    _write(tmp_path, "README.md", _README_TABLE)
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/metric_help.py",
           _HELP_MODULE_SRC)
    _write(tmp_path, "distributed_vgg_f_tpu/mod.py", """\
        inc("foo/a")
        inc("foo/undocumented_counter")
        inc("nowhere/b")
    """)
    hits = _rule_hits("counter-namespace-drift", tmp_path)
    messages = " | ".join(v.message for v in hits)
    assert "foo/undocumented_counter" in messages     # registered, no row
    assert "nowhere" in messages                      # namespace w/o row
    assert "foo/stale_entry" in messages              # documented, dead
    assert len(hits) == 3


def test_counter_rule_clean_fixture(tmp_path):
    _write(tmp_path, "README.md", _README_TABLE.replace(
        ", `stale_entry`", ""))
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/metric_help.py",
           _HELP_MODULE_SRC)
    _write(tmp_path, "distributed_vgg_f_tpu/mod.py", 'inc("foo/a")\n')
    assert _rule_hits("counter-namespace-drift", tmp_path) == []


def test_counter_rule_catches_help_table_drift_both_ways(tmp_path):
    """r22: the NAMESPACE_HELP registry must cover EXACTLY the README
    counter-table namespaces — a seeded gap is caught in each direction,
    plus the missing/empty-module degenerate cases."""
    readme = _README_TABLE.replace(
        "| `foo/` | somewhere | `a`, `stale_entry` |",
        "| `foo/` | somewhere | `a` |\n"
        "    | `bar/` | somewhere | `b` |")
    code = 'inc("foo/a")\ninc("bar/b")\n'
    # direction 1: README namespace with no help entry
    _write(tmp_path, "README.md", readme)
    _write(tmp_path, "distributed_vgg_f_tpu/mod.py", code)
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/metric_help.py",
           _HELP_MODULE_SRC)
    messages = " | ".join(
        v.message for v in _rule_hits("counter-namespace-drift", tmp_path))
    assert "'bar' has no NAMESPACE_HELP entry" in messages
    # direction 2: help entry for a namespace nothing documents
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/metric_help.py",
           _HELP_MODULE_SRC.replace(
               '"foo": "Fixture counters.",',
               '"foo": "Fixture counters.",\n'
               '    "bar": "Fixture counters.",\n'
               '    "ghost": "Nothing documents me.",'))
    messages = " | ".join(
        v.message for v in _rule_hits("counter-namespace-drift", tmp_path))
    assert "stale help entry" in messages and "ghost" in messages
    # degenerate: empty table, then missing module — each is one loud hit
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/metric_help.py",
           "NAMESPACE_HELP = {}\n")
    messages = " | ".join(
        v.message for v in _rule_hits("counter-namespace-drift", tmp_path))
    assert "not found/empty" in messages
    os.remove(os.path.join(
        tmp_path, "distributed_vgg_f_tpu/telemetry/metric_help.py"))
    messages = " | ".join(
        v.message for v in _rule_hits("counter-namespace-drift", tmp_path))
    assert "metric_help.py missing" in messages


# ------------------------------------------------- scaling-model-isolation
def test_scaling_isolation_catches_runtime_pin_read(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/data/bad.py", """\
        from distributed_vgg_f_tpu.utils.scaling_model import (
            HOST_DECODE_RATE_R9)
        RATE = HOST_DECODE_RATE_R9
    """)
    hits = _rule_hits("scaling-model-isolation", tmp_path)
    assert len(hits) == 2  # names the pin AND imports the model
    assert all(v.path.endswith("data/bad.py") for v in hits)


def test_scaling_isolation_allows_prose_citations(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/data/ok.py", '''\
        """Retires HOST_DECODE_RATE_R* as a runtime input; the
        scaling_model keeps them as bench artifacts."""
        X = 1
    ''')
    assert _rule_hits("scaling-model-isolation", tmp_path) == []


# ------------------------------------------------- schema-version-stamping
def test_schema_rule_catches_literal_stamp(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/utils/logging.py", """\
        from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION
        def rec():
            return {"event": "x", "schema_version": SCHEMA_VERSION}
    """)
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/flight.py", """\
        from distributed_vgg_f_tpu.telemetry import schema
        def box():
            return {"schema_version": schema.SCHEMA_VERSION}
    """)
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/regress.py", """\
        def art():
            return {"schema_version": "9.0"}
    """)
    hits = _rule_hits("schema-version-stamping", tmp_path)
    # regress.py: literal stamp AND therefore no constant-sourced stamp
    assert any("'9.0'" in v.message for v in hits)
    assert any(v.path.endswith("regress.py")
               and "no longer stamps" in v.message for v in hits)
    assert not any(v.path.endswith("logging.py") for v in hits)
    assert not any(v.path.endswith("flight.py") for v in hits)


# ----------------------------------------------- kill-switch-completeness
_COMPLETE_SWITCH = """\
    #if !defined(DVGGF_NO_WIDGET)
    #define DVGG_WIDGET 1
    #else
    #define DVGG_WIDGET 0
    #endif
    int active_widget_kind() {
      const char* env = std::getenv("DVGGF_DECODE_WIDGET");
      return (env && env[0] == '0') ? 0 : DVGG_WIDGET;
    }
    extern "C" {
    int dvgg_x_set_widget(int enable) { return enable; }
    }
"""


def test_kill_switch_rule_accepts_complete_triple(tmp_path):
    _write(tmp_path, "native/x.cc", _COMPLETE_SWITCH)
    assert _rule_hits("kill-switch-completeness", tmp_path) == []


def test_kill_switch_rule_catches_missing_parts(tmp_path):
    # env kill with neither compile-out nor setter
    _write(tmp_path, "native/x.cc", """\
        int active_widget_kind() {
          const char* env = std::getenv("DVGGF_DECODE_WIDGET");
          return (env && env[0] == '0') ? 0 : 1;
        }
    """)
    hits = _rule_hits("kill-switch-completeness", tmp_path)
    assert any("-DDVGGF_NO_WIDGET" in v.message for v in hits)
    assert any("set_widget" in v.message for v in hits)
    # compile-out with no env kill (the vice-versa direction)
    _write(tmp_path, "native/x.cc", """\
        #if !defined(DVGGF_NO_GADGET)
        #define DVGG_GADGET 1
        #endif
        extern "C" {
        int dvgg_x_set_gadget(int enable) { return enable; }
        }
    """)
    hits = _rule_hits("kill-switch-completeness", tmp_path)
    assert any("no matching env kill-switch" in v.message for v in hits)


def test_kill_switch_rule_ignores_tuning_knobs(tmp_path):
    # DVGGF_RESTART_FANOUT-style atoi knob: an env default, not a kill
    _write(tmp_path, "native/x.cc", """\
        int active_fanout() {
          const char* env = std::getenv("DVGGF_WIDGET_FANOUT");
          return env ? std::atoi(env) : 1;
        }
    """)
    assert _rule_hits("kill-switch-completeness", tmp_path) == []

def test_kill_switch_rule_covers_config_plane_switches(tmp_path):
    """r18/r19: every declared config-plane switch
    (data.iterator_state.enabled, mesh.elastic.enabled) needs a boolean
    config field AND a tier-1 test naming the dotted switch — each absence
    is its own violation; a complete set is clean."""
    cc = _COMPLETE_SWITCH
    good_cfg = """\
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class IteratorStateConfig:
            enabled: bool = True

        @dataclass(frozen=True)
        class ElasticConfig:
            enabled: bool = False

        @dataclass(frozen=True)
        class MeshConfig:
            shard_params: bool = False
    """
    good_test = ('SWITCH = "data.iterator_state.enabled"\n'
                 'ELASTIC = "mesh.elastic.enabled"\n'
                 'ZERO3 = "mesh.shard_params"\n')
    _write(tmp_path, "native/x.cc", cc)
    _write(tmp_path, "distributed_vgg_f_tpu/config.py", good_cfg)
    _write(tmp_path, "tests/test_x.py", good_test)
    assert _rule_hits("kill-switch-completeness", tmp_path) == []
    # missing boolean field
    _write(tmp_path, "distributed_vgg_f_tpu/config.py", """\
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class IteratorStateConfig:
            other: int = 1

        @dataclass(frozen=True)
        class ElasticConfig:
            enabled: bool = False
    """)
    hits = _rule_hits("kill-switch-completeness", tmp_path)
    assert any("no boolean field IteratorStateConfig.enabled" in v.message
               for v in hits)
    # field back, but no test names the dotted switch
    _write(tmp_path, "distributed_vgg_f_tpu/config.py", good_cfg)
    _write(tmp_path, "tests/test_x.py", "pass\n")
    hits = _rule_hits("kill-switch-completeness", tmp_path)
    assert any("named by no tier-1 test" in v.message for v in hits)



# -------------------------------------------------------- config-field-docs
def test_config_docs_rule_catches_undocumented_field(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/config.py", """\
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class FooConfig:
            documented: int = 1  # what this knob does
            undocumented_knob: int = 2
    """)
    hits = _rule_hits("config-field-docs", tmp_path)
    assert len(hits) == 1
    assert "FooConfig.undocumented_knob" in hits[0].message


def test_config_docs_rule_accepts_docstring_mention(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/config.py", '''\
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class FooConfig:
            """The knob `threshold` gates the thing."""
            threshold: float = 0.5
    ''')
    assert _rule_hits("config-field-docs", tmp_path) == []


# ----------------------------------------------- telemetry-import-isolation
def test_telemetry_isolation_catches_module_level_heavy_import(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/bad.py", """\
        import numpy as np
        try:
            from distributed_vgg_f_tpu.data import native_jpeg
        except ImportError:
            native_jpeg = None
    """)
    hits = _rule_hits("telemetry-import-isolation", tmp_path)
    assert any("numpy" in v.message for v in hits)
    assert any("native-build trigger" in v.message for v in hits)
    assert len(hits) == 2


def test_telemetry_isolation_allows_lazy_imports(tmp_path):
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/ok.py", """\
        import json

        def snapshot():
            import numpy as np  # lazy: only when a consumer calls in
            return np.zeros(1)
    """)
    assert _rule_hits("telemetry-import-isolation", tmp_path) == []


# -------------------------------------------------------------- CLI plumbing
def test_cli_reports_seeded_violation(tmp_path):
    """End-to-end: the CLI exits 1 and names the rule on a dirty tree."""
    _write(tmp_path, "distributed_vgg_f_tpu/telemetry/bad.py",
           "import numpy\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--repo", str(tmp_path),
         "--rule", "telemetry-import-isolation"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "telemetry-import-isolation" in out.stderr


def test_unknown_rule_fails_loudly():
    with pytest.raises(KeyError):
        get_rule("no-such-rule")
