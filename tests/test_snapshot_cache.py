"""Decoded-crop snapshot cache (data/snapshot_cache.py, r9).

Gates, in dependency order:
 - the SplitMix64 / epoch-shuffle mirror is EXACT against the native
   loader's own stream (labels joined over two epochs — a drifting mirror
   would silently mislabel every warm batch);
 - cold pass captures every item, the iterator flips to warm serving (the
   inner native loader is closed at the switch), and warm labels follow
   the native order;
 - warm pixels are the epoch-0 crops modulo the fresh per-epoch flip
   (checked against a direct decode_single of the mirrored item RNG);
 - the degradation contract: corrupt payloads and source-drifted files
   degrade per item to a sequential re-decode (repairing the store), and
   to the wire's corrupt-image fill only when that decode also fails —
   never stale pixels;
 - capacity is a hard bound (writes refused, cache never turns warm) and
   stale parameter generations are evicted;
 - config wiring: data.snapshot_cache.enabled=true wraps the native train
   iterator via build_dataset; enabled=false returns it untouched
   (byte-identical kill-switch);
 - prefetch/snapshot_{hits,misses,bytes} counters reach the registry.
"""

import os

import numpy as np
import pytest

from distributed_vgg_f_tpu.data.native_jpeg import (
    NativeJpegTrainIterator,
    decode_single_image,
    load_native_jpeg,
)

if load_native_jpeg() is None:  # pragma: no cover — g++/libjpeg exist here
    pytest.skip("native jpeg loader unavailable", allow_module_level=True)

from distributed_vgg_f_tpu.data import snapshot_cache as sc  # noqa: E402

MEAN = np.array([123.68, 116.78, 103.94], np.float32)
STD = np.array([58.393, 57.12, 57.375], np.float32)
N, B, SIZE, SEED = 23, 4, 32, 7


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    from distributed_vgg_f_tpu import telemetry
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """N whole-file JPEG items with DISTINCT labels (the order pin joins
    on them)."""
    from PIL import Image
    root = tmp_path_factory.mktemp("snap_src")
    rng = np.random.default_rng(0)
    files, labels = [], []
    for i in range(N):
        p = str(root / f"im{i}.jpg")
        Image.fromarray(rng.integers(0, 256, size=(64, 56, 3))
                        .astype(np.uint8)).save(p, "JPEG", quality=90)
        files.append(p)
        labels.append(i)
    return files, labels


def _inner(files, labels, dtype="uint8"):
    return NativeJpegTrainIterator(files, labels, B, SIZE, seed=SEED,
                                   mean=MEAN, std=STD, image_dtype=dtype,
                                   num_threads=2)


def _wrap(files, labels, cache_root, dtype="uint8", capacity=1 << 30):
    store = sc.SnapshotStore(str(cache_root), "g1", capacity, N)
    return sc.SnapshotCachingTrainIterator(
        _inner(files, labels, dtype), store, n_items=N, seed=SEED,
        labels=labels, files=files,
        path_idx=np.arange(N, dtype=np.int32),
        offsets=np.full(N, -1, np.int64), lengths=np.zeros(N, np.int64),
        mean=MEAN, std=STD, image_dtype=dtype, pack4=False,
        image_size=SIZE), store


def _cold_batches(n_items=N, batch=B):
    return (n_items + batch - 1) // batch  # covers every epoch-0 item


def test_shuffle_mirror_matches_native_stream(dataset):
    """The Python SplitMix64 epoch shuffle must reproduce the native
    loader's order bit-for-bit across multiple epochs — pinned on labels."""
    files, labels = dataset
    it = _inner(files, labels)
    got = []
    for _ in range(2 * N // B + 2):
        got.extend(int(x) for x in next(it)["label"])
    it.close()
    want = [labels[int(sc.shuffle_indices(N, SEED, g // N)[g % N])]
            for g in range(len(got))]
    assert got == want


def test_cold_capture_then_warm_serving(dataset, tmp_path):
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    for _ in range(_cold_batches()):
        next(w)
    assert store.complete
    assert w._inner_open  # the switch happens on the NEXT draw
    b = _cold_batches()
    batch = next(w)
    assert not w._inner_open  # inner loader closed at the warm switch
    want = [labels[int(sc.shuffle_indices(N, SEED, (b * B + j) // N)
                       [(b * B + j) % N])] for j in range(B)]
    assert [int(x) for x in batch["label"]] == want
    assert batch["image"].shape == (B, SIZE, SIZE, 3)
    assert batch["image"].dtype == np.uint8
    from distributed_vgg_f_tpu import telemetry
    snap = telemetry.get_registry().snapshot()
    assert snap.get("prefetch/snapshot_hits", 0) == B
    assert snap.get("prefetch/snapshot_bytes", 0) == B * SIZE * SIZE * 3
    w.close()


def test_warm_pixels_are_epoch0_crops_with_fresh_flip(dataset, tmp_path):
    """A warm item must be the STORED epoch-0 crop, hflipped exactly when
    the per-(seed, position) flip bit says so — checked against a direct
    decode_single of the mirrored epoch-0 item RNG."""
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    for _ in range(_cold_batches()):
        next(w)
    b = _cold_batches()
    batch = next(w)
    order0 = sc.shuffle_indices(N, SEED, 0)
    inv0 = np.empty_like(order0)
    inv0[order0] = np.arange(N)
    for j in range(B):
        g = b * B + j
        idx = int(sc.shuffle_indices(N, SEED, g // N)[g % N])
        with open(files[idx], "rb") as f:
            data = f.read()
        ref = decode_single_image(
            data, SIZE, MEAN, STD, image_dtype="uint8",
            rng_seed=sc.item_rng_seed(SEED, int(inv0[idx])))
        if sc._flip_bit(SEED, g):
            ref = ref[:, ::-1, :]
        np.testing.assert_array_equal(batch["image"][j], ref)
    w.close()


def test_corrupt_payload_degrades_to_redecode(dataset, tmp_path):
    """Bit-rot in a store entry: crc fails, the entry is evicted, the item
    is re-decoded sequentially (a miss), the store self-heals, and the
    served pixels equal the clean warm pixels."""
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    for _ in range(_cold_batches()):
        next(w)
    b = _cold_batches()
    g0 = b * B
    idx = int(sc.shuffle_indices(N, SEED, g0 // N)[g0 % N])
    off, nbytes = store._entries[idx][0], store._entries[idx][1]
    with open(store._pack_path, "r+b") as f:  # flip one payload byte
        f.seek(off + nbytes // 2)
        v = f.read(1)[0]
        f.seek(off + nbytes // 2)
        f.write(bytes([v ^ 0xFF]))
    batch = next(w)
    from distributed_vgg_f_tpu import telemetry
    snap = telemetry.get_registry().snapshot()
    assert snap.get("prefetch/snapshot_misses", 0) >= 1
    assert store.has(idx)  # repaired
    clean = store.read(idx)
    ref = clean[:, ::-1, :] if sc._flip_bit(SEED, g0) else clean
    np.testing.assert_array_equal(batch["image"][0], ref)
    w.close()


def test_source_payload_change_under_cache_never_serves_stale(dataset,
                                                              tmp_path):
    """The marker-indexed-file-changed case: rewriting a source file flips
    its stat fingerprint, so the cached crop is invalidated and the item is
    decoded from the NEW bytes — never served stale."""
    from PIL import Image
    files, labels = dataset
    files = list(files)
    victim_path = str(tmp_path / "victim.jpg")
    import shutil
    shutil.copy2(files[0], victim_path)
    files[0] = victim_path
    w, store = _wrap(files, labels, tmp_path / "cache")
    for _ in range(_cold_batches()):
        next(w)
    old = store.read(0)
    # replace the payload under the cache
    rng = np.random.default_rng(99)
    Image.fromarray(rng.integers(0, 256, size=(64, 56, 3))
                    .astype(np.uint8)).save(victim_path, "JPEG", quality=90)
    os.utime(victim_path, ns=(12345, 12345))
    w._stat_epoch = -1  # new epoch boundary: stat memo refreshes
    served = None
    for _ in range(3 * N // B + 2):
        batch = next(w)
        labs = [int(x) for x in batch["label"]]
        if labels[0] in labs:
            served = batch["image"][labs.index(labels[0])]
            break
    assert served is not None
    fresh = store.read(0)  # repaired from the new bytes
    assert fresh is not None and not np.array_equal(fresh, old)
    assert (np.array_equal(served, fresh)
            or np.array_equal(served, fresh[:, ::-1, :]))
    w.close()


def test_unreadable_source_mean_fills_like_r9(dataset, tmp_path):
    """When the degraded decode ALSO fails (source gone + entry corrupt),
    the u8 wire mean-fills — the r9 corrupt-image contract."""
    files, labels = dataset
    files = list(files)
    victim_path = str(tmp_path / "gone.jpg")
    import shutil
    shutil.copy2(files[3], victim_path)
    files[3] = victim_path
    w, store = _wrap(files, labels, tmp_path / "cache")
    for _ in range(_cold_batches()):
        next(w)
    next(w)  # latch warm FIRST: an eviction before the latch un-completes
    #          the store and the passthrough would just re-capture the item
    assert not w._inner_open
    store.evict(3)
    os.unlink(victim_path)
    w._stat_epoch = -1
    served = None
    for _ in range(3 * N // B + 2):
        batch = next(w)
        labs = [int(x) for x in batch["label"]]
        if labels[3] in labs:
            served = batch["image"][labs.index(labels[3])]
            break
    assert served is not None
    fill = np.clip(np.round(MEAN), 0, 255).astype(np.uint8)
    assert np.array_equal(served, np.broadcast_to(fill, served.shape))
    assert w.decode_errors() >= 1
    w.close()


def test_capacity_bound_refuses_writes_and_never_warms(dataset, tmp_path):
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path, capacity=6000)
    for _ in range(3 * _cold_batches()):
        next(w)
    assert not store.complete
    assert store.rejected_writes > 0
    assert store.bytes_used <= 6000
    assert w._inner_open  # never switched off the native path
    w.close()


def test_stale_generation_evicted(tmp_path):
    """Eviction takes generations nobody has touched for the grace window
    — and ONLY those: a recently-opened foreign generation may belong to a
    live concurrent job (multi-host shards over a shared data_dir hash to
    distinct keys) and must survive another store's startup."""
    import time
    root = str(tmp_path)
    s1 = sc.SnapshotStore(root, "gen_a", 1 << 20, 4)
    s1.write(0, np.zeros((8, 8, 3), np.uint8), (1, 2, -1, 0))
    s1.close()
    assert os.path.isdir(os.path.join(root, "gen_a"))
    sc.SnapshotStore(root, "gen_live", 1 << 20, 4)
    # gen_a is recent: retained (the shared-root live-cache contract)
    assert os.path.isdir(os.path.join(root, "gen_a"))
    dead = time.time() - sc.SnapshotStore._EVICT_GRACE_S - 60
    os.utime(os.path.join(root, "gen_a"), (dead, dead))
    sc.SnapshotStore(root, "gen_b", 1 << 20, 4)
    assert not os.path.isdir(os.path.join(root, "gen_a"))
    assert os.path.isdir(os.path.join(root, "gen_live"))


def test_persistent_cache_serves_warm_from_batch_zero(dataset, tmp_path):
    """A complete store left by a previous run: the next run's iterator
    never opens a single JPEG (warm from batch 0 — the cross-run win)."""
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    for _ in range(_cold_batches()):
        next(w)
    assert store.complete
    w.close()
    w2, _ = _wrap(files, labels, tmp_path)
    first = next(w2)
    assert not w2._inner_open  # closed on the first draw: fully warm
    want = [labels[int(sc.shuffle_indices(N, SEED, 0)[j])] for j in range(B)]
    assert [int(x) for x in first["label"]] == want
    w2.close()


def test_restore_state_seeks_in_warm_region(dataset, tmp_path):
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    for _ in range(_cold_batches()):
        next(w)
    w.close()
    w2, _ = _wrap(files, labels, tmp_path)
    step = 2 * (N // B) + 1
    assert w2.restore_state(step)
    batch = next(w2)
    want = [labels[int(sc.shuffle_indices(N, SEED, (step * B + j) // N)
                       [(step * B + j) % N])] for j in range(B)]
    assert [int(x) for x in batch["label"]] == want
    assert not w2.restore_state(0)  # too late after the first draw
    w2.close()


def test_host_wire_bf16_round_trip(dataset, tmp_path):
    """Host-normalize wires go through the store too: bf16 payloads
    round-trip bit-exactly (stored dtype tag resolves via ml_dtypes)."""
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path, dtype="bfloat16")
    cold = [next(w) for _ in range(_cold_batches())]
    assert store.complete
    batch = next(w)
    import ml_dtypes
    assert batch["image"].dtype == np.dtype(ml_dtypes.bfloat16)
    assert cold[0]["image"].dtype == batch["image"].dtype
    w.close()


def test_build_dataset_config_wiring(dataset, tmp_path):
    """data.snapshot_cache.enabled=true wraps the native train iterator;
    the default (disabled) returns it untouched — the kill-switch is a
    structural no-op, byte-identical by construction."""
    from PIL import Image
    from distributed_vgg_f_tpu.config import DataConfig, SnapshotCacheConfig
    from distributed_vgg_f_tpu.data import build_dataset
    root = tmp_path / "imagenet" / "train" / "n00000001"
    os.makedirs(root)
    rng = np.random.default_rng(1)
    for i in range(8):
        Image.fromarray(rng.integers(0, 256, size=(48, 40, 3))
                        .astype(np.uint8)).save(
            str(root / f"{i}.JPEG"), "JPEG", quality=90)
    base = dict(name="imagenet", data_dir=str(tmp_path / "imagenet"),
                image_size=32, global_batch_size=4, shuffle_buffer=8,
                native_threads=1)
    off = build_dataset(DataConfig(**base), "train", seed=3)
    assert isinstance(off, NativeJpegTrainIterator)
    off.close()
    cfg = DataConfig(**base, snapshot_cache=SnapshotCacheConfig(
        enabled=True, dir=str(tmp_path / "snapcache")))
    on = build_dataset(cfg, "train", seed=3)
    assert isinstance(on, sc.SnapshotCachingTrainIterator)
    a = next(on)   # cold batch rides the wrapped native loader
    off2 = build_dataset(DataConfig(**base), "train", seed=3)
    b = next(off2)
    np.testing.assert_array_equal(np.asarray(a["image"], np.float32),
                                  np.asarray(b["image"], np.float32))
    np.testing.assert_array_equal(a["label"], b["label"])
    on.close()
    off2.close()


def test_prefetch_accepts_wrapper_unless_ring_armed(dataset, tmp_path):
    """The wrapper honors the r7 buffer-ownership contract: fresh arrays
    by default (device prefetch may keep references), refusal only once
    the bench arms the reuse ring."""
    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    assert not w.reuses_output_buffers
    w.enable_output_buffer_reuse(3)
    assert w.reuses_output_buffers
    w.close()


# ------------------------------------------------------------------- schema
def test_schema_validates_snapshot_and_restart_receipts():
    """ISSUE 6 satellite: the r9 bench rows — `restart_receipt` on decode
    rows, `mode=decode_bench_snapshot` warm/cold rows — are schema-checked
    so a malformed committed artifact fails tier-1, not a reader."""
    from distributed_vgg_f_tpu.telemetry.schema import validate_bench_artifact
    good = {"metric": "m", "value": 1000.0, "layouts": [
        {"wire": "u8",
         "restart_receipt": {"images": 10, "marker_absent": 0,
                             "segments_used": 40, "segments_skipped": 20,
                             "engaged_fraction": 1.0,
                             "segments_skipped_fraction": 1 / 3}},
        {"mode": "decode_bench_snapshot",
         "warm_images_per_sec_per_core": 2000.0,
         "cold_images_per_sec_per_core": 900.0,
         "snapshot": {"hits": 768, "misses": 0, "bytes_served": 10,
                      "items": 256, "hit_rate": 1.0}}]}
    assert validate_bench_artifact(good) == []
    bad = {"metric": "m", "value": 1000.0, "layouts": [
        {"restart_receipt": {"images": -1, "engaged_fraction": 1.5}},
        {"mode": "decode_bench_snapshot",
         "warm_images_per_sec_per_core": 0.0,
         "snapshot": {"hits": -2, "hit_rate": 2.0}}]}
    errors = validate_bench_artifact(bad)
    assert any("'images'" in e for e in errors)
    assert any("engaged_fraction" in e for e in errors)
    assert any("warm_images_per_sec_per_core" in e for e in errors)
    assert any("'hits'" in e for e in errors)
    assert any("hit_rate" in e for e in errors)
    # a snapshot row without its receipt object is itself an error
    errors = validate_bench_artifact(
        {"metric": "m", "value": 1.0,
         "layouts": [{"mode": "decode_bench_snapshot"}]})
    assert any("snapshot" in e for e in errors)


def test_warm_epoch_attributed_compute_bound_from_real_spans(dataset,
                                                            tmp_path):
    """ISSUE 8 satellite: a snapshot-cache WARM epoch (hit rate 1.0) must
    come back compute_bound — not infeed_bound — from the stall attributor,
    driven by REAL spans recorded around the warm iterator (the trainer's
    feed-path instrumentation, op-for-op via instrument_iterator) with the
    device's share of the window simulated by a sleep. Pins that PR 6's
    prefetch/snapshot_* counters and the warm serve path actually feed the
    PR 4 attributor — and that libjpeg really never ran (decode/images
    flat across the warm window)."""
    import time

    from distributed_vgg_f_tpu import telemetry

    files, labels = dataset
    w, store = _wrap(files, labels, tmp_path)
    for _ in range(_cold_batches()):
        next(w)
    next(w)  # latch warm (inner loader closed)
    assert store.complete and not w._inner_open

    reg = telemetry.get_registry()
    reg.delta("warm_window")  # baseline: only the warm window below counts
    decode_before = reg.snapshot().get("decode/images", 0)
    it = telemetry.instrument_iterator(w)
    attributor = telemetry.StallAttributor(
        registry=reg, recorder=telemetry.get_recorder())
    t0 = time.monotonic_ns()
    for _ in range(6):
        next(it)            # real warm serve, really-timed infeed spans
        time.sleep(0.02)    # the device's share of the window
    t1 = time.monotonic_ns()
    w.close()

    verdict = attributor.window_from_spans(t0, t1)
    assert verdict["verdict"] == "compute_bound", verdict
    assert verdict["infeed_fraction"] < 0.25

    counters = reg.delta("warm_window")
    hits = counters.get("prefetch/snapshot_hits", 0)
    misses = counters.get("prefetch/snapshot_misses", 0)
    assert hits == 6 * B and misses == 0          # hit rate 1.0
    assert counters.get("prefetch/snapshot_bytes", 0) == \
        6 * B * SIZE * SIZE * 3
    # the entropy decoder never ran during the warm window
    assert reg.snapshot().get("decode/images", 0) == decode_before
