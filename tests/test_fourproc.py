"""Four-OS-process distributed tests (VERDICT r2 #7): uneven-shard exact
eval with a ZERO-data host, decode-error allgather with mostly-zero
contributions, and SIGTERM stop-consensus landing on a middle rank — the
N>2 edge-room the two-process tests cannot cover. Real processes, Gloo CPU
collectives, one combined child run (tests/fourproc_child.py)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "fourproc_child.py")
N = 4


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_four_process_training_eval_errors_preemption(tmp_path):
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    outs = [str(tmp_path / f"result_{i}.json") for i in range(N)]
    jsonl = str(tmp_path / "metrics.jsonl")
    ckpt = str(tmp_path / "ckpt")
    procs = [subprocess.Popen(
        [sys.executable, CHILD, str(port), str(N), str(i), outs[i], ckpt,
         jsonl],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(N)]
    try:
        deadline = time.monotonic() + 900
        # EVERY rank must be stepping in phase D (its SIGTERM handler is then
        # installed) before the signal is sent — a single-rank sentinel races
        sentinels = [o + ".stepped" for o in outs]
        while not all(os.path.exists(s) for s in sentinels):
            if any(p.poll() is not None for p in procs):
                dumps = [p.stdout.read().decode(errors="replace")
                         for p in procs if p.poll() is not None]
                pytest.fail("child exited before phase D:\n"
                            + dumps[0][-3000:])
            if time.monotonic() > deadline:
                pytest.fail("phase D not reached within 900s")
            time.sleep(0.2)
        # SIGTERM a MIDDLE rank (2): consensus must stop ranks 0,1,3 too
        procs[2].send_signal(signal.SIGTERM)
        t_signal = time.monotonic()
        outputs = [p.communicate(timeout=600)[0].decode(errors="replace")
                   for p in procs]
        stop_latency = time.monotonic() - t_signal
        for i, p in enumerate(procs):
            assert p.returncode == 0, "\n\n".join(
                f"--- rank {j} (rc={q.returncode}) ---\n{outputs[j][-2000:]}"
                for j, q in enumerate(procs))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = [json.load(open(o)) for o in outs]
    # A: synchronous DP over 4 ranks — bit-identical params
    assert all(r["step"] == 2 for r in results)
    assert len({r["fingerprint"] for r in results}) == 1
    # B: exact eval scored exactly 21+9+0+35 once each, on every rank
    assert all(r["exact_eval_examples"] == 65 for r in results)
    # multi-hop ring over 4 real processes: blocks (and the flash backward's
    # traveling dK/dV accumulators) pass THROUGH intermediate hosts
    assert all(r["ring_ok"] for r in results)
    assert all(r["ring_flash_ok"] for r in results)
    assert all(r["ring_flash_grad_finite"] for r in results)
    # Ulysses all-to-all with every process contributing a distinct
    # sequence+head slice across 4 real processes, forward and backward
    assert all(r["ulysses_ok"] for r in results)
    assert all(r["ulysses_grads_ok"] for r in results)
    # C: rank 0's log shows the cross-host decode-error total (0+3+0+5)
    with open(jsonl) as f:
        events = [json.loads(l) for l in f if l.strip()]
    err_train = [e for e in events if e["event"] == "train"
                 and "data_decode_errors" in e]
    assert err_train and err_train[-1]["data_decode_errors"] == 8
    # D: all four ranks stopped at the same step with the checkpoint durable
    stop_steps = {r["preempt_step"] for r in results}
    assert len(stop_steps) == 1 and results[0]["preempt_step"] >= 1
    assert all(r["latest_ckpt"] == results[0]["preempt_step"]
               for r in results)
    assert stop_latency < 180
    preempts = [e for e in events if e.get("event") == "preempt"]
    assert len(preempts) == 1 \
        and preempts[0]["step"] == results[0]["preempt_step"]
