"""VGG-16 / ResNet-50 / ViT-S/16 shape & param-count tests, plus the sync-BN
cross-replica statistics test on the fake 8-device mesh (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_vgg_f_tpu.config import ModelConfig
from distributed_vgg_f_tpu.models import build_model
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh

from distributed_vgg_f_tpu.parallel.compat import shard_map


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def _init_shapes(name, num_classes, image=224, extra=None):
    model = build_model(ModelConfig(name=name, num_classes=num_classes,
                                    compute_dtype="float32",
                                    extra=extra or {}))
    x = jnp.zeros((2, image, image, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), x, train=False))
    out = jax.eval_shape(lambda v: model.apply(v, x, train=False), variables)
    return variables, out


def test_vgg16_params():
    variables, out = _init_shapes("vgg16", 1000)
    assert out.shape == (2, 1000)
    n = _param_count(variables["params"])
    # Simonyan & Zisserman config D: ~138M
    assert 136e6 < n < 140e6, n


def test_resnet50_params():
    variables, out = _init_shapes("resnet50", 1000)
    assert out.shape == (2, 1000)
    n = _param_count(variables["params"])
    assert 24e6 < n < 27e6, n   # ResNet-50 ≈ 25.6M
    assert "batch_stats" in variables


def test_vit_s16_params():
    variables, out = _init_shapes("vit_s16", 1000)
    assert out.shape == (2, 1000)
    n = _param_count(variables["params"])
    assert 21e6 < n < 23.5e6, n  # ViT-S/16 ≈ 22M


def test_resnet_forward_small():
    model = build_model(ModelConfig(name="resnet50", num_classes=10,
                                    compute_dtype="float32"))
    x = jax.random.normal(jax.random.key(0), (2, 64, 64, 3))
    variables = model.init(jax.random.key(1), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_sync_bn_uses_cross_replica_stats(devices8):
    """With sync-BN, per-replica batches with DIFFERENT statistics must be
    normalized with the GLOBAL mean/var: feeding replica i the constant i,
    global mean is 3.5 — so replica outputs (pre-scale) must be (i - 3.5)/std,
    not 0 (which local BN would give)."""
    model = build_model(ModelConfig(name="resnet50", num_classes=10,
                                    compute_dtype="float32"))
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    x_global = jnp.concatenate(
        [jnp.full((1, 32, 32, 3), float(i)) for i in range(8)])
    variables = model.init(jax.random.key(0), x_global[:1], train=False)

    def fwd(v, xs):
        out, updated = model.apply(v, xs, train=True,
                                   mutable=["batch_stats"])
        return updated["batch_stats"]

    f = shard_map(fwd, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
                  check_vma=False)
    new_stats = jax.jit(f)(variables, x_global)
    # running mean of the first BN: updated toward the global per-channel mean
    # of conv output. With sync-BN all replicas agree (out_specs=P() would fail
    # to even be consistent otherwise); check it moved off init zero.
    mean0 = np.asarray(
        jax.tree_util.tree_leaves(new_stats)[0])
    assert np.any(mean0 != 0.0)


def test_sync_bn_matches_global_batch(devices8):
    """BN train-mode output on 8 shards with sync must equal single-device BN
    on the concatenated batch — direct cross-replica mean/var check using a
    bare BatchNorm layer."""
    import flax.linen as nn

    bn = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5,
                      axis_name="data")
    x_global = jax.random.normal(jax.random.key(0), (16, 4))
    variables = bn.init(jax.random.key(1), x_global)

    # reference: plain BN over the whole batch (no axis_name binding needed
    # when values are identical — compute directly)
    mean = x_global.mean(0)
    var = x_global.var(0)
    want = (x_global - mean) / jnp.sqrt(var + 1e-5)

    mesh = build_mesh(MeshSpec(("data",), (8,)))

    def fwd(v, xs):
        out, _ = bn.apply(v, xs, mutable=["batch_stats"])
        return out

    f = shard_map(fwd, mesh=mesh, in_specs=(P(), P("data")),
                  out_specs=P("data"), check_vma=False)
    got = jax.jit(f)(variables, x_global)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4,
                               atol=1e-5)


def test_vit_trains_one_step(devices8):
    """ViT under the same DP trainer — config swap, not fork (SURVEY.md §7)."""
    import dataclasses
    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, OptimConfig, TrainConfig)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    import io

    cfg = ExperimentConfig(
        name="vit_tiny_test",
        model=ModelConfig(name="vit_s16", num_classes=10, dropout_rate=0.1,
                          compute_dtype="float32",
                          extra={"hidden_dim": 32, "depth": 2, "num_heads": 2,
                                 "mlp_dim": 64, "patch_size": 8}),
        optim=OptimConfig(base_lr=1e-3, reference_batch_size=16,
                          schedule="cosine", warmup_epochs=0.0),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        train=TrainConfig(steps=2, seed=0),
    )
    tr = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = tr.init_state()
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10, seed=0)
    batch = tr.shard(next(ds))
    state, metrics = tr.train_step(state, batch, tr.base_rng())
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_resnet_trains_one_step_sync_bn(devices8):
    import io
    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, OptimConfig, TrainConfig)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = ExperimentConfig(
        name="resnet_tiny_test",
        model=ModelConfig(name="resnet50", num_classes=10,
                          compute_dtype="float32",
                          extra={"stage_sizes": (1, 1, 1, 1)}),
        optim=OptimConfig(base_lr=0.1, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        train=TrainConfig(steps=2, seed=0),
    )
    tr = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = tr.init_state()
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10, seed=0)
    batch = tr.shard(next(ds))
    old_stats = jax.device_get(state.batch_stats)
    state, metrics = tr.train_step(state, batch, tr.base_rng())
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    # batch_stats must have been updated by the train step
    new_stats = jax.device_get(state.batch_stats)
    diffs = [not np.allclose(a, b) for a, b in
             zip(jax.tree_util.tree_leaves(old_stats),
                 jax.tree_util.tree_leaves(new_stats))]
    assert any(diffs)


@pytest.mark.parametrize("layout", ["head_major", "token_major", "flash",
                                    "auto"])
def test_fused_attention_matches_flax_mha(layout):
    """FusedSelfAttention (one QKV GEMM) must reproduce
    nn.MultiHeadDotProductAttention exactly given repacked params — the
    fusion is a layout change, not a math change. Both internal layouts
    (head-major single-transpose and token-major split) share one param
    tree, so checkpoints are layout-portable."""
    import flax.linen as nn

    from distributed_vgg_f_tpu.models.vit import FusedSelfAttention

    B, T, D, H = 2, 17, 48, 6
    x = jax.random.normal(jax.random.key(0), (B, T, D), jnp.float32)

    ref = nn.MultiHeadDotProductAttention(
        num_heads=H, dtype=jnp.float32, param_dtype=jnp.float32,
        dropout_rate=0.0, deterministic=True)
    ref_vars = ref.init(jax.random.key(1), x, x)
    ref_out = ref.apply(ref_vars, x, x)

    p = ref_vars["params"]
    fused_params = {"params": {
        "qkv": {
            "kernel": jnp.stack([p["query"]["kernel"], p["key"]["kernel"],
                                 p["value"]["kernel"]], axis=1),
            "bias": jnp.stack([p["query"]["bias"], p["key"]["bias"],
                               p["value"]["bias"]], axis=0),
        },
        "out": p["out"],
    }}
    from distributed_vgg_f_tpu.ops import flash_attention
    fused = FusedSelfAttention(num_heads=H, dropout_rate=0.0,
                               compute_dtype=jnp.float32, layout=layout)
    old_interpret = flash_attention.INTERPRET
    flash_attention.INTERPRET = True   # CPU: run the kernel interpreted
    try:
        fused_out = fused.apply(fused_params, x, train=False)
    finally:
        flash_attention.INTERPRET = old_interpret
    np.testing.assert_allclose(np.asarray(fused_out), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)


def test_resnet_space_to_depth_stem_matches_conv7():
    """stem='space_to_depth' (the r3-trace targeted experiment, VERDICT r3
    #5): the 2x2-packed 4x4/1 stem must equal the 7x7/2 pad-3 conv on the
    SAME logical (7,7,3,64) parameters — the zero-padded leading tap only
    ever multiplies padding — and the param tree must be checkpoint-
    compatible between the two stems."""
    from distributed_vgg_f_tpu.models.resnet import StemConv

    x = np.random.default_rng(0).standard_normal((2, 32, 32, 3)).astype(
        np.float32)
    ref = StemConv(8, jnp.float32, stem="conv7")
    s2d = StemConv(8, jnp.float32, stem="space_to_depth")
    variables = ref.init(jax.random.key(1), jnp.asarray(x))
    assert variables["params"]["kernel"].shape == (7, 7, 3, 8)
    out_ref = ref.apply(variables, jnp.asarray(x))
    out_s2d = s2d.apply(variables, jnp.asarray(x))     # same params
    assert out_ref.shape == out_s2d.shape == (2, 16, 16, 8)
    np.testing.assert_allclose(np.asarray(out_s2d), np.asarray(out_ref),
                               rtol=1e-5, atol=1e-5)
    # odd spatial size: silently falls back to the plain conv
    x_odd = jnp.asarray(x[:, :31, :31])
    np.testing.assert_allclose(
        np.asarray(s2d.apply(variables, x_odd)),
        np.asarray(ref.apply(variables, x_odd)), rtol=1e-5, atol=1e-5)
    # bad value raises at call time (bench.py's eval_shape validation path)
    with pytest.raises(ValueError, match="unknown resnet stem"):
        StemConv(8, jnp.float32, stem="conv7x7").init(
            jax.random.key(0), jnp.asarray(x))
    # the full model accepts the extra and keeps its param count
    variables_full, out = _init_shapes("resnet50", 1000,
                                       extra={"stem": "space_to_depth"})
    assert out.shape == (2, 1000)
    assert _param_count(variables_full["params"]) == 25_557_032


def test_fused_attention_gemms_stay_bf16():
    """Under bf16 compute, every attention GEMM must run in bf16 — a
    strongly-typed scalar in the q-scaling once silently promoted QK^T to
    fp32 (code-review r3), defeating the MXU fusion the module exists for."""
    from distributed_vgg_f_tpu.models.vit import FusedSelfAttention

    x = jnp.zeros((2, 17, 48), jnp.bfloat16)
    fused = FusedSelfAttention(num_heads=6, dropout_rate=0.0,
                               compute_dtype=jnp.bfloat16)
    variables = fused.init(jax.random.key(0), x, train=False)

    closed = jax.make_jaxpr(
        lambda v, y: fused.apply(v, y, train=False))(variables, x)

    def dots(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dot_general":
                yield eqn
            for v in eqn.params.values():
                for item in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(item, "jaxpr"):
                        yield from dots(item.jaxpr)
                    elif hasattr(item, "eqns"):
                        yield from dots(item)

    dtypes = {e.outvars[0].aval.dtype for e in dots(closed.jaxpr)}
    assert dtypes == {np.dtype(jnp.bfloat16)}, dtypes


def test_auto_layout_with_attention_dropout_rejected_eagerly():
    """ADVICE r5: layout='auto' + attention dropout > 0 only failed at call
    time, and only once T crossed the flash threshold — a length-dependent
    error for a configuration that is wrong at build time (flash never
    materializes the attention weights). Both module altitudes must reject
    at CONSTRUCTION, naming the configured layout."""
    import jax.numpy as jnp
    from distributed_vgg_f_tpu.models.vit import FusedSelfAttention, ViT

    for layout in ("auto", "flash"):
        with pytest.raises(ValueError, match=layout):
            FusedSelfAttention(num_heads=2, dropout_rate=0.1,
                               compute_dtype=jnp.float32, layout=layout)
        # model altitude: rejected at build_model time, before any trace
        with pytest.raises(ValueError, match=layout):
            build_model(ModelConfig(
                name="vit_s16", num_classes=10,
                extra={"attention_layout": layout,
                       "attention_dropout_rate": 0.1}))
    # dropout 0 stays valid for both, and einsum layouts keep dropout
    FusedSelfAttention(num_heads=2, dropout_rate=0.0,
                       compute_dtype=jnp.float32, layout="auto")
    FusedSelfAttention(num_heads=2, dropout_rate=0.1,
                       compute_dtype=jnp.float32, layout="head_major")
    build_model(ModelConfig(name="vit_s16", num_classes=10,
                            extra={"attention_layout": "auto"}))


def test_attention_auto_layout_resolves_by_length(monkeypatch):
    """attention_layout="auto" is the measured regime rule as code: the
    einsum path below ATTENTION_AUTO_FLASH_THRESHOLD tokens, the flash
    kernel from the threshold up (where XLA's einsum cannot compile).
    Pinned by counting which path's HLO the traced program contains —
    the flash path calls a pallas custom op, the einsum path does not."""
    from distributed_vgg_f_tpu.models import vit as vit_mod
    from distributed_vgg_f_tpu.models.vit import FusedSelfAttention
    from distributed_vgg_f_tpu.ops import flash_attention

    monkeypatch.setattr(vit_mod, "ATTENTION_AUTO_FLASH_THRESHOLD", 64)
    monkeypatch.setattr(flash_attention, "INTERPRET", True)
    mod = FusedSelfAttention(num_heads=2, dropout_rate=0.0,
                             compute_dtype=jnp.float32, layout="auto")

    def jaxpr_for(t):
        x = jnp.zeros((1, t, 16), jnp.float32)
        variables = mod.init(jax.random.key(0), x, train=False)
        return str(jax.make_jaxpr(
            lambda v, a: mod.apply(v, a, train=False))(variables, x))

    short = jaxpr_for(32)    # below threshold -> einsum path
    long = jaxpr_for(64)     # at threshold -> flash path
    assert "softmax" in short or "reduce_max" in short
    assert "flash" in long or "pallas" in long or "custom_vjp" in long
    assert ("pallas" in long) != ("pallas" in short) or         ("custom_vjp" in long and "custom_vjp" not in short)
