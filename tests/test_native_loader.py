"""Native (C++) batch assembler tests: build, determinism, normalization
correctness against numpy, augmentation behavior, throughput smoke."""

import numpy as np
import pytest

from distributed_vgg_f_tpu.data.native_loader import (
    NativeBatchIterator,
    load_native,
)

pytestmark = pytest.mark.skipif(load_native() is None,
                                reason="native toolchain unavailable")


def _dataset(n=64, h=8, w=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, h, w, c)).astype(np.uint8)
    labels = rng.integers(0, 10, size=(n,)).astype(np.int32)
    return images, labels


def test_eval_mode_matches_numpy_normalization():
    images, labels = _dataset()
    mean, std = (10.0, 20.0, 30.0), (2.0, 3.0, 4.0)
    it = NativeBatchIterator(images, labels, 16, train=False, seed=0,
                             mean=mean, std=std)
    batch = next(it)
    # eval mode is sequential from index 0, no augmentation
    want = (images[:16].astype(np.float32) - np.asarray(mean)) / np.asarray(std)
    np.testing.assert_allclose(batch["image"], want, rtol=1e-6)
    np.testing.assert_array_equal(batch["label"], labels[:16])
    it.close()


def test_train_deterministic_same_seed():
    images, labels = _dataset()
    a = NativeBatchIterator(images, labels, 16, train=True, seed=7,
                            mean=(0, 0, 0), std=(1, 1, 1))
    b = NativeBatchIterator(images, labels, 16, train=True, seed=7,
                            mean=(0, 0, 0), std=(1, 1, 1))
    for _ in range(5):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
    a.close(), b.close()


def test_train_different_seed_differs():
    images, labels = _dataset()
    a = NativeBatchIterator(images, labels, 16, train=True, seed=1,
                            mean=(0, 0, 0), std=(1, 1, 1))
    b = NativeBatchIterator(images, labels, 16, train=True, seed=2,
                            mean=(0, 0, 0), std=(1, 1, 1))
    assert not np.array_equal(next(a)["image"], next(b)["image"])
    a.close(), b.close()


def test_train_covers_epoch_and_labels_match_images():
    """Augmentation permutes/crops pixels but each image must keep its own
    label: checked via per-class channel statistics on a labeled-constant
    dataset (image filled with its label value)."""
    n, h, w, c = 40, 8, 8, 3
    labels = np.arange(n, dtype=np.int32) % 10
    images = np.broadcast_to(
        (labels * 20)[:, None, None, None], (n, h, w, c)).astype(np.uint8).copy()
    it = NativeBatchIterator(images, labels, 8, train=True, seed=3,
                             mean=(0, 0, 0), std=(1, 1, 1))
    for _ in range(10):
        batch = next(it)
        # constant images: any crop/flip of a constant image is constant
        per_img = batch["image"].reshape(8, -1)
        assert np.allclose(per_img.min(1), per_img.max(1))
        np.testing.assert_array_equal(per_img[:, 0].astype(np.int32),
                                      batch["label"] * 20)
    it.close()


def test_epoch_reshuffle():
    images, labels = _dataset(n=32)
    it = NativeBatchIterator(images, labels, 16, train=True, seed=0,
                             mean=(0, 0, 0), std=(1, 1, 1))
    epoch1 = [next(it)["label"] for _ in range(2)]
    epoch2 = [next(it)["label"] for _ in range(2)]
    # each epoch visits all 32 examples exactly once
    assert sorted(np.concatenate(epoch1).tolist()) == sorted(labels.tolist())
    assert sorted(np.concatenate(epoch2).tolist()) == sorted(labels.tolist())
    it.close()


def test_cifar10_uses_native_when_available():
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset

    cfg = DataConfig(name="cifar10", data_dir="", image_size=32,
                     global_batch_size=16)
    ds = build_dataset(cfg, "train", seed=0)
    assert isinstance(ds, NativeBatchIterator)
    batch = next(ds)
    assert batch["image"].shape == (16, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    ds.close()
