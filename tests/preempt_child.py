"""CPU-pinned wrapper around train.py for the SIGTERM preemption test.

The test must not depend on the machine's single-grant TPU tunnel being
available (a wedged grant would block the child inside jax.devices() and
time the test out); preemption semantics are platform-independent. The
sitecustomize pins jax_platforms, so the env var alone is not enough —
config.update before any jax use is.
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")

import train  # noqa: E402

if __name__ == "__main__":
    train.main(sys.argv[1:])
