"""Latency-tiered serving (serving/tiers.py + the (model, tier) router,
r23): per-tier bitwise server≡offline parity, the typed unknown-tier 400,
per-(model, tier) batcher isolation, the compacted≡dense int8 equivalence,
the /servingz ladder build receipt, and the kill switch —
serving.tiers.enabled=false pins the server to the r22 fp32-only surface
(non-fp32 engines refused, ?tier= ignored, response/table shapes
unchanged)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import (
    SERVING_TIERS,
    ModelConfig,
    ServingConfig,
    ServingTiersConfig,
)
from distributed_vgg_f_tpu.telemetry import exporter as exporter_mod
from distributed_vgg_f_tpu.telemetry import flight as flight_mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    flight_mod.get_flight().clear()
    yield
    exporter_mod.stop_exporter()
    telemetry.reset()
    flight_mod.get_flight().clear()
    telemetry.configure(enabled=True)


SIZE, CLASSES = 32, 5


def _base_engine(num_classes=CLASSES, size=SIZE, max_batch=4):
    import jax

    from distributed_vgg_f_tpu.models.registry import build_model
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    model = build_model(ModelConfig(name="vggf", num_classes=num_classes,
                                    compute_dtype="float32"))
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, size, size, 3), np.float32),
                        train=False)["params"]
    return PredictEngine(model_name="vggf", model=model, params=params,
                         batch_stats=None, image_size=size,
                         num_classes=num_classes, max_batch=max_batch)


def _student_engine(base):
    import jax

    from distributed_vgg_f_tpu.models.registry import build_model
    from distributed_vgg_f_tpu.serving.tiers import build_student_engine
    smodel = build_model(ModelConfig(name="vggf_student",
                                     num_classes=base.num_classes,
                                     compute_dtype="float32"))
    sparams = smodel.init(jax.random.PRNGKey(1),
                          np.zeros((1, SIZE, SIZE, 3), np.float32),
                          train=False)["params"]
    return build_student_engine(base, student_model=smodel,
                                student_params=sparams)


def _ladder(base):
    from distributed_vgg_f_tpu.serving.tiers import (build_bf16_engine,
                                                     build_int8_engine)
    return {"fp32": base,
            "bf16": build_bf16_engine(base),
            "int8": build_int8_engine(
                base, tiers_cfg=ServingTiersConfig(enabled=True)),
            "student": _student_engine(base)}


def _images(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n, SIZE, SIZE, 3)).astype(np.uint8)


def _post(port, model, image, query="", expect_error=False):
    url = f"http://127.0.0.1:{port}/v1/predict/{model}{query}"
    req = urllib.request.Request(url, data=image.tobytes(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        if not expect_error:
            raise
        return e.code, json.loads(e.read())


def _tier_server():
    from distributed_vgg_f_tpu.serving.server import PredictServer
    cfg = ServingConfig(enabled=True, max_batch=4, buckets=(1, 2, 4),
                        controller=False, warmup=False,
                        tiers=ServingTiersConfig(enabled=True))
    server = PredictServer(cfg)
    base = _base_engine()
    ladder = _ladder(base)
    for eng in ladder.values():
        server.add_engine(eng)
    return server, ladder


# ----------------------------------------------------------------- builders

def test_tier_engines_agree_with_fp32_within_tolerance():
    """Every rung still computes (approximately) the same classifier —
    bf16/int8 are precision variants, not different functions."""
    base = _base_engine()
    ladder = _ladder(base)
    imgs = _images(3)
    ref, _ = base.run(imgs)
    for tier in ("bf16", "int8"):
        probs, _ = ladder[tier].run(imgs)
        assert probs.shape == ref.shape
        assert np.max(np.abs(probs - ref)) < 0.05, tier
        assert ladder[tier].tier == tier
    # the student is a DIFFERENT architecture — same contract, own math
    sprobs, _ = ladder["student"].run(imgs)
    assert sprobs.shape == ref.shape
    assert ladder["student"].served_by == "vggf_student"


def test_int8_compacted_equals_dense_reference_on_calibration_range():
    """The elision claim: dropping sub-LSB channels is EXACT int8
    semantics on calibration-range inputs — the compacted engine matches
    dense int8 emulation with the same scales (allclose, not bitwise:
    the compacted GEMM sums in a different order)."""
    import jax
    import jax.numpy as jnp

    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    from distributed_vgg_f_tpu.serving import tiers as tiers_mod
    base = _base_engine()
    eng = tiers_mod.build_int8_engine(
        base, tiers_cfg=ServingTiersConfig(enabled=True))
    calib = eng.calibration
    # some channels actually elided, or the test pins nothing
    assert sum(calib.widths.values()) > sum(
        len(k) for k in calib.keep.values())
    # calibration-range inputs: the same procedural stream family
    imgs = tiers_mod.calibration_images(SIZE, batches=1, batch_size=4,
                                        seed=99)
    compacted, _ = eng.run(imgs)
    finish = make_device_finish(base._mean, base._std)
    trunk = tiers_mod._make_trunk(base._model, {"params": base._params},
                                  finish)
    heads = tiers_mod.dense_int8_reference(base._params, calib)
    logits = heads(trunk(jnp.asarray(imgs)))
    dense = np.asarray(jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
    assert np.allclose(compacted, dense, atol=1e-4)
    # the receipt round-trips through JSON (the committed artifact shape)
    receipt = json.loads(json.dumps(calib.receipt()))
    assert set(receipt["scales"]) == {"fc6", "fc7", "fc8"}
    assert all(receipt["kept"][k] <= receipt["widths"][k]
               for k in receipt["kept"])


def test_int8_refuses_non_vggf_heads():
    from distributed_vgg_f_tpu.serving import tiers as tiers_mod
    with pytest.raises(ValueError, match="head stack"):
        tiers_mod._split_params({"conv1": {"kernel": np.zeros((1, 1))}})


def test_serving_only_descriptor_excluded_from_training_zoo():
    """vggf_student serves, it never trains: zoo_model_names() (presets,
    training grids, the slow zoo parity matrix) must not see it; the
    descriptor table itself must."""
    from distributed_vgg_f_tpu.models.ingest import (INGEST_DESCRIPTORS,
                                                     zoo_model_names)
    assert "vggf_student" in INGEST_DESCRIPTORS
    assert INGEST_DESCRIPTORS["vggf_student"].serving_only
    assert "vggf_student" not in zoo_model_names()
    assert "vggf_student" in zoo_model_names(include_serving_only=True)
    # the schema literal and the config literal stay in lockstep with the
    # serving module (leaf-module duplicates, drift pinned here)
    from distributed_vgg_f_tpu.serving.tiers import TIERS
    from distributed_vgg_f_tpu.telemetry.schema import _SERVING_TIERS
    assert tuple(TIERS) == tuple(_SERVING_TIERS) == tuple(SERVING_TIERS)


# ------------------------------------------------------------------- router

def test_per_tier_server_bitwise_equals_offline():
    """The r14 parity contract, per rung: what the server answers on
    /v1/predict/<model>?tier=<t> is bitwise what THAT tier's offline
    engine.run produces — same executables, same bits."""
    server, ladder = _tier_server()
    port = server.start()
    imgs = _images(len(SERVING_TIERS), seed=3)
    try:
        for i, tier in enumerate(SERVING_TIERS):
            status, body = _post(port, "vggf", imgs[i],
                                 query=f"?tier={tier}&k={CLASSES}")
            assert status == 200 and body["tier"] == tier
            offline, bucket = ladder[tier].run(imgs[i:i + 1])
            assert body["bucket"] == bucket
            served = {r["class"]: r["prob"] for r in body["top_k"]}
            for cls, prob in enumerate(offline[0]):
                # exact equality — full-precision probs over the wire
                assert served[cls] == float(prob), (tier, cls)
    finally:
        server.close()


def test_unknown_tier_is_typed_400_naming_the_ladder():
    server, _ = _tier_server()
    port = server.start()
    try:
        status, body = _post(port, "vggf", _images(1)[0],
                             query="?tier=fp64", expect_error=True)
        assert status == 400
        assert body["error"] == "bad_request"
        assert body["tier"] == "fp64"
        assert body["tiers"] == list(SERVING_TIERS)
    finally:
        server.close()


def test_batcher_isolation_per_model_tier():
    """Batches never mix tiers: each (model, tier) key owns its batcher,
    and traffic to one rung leaves the others' admission state untouched."""
    server, _ = _tier_server()
    port = server.start()
    imgs = _images(4, seed=5)
    try:
        for _ in range(2):
            _post(port, "vggf", imgs[0], query="?tier=int8")
        _post(port, "vggf", imgs[1], query="?tier=fp32")
        batchers = server._batchers
        assert set(batchers) == {("vggf", t) for t in SERVING_TIERS}
        assert len({id(b) for b in batchers.values()}) == len(SERVING_TIERS)
        by_tier = {t: batchers[("vggf", t)].describe()
                   for t in SERVING_TIERS}
        assert by_tier["int8"]["completed_total"] == 2
        assert by_tier["fp32"]["completed_total"] == 1
        assert by_tier["bf16"]["completed_total"] == 0
        assert by_tier["student"]["completed_total"] == 0
        assert by_tier["int8"]["tier"] == "int8"
        reg = telemetry.get_registry()
        assert reg.counter_value("serving/tier_requests_int8") == 2
        assert reg.counter_value("serving/tier_requests_fp32") == 1
        assert reg.counter_value("serving/tier_requests_student") == 0
    finally:
        server.close()


def test_models_table_and_servingz_report_the_ladder():
    server, _ = _tier_server()
    port = server.start()
    try:
        # force one compile so the build receipt has an entry
        _post(port, "vggf", _images(1)[0], query="?tier=int8")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/models", timeout=30) as r:
            table = json.loads(r.read())["models"]
        row = table["vggf"]
        # r22 row shape intact (the zoo routing contract) + the ladder
        assert row["ingest"]["wire"] == "u8"
        assert sorted(row["tiers"]) == sorted(SERVING_TIERS)
        assert row["tiers"]["student"]["served_by"] == "vggf_student"
        payload = server.servingz_payload()
        assert payload["tier_default"] == "fp32"
        ladder = payload["ladder"]["vggf"]
        assert sorted(ladder) == sorted(SERVING_TIERS)
        int8_row = ladder["int8"]
        # the build receipt: per-bucket compile seconds + HBM estimate
        assert int8_row["compile_s"] and all(
            s > 0 for s in int8_row["compile_s"].values())
        assert int8_row["hbm_estimate_bytes"] > 0
        # int8 heads resident as int8: estimate strictly below fp32's
        assert int8_row["hbm_estimate_bytes"] < \
            ladder["fp32"]["hbm_estimate_bytes"]
        assert payload["models"]["vggf"]["tiers"]["int8"]["admission"][
            "completed_total"] == 1
    finally:
        server.close()


def test_default_tier_routes_tier_default():
    from distributed_vgg_f_tpu.serving.server import PredictServer
    cfg = ServingConfig(enabled=True, max_batch=4, buckets=(1, 2, 4),
                        controller=False, warmup=False,
                        tier_default="student",
                        tiers=ServingTiersConfig(enabled=True))
    server = PredictServer(cfg)
    base = _base_engine()
    server.add_engine(base)
    server.add_engine(_student_engine(base))
    port = server.start()
    try:
        status, body = _post(port, "vggf", _images(1)[0])
        assert status == 200 and body["tier"] == "student"
        # explicit ?tier= still wins over the default
        status, body = _post(port, "vggf", _images(1)[0],
                             query="?tier=fp32")
        assert status == 200 and body["tier"] == "fp32"
        # explicit ask for an unregistered rung: typed 400, NOT a silent
        # substitution
        status, body = _post(port, "vggf", _images(1)[0],
                             query="?tier=int8", expect_error=True)
        assert status == 400 and body["tiers"] == ["fp32", "student"]
    finally:
        server.close()


# -------------------------------------------------------------- kill switch

def test_kill_switch_tiers_disabled_is_r22_fp32_surface():
    """serving.tiers.enabled=false (the default) pins the r22 server:
    non-fp32 engines are REFUSED at registration (the disabled server
    cannot even hold a ladder — lowered-surface identity), `?tier=` is
    ignored exactly as r22 ignored unknown query params, and the
    response/table/servingz shapes carry no tier keys."""
    from distributed_vgg_f_tpu.serving.server import PredictServer
    from distributed_vgg_f_tpu.serving.tiers import build_bf16_engine
    cfg = ServingConfig(enabled=True, max_batch=4, buckets=(1, 2, 4),
                        controller=False, warmup=False)
    assert cfg.tiers.enabled is False  # the committed default
    server = PredictServer(cfg)
    base = _base_engine()
    server.add_engine(base)
    with pytest.raises(ValueError, match="serving.tiers.enabled"):
        server.add_engine(build_bf16_engine(base))
    assert set(server._engines) == {("vggf", "fp32")}
    port = server.start()
    img = _images(1)[0]
    try:
        # ?tier= ignored: routed to fp32, bitwise the fp32 answer, and
        # the body is the r22 shape (no "tier" key)
        status, body = _post(port, "vggf", img,
                             query=f"?tier=int8&k={CLASSES}")
        assert status == 200
        assert set(body) == {"model", "top_k", "bucket", "latency_ms"}
        offline, _ = base.run(img[None])
        served = {r["class"]: r["prob"] for r in body["top_k"]}
        assert all(served[c] == float(p)
                   for c, p in enumerate(offline[0]))
        # even a GARBAGE tier value is ignored, not a 400 — r22 routing
        status, _ = _post(port, "vggf", img, query="?tier=bogus")
        assert status == 200
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/models", timeout=30) as r:
            row = json.loads(r.read())["models"]["vggf"]
        assert "tiers" not in row
        payload = server.servingz_payload()
        assert "ladder" not in payload and "tier_default" not in payload
        assert "tiers" not in payload["models"]["vggf"]
    finally:
        server.close()


# ------------------------------------------------------------------- schema

def test_schema_validates_tier_and_accuracy_blocks():
    from distributed_vgg_f_tpu.telemetry import schema
    row = {"admitted_rps": 100.0, "tier": "int8",
           "serving": {"buckets": [1, 2, 4], "max_batch": 4,
                       "window_ms": 20.0, "queue_limit": 32,
                       "controller": False},
           "stages": [{"offered_rps": 120.0, "admitted_rps": 100.0,
                       "duration_s": 6.0, "shed_rate": 0.1,
                       "p50_ms": 5.0, "p95_ms": 9.0, "p99_ms": 11.0}],
           "accuracy": {"top1": 0.60, "fp32_top1": 0.62, "delta": 0.02,
                        "bound": 0.05, "eval_examples": 512}}
    errors = []
    schema.validate_serving_row(row, "row", errors)
    assert errors == []
    bad = dict(row, tier="fp64")
    errors = []
    schema.validate_serving_row(bad, "row", errors)
    assert any("tier" in e for e in errors)
    broken = dict(row, accuracy=dict(row["accuracy"], delta=0.09))
    errors = []
    schema.validate_serving_row(broken, "row", errors)
    assert any("accuracy contract" in e for e in errors)


def test_tiers_config_validation():
    with pytest.raises(ValueError, match="tier_default"):
        ServingConfig(tier_default="fp16")
    with pytest.raises(ValueError):
        ServingTiersConfig(calibration_batches=0)
    with pytest.raises(ValueError):
        ServingTiersConfig(max_top1_delta_int8=1.5)
