"""Deterministic ImageNet data resume (SURVEY.md §5 data-iterator state).

The tf.data train pipeline is a pure function of (seed, position): seeded
shuffle, deterministic interleave, stateless index-keyed augmentation. Symbolic
iterator snapshots restore a mid-stream position in O(1) — these tests assert
the restored stream is BIT-identical to the uninterrupted one. The full
SIGKILL variant lives in tests/test_kill_restart.py.
"""

import os

import numpy as np
import pytest

from distributed_vgg_f_tpu.config import DataConfig
from distributed_vgg_f_tpu.data import build_dataset


@pytest.fixture(scope="module")
def fake_tfrecord_dir(tmp_path_factory):
    tf = pytest.importorskip("tensorflow")
    root = tmp_path_factory.mktemp("resume_imagenet")
    rng = np.random.default_rng(0)
    for i in range(3):
        path = os.path.join(root, f"train-{i:05d}-of-00003")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(12):
                img = rng.integers(0, 256, size=(48, 64, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(
                            value=[int(rng.integers(1, 11))])),
                }))
                w.write(ex.SerializeToString())
    return str(root)


def _cfg(root, **kw):
    # native_jpeg=False pins the tf.data machinery for the snapshot-file
    # tests; the default (native loader, O(1) seek, no snapshot files) has
    # its own resume coverage in tests/test_native_jpeg.py and
    # tests/test_native_tfrecord.py.
    return DataConfig(name="imagenet", data_dir=root, image_size=32,
                      global_batch_size=4, shuffle_buffer=16, **kw)


def test_train_stream_deterministic_per_seed(fake_tfrecord_dir):
    a = build_dataset(_cfg(fake_tfrecord_dir), "train", seed=3)
    b = build_dataset(_cfg(fake_tfrecord_dir), "train", seed=3)
    for _ in range(5):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
    c = build_dataset(_cfg(fake_tfrecord_dir), "train", seed=4)
    assert not np.array_equal(next(c)["image"], next(
        build_dataset(_cfg(fake_tfrecord_dir), "train", seed=3))["image"])


def test_augmentation_varies_across_epochs(fake_tfrecord_dir):
    """The stream index keys the stateless crops, so epoch 2 must not replay
    epoch 1's exact augmented pixels (36 examples / 4 = 9 batches per epoch)."""
    ds = build_dataset(_cfg(fake_tfrecord_dir), "train", seed=0)
    epoch1 = [next(ds)["image"] for _ in range(9)]
    epoch2 = [next(ds)["image"] for _ in range(9)]
    assert not any(np.array_equal(x, y) for x, y in zip(epoch1, epoch2))


def test_snapshot_restore_bit_identical(fake_tfrecord_dir, tmp_path):
    state_dir = str(tmp_path / "iter_state")
    make = lambda: build_dataset(_cfg(fake_tfrecord_dir, native_jpeg=False), "train", seed=1,
                                 state_dir=state_dir, snapshot_every=2)
    ds = make()
    assert ds.supports_state
    batches = [next(ds) for _ in range(8)]
    # snapshots exist at the every-2 draw boundaries
    assert os.path.exists(os.path.join(state_dir, f"iter_{4:012d}.index"))

    resumed = make()
    assert resumed.restore_state(4)
    for i in range(4, 8):
        b = next(resumed)
        np.testing.assert_array_equal(b["image"], batches[i]["image"])
        np.testing.assert_array_equal(b["label"], batches[i]["label"])


def test_snapshot_rotation_keeps_last_k(fake_tfrecord_dir, tmp_path):
    state_dir = str(tmp_path / "rotate")
    ds = build_dataset(_cfg(fake_tfrecord_dir, native_jpeg=False), "train", seed=1,
                       state_dir=state_dir, snapshot_every=1)
    for _ in range(7):
        next(ds)
    stamps = sorted(int(f[len("iter_"):-len(".index")])
                    for f in os.listdir(state_dir) if f.endswith(".index"))
    assert stamps == [4, 5, 6, 7]  # keep=4


def test_restore_missing_snapshot_returns_false(fake_tfrecord_dir, tmp_path):
    ds = build_dataset(_cfg(fake_tfrecord_dir, native_jpeg=False), "train", seed=1,
                       state_dir=str(tmp_path / "none"), snapshot_every=5)
    assert ds.restore_state(0) is True        # fresh stream needs nothing
    assert ds.restore_state(3) is False       # no snapshot written yet
