"""Arithmetic of the analytic scaling model (utils/scaling_model.py) —
VERDICT r3 #3 asks for the model's math to be unit-tested, since no
multi-chip hardware can ever check it here."""

import math

import pytest

from distributed_vgg_f_tpu.utils.scaling_model import (
    MEASURED, V4, V5E, ModelPoint, allreduce_bytes_per_chip,
    north_star_summary, predict, predict_table, torus_hops)


def test_allreduce_bytes_formula():
    # ring all-reduce: 2·G·(N−1)/N per chip — exact small cases
    assert allreduce_bytes_per_chip(1000, 1) == 0.0
    assert allreduce_bytes_per_chip(1000, 2) == 1000.0          # 2·1000·1/2
    assert allreduce_bytes_per_chip(1000, 8) == 1750.0          # 2·1000·7/8
    # ZeRO-1 moves IDENTICAL wire bytes (reduce-scatter + all-gather):
    # its win is memory, not bandwidth — the table's point
    for n in (2, 8, 32, 128):
        assert allreduce_bytes_per_chip(12345, n, zero1=True) == \
            pytest.approx(allreduce_bytes_per_chip(12345, n, zero1=False))
    # ...EXCEPT with a narrower gradient wire: ZeRO-1's all-gather leg
    # moves fp32 PARAMS regardless (train/step.py), so bf16 saves only the
    # scatter leg — 0.75x, not 0.5x (code-review r4)
    assert allreduce_bytes_per_chip(500, 8, zero1=True, param_bytes=1000) \
        == pytest.approx(1500 * 7 / 8)


def test_wire_bytes_saturate_with_n():
    # (N−1)/N → 1: per-chip bytes approach 2G, never exceed it
    g = 243.3e6
    prev = 0.0
    for n in (2, 4, 8, 64, 1024):
        b = allreduce_bytes_per_chip(g, n)
        assert prev < b < 2 * g
        prev = b


def test_torus_hops():
    assert torus_hops(8) == 3        # 2×2×2: one hop per dimension
    assert torus_hops(64) == 9       # 4×4×4
    assert torus_hops(128) == 12     # ~5.04 per side
    assert torus_hops(8, dims=1) == 7  # flat ring fallback: N−1


def test_step_time_rescale_v5e_to_v4():
    p = MEASURED[0]
    assert p.v5e_step_time_s == pytest.approx(2048 / 22_028.4)
    # v4 is faster by the peak ratio (ASSUMPTIONS: MFU carries over)
    assert p.step_time_on(V4) == pytest.approx(
        p.v5e_step_time_s * 197e12 / 275e12)
    assert p.step_time_on(V5E) == pytest.approx(p.v5e_step_time_s)


def test_efficiency_bounds_and_monotonicity():
    for point in MEASURED:
        prev_eff = 1.01
        for n in (2, 8, 32, 128):
            r = predict(point, n)
            assert 0.0 < r.efficiency <= 1.0
            # vs-single-chip efficiency cannot IMPROVE with more chips
            assert r.efficiency <= prev_eff + 1e-12
            prev_eff = r.efficiency
            # identity: rate = batch / (step + exposed + latency)
            assert r.images_per_sec_per_chip == pytest.approx(
                point.per_chip_batch
                / (r.step_time_s + r.exposed_comm_s + r.latency_s))


def test_overlap_hides_comm_fully_for_flagship():
    # VGG-F at 128 chips: wire time ≈ 2.2 ms vs ~33 ms of overlappable
    # backward — exposed must be exactly 0 under the default overlap
    r = predict(MEASURED[0], 128)
    assert r.exposed_comm_s == 0.0
    assert r.efficiency > 0.999


def test_no_overlap_worst_case_still_above_target():
    # overlap_fraction=0: every wire byte exposed. Even the 553 MB VGG-16
    # gradient keeps efficiency above the 0.90 north star at 128 chips —
    # the committed claim that ICI is not the binding constraint
    for point in MEASURED:
        r = predict(point, 128, overlap_fraction=0.0)
        assert r.comm_time_s == pytest.approx(
            allreduce_bytes_per_chip(point.param_count * 4, 128)
            / (V4.injection_bytes_per_s * 0.8))
        assert r.exposed_comm_s == pytest.approx(r.comm_time_s)
        assert r.efficiency > 0.90, (point.name, r.efficiency)


def test_bf16_reduce_halves_wire_and_lifts_worst_case():
    # mesh.reduce_dtype='bfloat16' → grad_bytes_per_param=2: exactly half
    # the wire time under replicated DP, and the fp32 worst case (VGG-16,
    # no overlap, 128 chips) improves from ~0.93 to ~0.96
    fp32 = predict(MEASURED[1], 128, overlap_fraction=0.0)
    bf16 = predict(MEASURED[1], 128, overlap_fraction=0.0,
                   grad_bytes_per_param=2)
    assert bf16.comm_time_s == pytest.approx(fp32.comm_time_s / 2)
    assert fp32.efficiency < 0.93 < 0.96 < bf16.efficiency
    # under ZeRO-1 the param all-gather stays fp32: 0.75x, NOT 0.5x — the
    # model must match the implementation, not flatter it
    z32 = predict(MEASURED[1], 128, overlap_fraction=0.0, zero1=True)
    zbf = predict(MEASURED[1], 128, overlap_fraction=0.0, zero1=True,
                  grad_bytes_per_param=2)
    assert zbf.comm_time_s == pytest.approx(z32.comm_time_s * 0.75)


def test_host_ceiling_clears_flagship_device_rate_at_r9_decode():
    # v4 host ceiling: 240 cores × HOST_DECODE_RATE_R9 img/s/core / 4 chips
    # ≈ 73.7k — the r9 decode rate (restart-marker excerpt entropy decode
    # on the u8 wire, lower committed restart-on trio, runs/host_r10;
    # assumes interval-1 markers via reencode_restart.py). That is >2.3x
    # ABOVE the flagship's predicted 30.7k device rate: compute-bound with
    # real margin. The watch-item history is pinned below: at the frozen
    # r4 rate (556.34) the margin was ~9% thin, at the r3 rate (492/core)
    # the same model said "host" — the conclusion is sensitive to host
    # provisioning, which is the point
    from distributed_vgg_f_tpu.utils.scaling_model import HOST_DECODE_RATE_R9
    r = predict(MEASURED[0], 128)
    assert r.host_bound_images_per_sec_per_chip == pytest.approx(
        240 * HOST_DECODE_RATE_R9 / 4)
    assert r.binding_constraint == "compute"
    ratio = (r.host_bound_images_per_sec_per_chip
             / r.images_per_sec_per_chip)
    assert 2.2 < ratio < 2.6                        # ~2.4x headroom now
    # the r4 frozen rate reproduces the thin-margin era the README table
    # carried since r3
    r_r4 = predict(MEASURED[0], 128, host_decode_per_core=556.34)
    assert (r_r4.host_bound_images_per_sec_per_chip
            / r_r4.images_per_sec_per_chip) < 1.15
    r_slow_host = predict(MEASURED[0], 128, host_decode_per_core=492.456)
    assert r_slow_host.binding_constraint == "host"
    # VGG-16 at 1.9k img/s/chip is nowhere near the host ceiling
    r16 = predict(MEASURED[1], 128)
    assert r16.binding_constraint == "compute"


def test_north_star_summary_meets_target():
    ns = north_star_summary()
    # the 8→128 device-rate ratio: comm grows only via (N−1)/N, fully
    # hidden for vggf, so the ratio is ~1.0 — comfortably ≥ 0.90
    assert ns["efficiency_8_to_128"] >= 0.99
    assert ns["predicted_at_128"].latency_s < 1e-4


def test_predict_table_shape():
    rows = predict_table(n_chips_list=(8, 128))
    assert len(rows) == len(MEASURED) * 2 * 2   # models × layouts × sizes
    assert {r.layout for r in rows} == {"replicated", "zero1"}
    # zero1 and replicated agree on comm time (same wire bytes)
    by_key = {(r.model, r.layout, r.n_chips): r for r in rows}
    for p in MEASURED:
        for n in (8, 128):
            assert by_key[(p.name, "zero1", n)].comm_time_s == pytest.approx(
                by_key[(p.name, "replicated", n)].comm_time_s)


def test_ring_attention_compute_hides_comm_at_long_context():
    from distributed_vgg_f_tpu.utils.scaling_model import (
        ring_attention_comm_model)

    # the defining property: compute/comm ratio grows LINEARLY in T_local
    r1 = ring_attention_comm_model(1024, 8)
    r2 = ring_attention_comm_model(2048, 8)
    assert r2.compute_to_comm == pytest.approx(2 * r1.compute_to_comm)
    # hop bytes: 2·B·T·H·D·2 bytes (bf16 K and V blocks); forward-hop
    # compute is 4·B·H·T²·D FLOPs — TWO einsums of B·H·T²·D MACs, pinned
    # against parallel/ring_attention.py (code-review r4 caught a 2x
    # overcount here)
    assert r1.hop_bytes == 2 * 1 * 1024 * 8 * 64 * 2
    assert r1.hop_compute_s == pytest.approx(
        4 * 1 * 8 * 1024 ** 2 * 64 / (275e12 * 0.5))
    # the break-even length is consistent: at min_t_local_to_hide the
    # ratio is ~1 (within integer ceil)
    be = ring_attention_comm_model(r1.min_t_local_to_hide, 8)
    assert 0.9 < be.compute_to_comm < 1.2
    # a realistic long-context shard (8k tokens/chip) hides its hops with
    # ~2x headroom on ONE ICI link (break-even T_local ≈ 3.8k), and the
    # pipeline model agrees: zero exposed comm above break-even
    r8k = ring_attention_comm_model(8192, 8)
    assert r8k.compute_to_comm > 2
    assert r8k.comm_exposed_fraction == 0.0
    assert r8k.ring_time_s == pytest.approx(8 * r8k.hop_compute_s)
    # below break-even the exposure is real and grows with ring size
    short8 = ring_attention_comm_model(512, 8)
    short128 = ring_attention_comm_model(512, 128)
    assert 0 < short8.comm_exposed_fraction < short128.comm_exposed_fraction


def test_ulysses_comm_model_vs_ring():
    from distributed_vgg_f_tpu.utils.scaling_model import (
        ring_attention_comm_model, ulysses_comm_model)

    u = ulysses_comm_model(1024, 8)
    # injected bytes: 4 all_to_alls × (n−1)/n of the B·T·H·D·2 shard;
    # the ring injects 2·s·(n−1) — exactly n/2× more
    s = 1 * 1024 * 8 * 64 * 2
    assert u.a2a_bytes == pytest.approx(s * 7 / 8)
    assert u.wire_bytes_total == pytest.approx(4 * s * 7 / 8)
    assert u.ring_wire_bytes == pytest.approx(2 * s * 7)
    assert u.bytes_ratio_vs_ring == pytest.approx(8 / 2)
    # on torus ICI the byte advantage collapses to ≈2× wire TIME
    # (mean hop distance n/4 serializes on shared links)
    assert u.time_ratio_vs_ring == pytest.approx(2.0)
    # per-chip attention FLOPs equal the ring's total over its n hops
    r = ring_attention_comm_model(1024, 8)
    assert u.compute_s == pytest.approx(8 * r.hop_compute_s)
    # exposure: conservative model charges every ulysses wire second, so
    # above the ring's break-even the RING is the better layout...
    long_u = ulysses_comm_model(8192, 8)
    long_r = ring_attention_comm_model(8192, 8)
    assert long_r.comm_exposed_fraction == 0.0
    assert long_u.comm_exposed_fraction > 0.0
    # ...while far below break-even ulysses exposes less wall time than
    # the ring's exposed fraction of its pipeline
    short_u = ulysses_comm_model(256, 8)
    short_r = ring_attention_comm_model(256, 8)
    assert (short_u.comm_time_s
            < short_r.comm_exposed_fraction * short_r.ring_time_s)


def test_param_counts_match_models_exactly():
    # pins the committed counts to the real models (jax.eval_shape is cheap
    # tracing on the CPU test platform — no compile, no device step)
    import jax
    import jax.numpy as jnp

    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models import build_model

    for point in MEASURED:
        model = build_model(ModelConfig(name=point.name, num_classes=1000,
                                        compute_dtype="bfloat16"))
        shapes = jax.eval_shape(
            lambda m=model: m.init(jax.random.key(0),
                                   jnp.zeros((1, 224, 224, 3), jnp.float32),
                                   train=False))
        n = sum(math.prod(l.shape) for l in jax.tree.leaves(shapes["params"]))
        assert n == point.param_count, (point.name, n)


def test_host_provisioning_requirement():
    """The deployable host spec (VERDICT r4 #8): cores/chip from the
    measured decode rate. Facts re-pinned across the SIX measured rate
    regimes: at the r9 default (HOST_DECODE_RATE_R9 — restart-marker
    excerpt entropy decode on the u8 wire; assumes interval-1 markers,
    reencode_restart.py) stock hosts feed VGG-F on BOTH chip generations
    with the margin WIDENED again (21.5 cores needed w/ margin vs 23.7
    at r8 and 26.7 at r7, against 28 stock on v5e); at the r8 uint8-wire
    rate (what a marker-absent dataset decodes at) and the r7/r6 values
    the same verdict holds; at the r5 rate (728.05, scalar hoists) stock
    v5e could not; at the frozen r4 rate (556.34) even stock v4 was
    marginal. Every other model stays under 20% of stock at the
    default."""
    from distributed_vgg_f_tpu.utils.scaling_model import (
        HOST_DECODE_RATE_R5, HOST_DECODE_RATE_R6, HOST_DECODE_RATE_R7,
        HOST_DECODE_RATE_R8, HOST_DECODE_RATE_R9, MEASURED, V4, V5E,
        host_provisioning_requirement, host_provisioning_table)

    vggf = MEASURED[0]
    r = host_provisioning_requirement(vggf, chip=V4)
    # hand arithmetic: rate = v5e rate x 275/197; cores = rate / the
    # measured decode rate (HOST_DECODE_RATE_R9)
    rate = vggf.v5e_images_per_sec_per_chip * 275 / 197
    assert r.device_rate_img_s_chip == pytest.approx(rate)
    assert r.cores_per_chip_required == pytest.approx(
        rate / HOST_DECODE_RATE_R9)
    assert r.stock_cores_per_chip == pytest.approx(240 / 4)
    assert r.stock_sufficient                     # r9 decode: easy fit
    assert 0.40 < r.stock_utilization < 0.45
    # the row that flipped in r6, tightened to 26.7-vs-28 in r7 and
    # widened to 23.7 at r8 widens AGAIN at the r9 excerpt-decode rate:
    # stock v5e (224/8 = 28 cores/chip) feeds the flagship at its native
    # 22k rate needing 21.5 cores w/ margin
    r5e = host_provisioning_requirement(vggf, chip=V5E)
    assert r5e.stock_sufficient
    assert r5e.cores_per_chip_with_margin < 22.0
    assert 0.60 < r5e.stock_utilization < 0.70
    # the r8 uint8-wire rate — ALSO the operative rate for a dataset
    # nobody ran reencode_restart.py over — stays a sensitivity row with
    # the r8-era verdict (23.7 w/ margin vs 28 stock)
    r5e_r8 = host_provisioning_requirement(vggf, chip=V5E,
                                           decode_per_core=HOST_DECODE_RATE_R8)
    assert r5e_r8.stock_sufficient
    assert 23.0 < r5e_r8.cores_per_chip_with_margin < 24.0
    # the r7 host-wire rate and the r6 point value stay sensitivity rows
    # with the same verdict (r7: 26.7 w/ margin vs 28 stock — the value
    # the u8 wire was built to widen)
    r5e_r7 = host_provisioning_requirement(vggf, chip=V5E,
                                           decode_per_core=HOST_DECODE_RATE_R7)
    assert r5e_r7.stock_sufficient
    assert 26.0 < r5e_r7.cores_per_chip_with_margin < 28.0
    r5e_r6 = host_provisioning_requirement(vggf, chip=V5E,
                                           decode_per_core=HOST_DECODE_RATE_R6)
    assert r5e_r6.stock_sufficient
    # at the r5 scalar-hoist rate stock v5e could NOT feed it — the fact
    # the r5-era table committed, kept pinned as the sensitivity row
    r5e_old = host_provisioning_requirement(vggf, chip=V5E,
                                            decode_per_core=HOST_DECODE_RATE_R5)
    assert r5e_old.stock_utilization > 1.0
    assert not r5e_old.stock_sufficient
    # at the FROZEN pre-hoist r4 rate the v4 spec was marginal
    r_old = host_provisioning_requirement(vggf, chip=V4,
                                          decode_per_core=556.34)
    assert 0.90 < r_old.stock_utilization < 0.95
    assert not r_old.stock_sufficient
    # every non-flagship model is far under stock on both chips
    for chip in (V4, V5E):
        for row in host_provisioning_table(chip=chip)[1:]:
            assert row.stock_sufficient and row.stock_utilization < 0.2
    # sensitivity: requirement scales inversely with the decode rate
    slow = host_provisioning_requirement(
        vggf, decode_per_core=HOST_DECODE_RATE_R9 / 2)
    assert slow.cores_per_chip_required == pytest.approx(
        2 * r.cores_per_chip_required)
    with pytest.raises(ValueError, match="headroom"):
        host_provisioning_requirement(vggf, headroom=0.9)
