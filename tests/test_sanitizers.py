"""Sanitizer-hardened native builds (r15 correctness tooling plane).

The native ingest layer is exactly the code where a silent race or heap
error corrupts training data without failing a test: thread pools with
live resize (ABI v8), ChunkPool fan-out, longjmp error paths. These tests
run that code under the compilers' dynamic analyzers:

  * ASan+UBSan — the EXISTING byte-parity suites re-run against the
    instrumented build (`libdvgg_jpeg.asan.so`, built by the same
    native_build.py path under DVGGF_NATIVE_SANITIZER=asan), so every
    decode the parity contract covers is also checked for heap errors and
    UB;
  * TSan — a dedicated concurrency stress suite: the C++ driver
    (native/stress_driver.cc, sanitizer in the MAIN executable so every
    pthread is born instrumented) plus a Python-hosted stress that drives
    the documented concurrent surfaces (pool resize under load from a
    second thread, ChunkPool fan-out via decode_single from many clients,
    host-prefetch producer-consumer, device-ring prefetch, snapshot-store
    repair decodes, exporter scrape-under-load) through the instrumented
    .so with the TSan runtime LD_PRELOADed.

Every test skips WITH A REASON (native_build.sanitizer_missing) when the
toolchain lacks the sanitizer runtimes — mirroring
native_build.toolchain_missing, so 'not run' is always visible and
specific.

Leak checking: detect_leaks=0 in the PYTHON-hosted runs only — CPython
arenas are immortal by design and would drown the report; the pure-C++
stress drivers run with detect_leaks=1, which keeps the library-level
leak dimension covered. There are NO suppression files: the first full
ASan/UBSan/TSan pass over the v9 surface came back clean (receipts in
benchmarks/runs/ when the r12 session lands), and any future finding must
be fixed or suppressed with a written justification per entry.
"""

import os
import subprocess
import sys

import pytest

from distributed_vgg_f_tpu.data import native_build

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

pytestmark = pytest.mark.sanitizer

_missing_cache: dict = {}


def _require(kind: str) -> None:
    """Skip-with-reason when `kind` is unavailable. Lazy + cached: the
    g++ probe subprocesses run at most once per kind, and only when a
    sanitizer test is actually selected — a `-m 'not sanitizer'` loop
    pays nothing at collection."""
    if kind not in _missing_cache:
        _missing_cache[kind] = native_build.sanitizer_missing(kind)
    if _missing_cache[kind] is not None:
        pytest.skip(f"{kind} unavailable: {_missing_cache[kind]}")


def _san_env(kind: str) -> dict:
    """Environment for a python child that loads the instrumented .so:
    the sanitizer runtime must be LD_PRELOADed (ASan refuses to run
    otherwise), DVGGF_NATIVE_SANITIZER redirects native_build to the
    <lib>.<kind>.so variant, and halt_on_error turns any report into a
    nonzero exit this test can assert on."""
    rt = native_build.sanitizer_preload(kind)
    assert rt, f"sanitizer_missing() passed but no runtime for {kind}"
    env = dict(os.environ)
    env["LD_PRELOAD"] = rt
    env["DVGGF_NATIVE_SANITIZER"] = kind
    env["JAX_PLATFORMS"] = "cpu"
    if kind == "asan":
        env["ASAN_OPTIONS"] = ("detect_leaks=0:halt_on_error=1:"
                               "exitcode=66")
        env["UBSAN_OPTIONS"] = "halt_on_error=1:print_stacktrace=1"
    else:
        opts = ("halt_on_error=1:exitcode=66:"
                "ignore_noninstrumented_modules=1")
        supp = os.path.join(NATIVE, "tsan.supp")
        if os.path.exists(supp):  # per-entry justified suppressions only
            opts += f":suppressions={supp}"
        env["TSAN_OPTIONS"] = opts
    return env


def _make(target: str) -> str:
    """Build a Makefile target in native/ (cached by make's own mtime
    logic); returns the artifact path. Skip-with-reason when the host has
    a sanitizer toolchain but no make — same visibility contract as
    sanitizer_missing()."""
    import shutil
    if shutil.which("make") is None:
        pytest.skip("make not on PATH (stress drivers build via make)")
    proc = subprocess.run(["make", "-C", NATIVE, target],
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        f"make {target} failed:\n{proc.stdout}\n{proc.stderr}"
    return os.path.join(NATIVE, target)


# ------------------------------------------------------------ build matrix
def test_asan_instrumented_lib_actually_loads():
    """Receipt before trust: the child process must map the .asan.so
    variant — a green 'sanitized' suite that silently loaded the
    production build would sanitize nothing."""
    _require("asan")
    code = (
        "import numpy as np\n"
        "from distributed_vgg_f_tpu.data import native_jpeg\n"
        "assert native_jpeg.load_native_jpeg() is not None, 'no native'\n"
        "maps = open('/proc/self/maps').read()\n"
        "assert 'libdvgg_jpeg.asan.so' in maps, 'asan variant not mapped'\n"
        "assert 'libasan' in maps, 'asan runtime not mapped'\n"
        "print('ASAN_MAPPED')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         env=_san_env("asan"), capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ASAN_MAPPED" in out.stdout


def test_sanitizer_variant_builds_are_cached_side_by_side():
    """The variant redirect must never clobber the production .so: both
    names exist after a variant build, and the production path still
    resolves without the env var."""
    _require("asan")
    env = dict(os.environ)
    env["DVGGF_NATIVE_SANITIZER"] = "asan"
    code = (
        "from distributed_vgg_f_tpu.data import native_build\n"
        "p = native_build.build_native_lib('tfrecord_index.cc',"
        " 'libdvgg_tfrecord.so')\n"
        "assert p and p.endswith('libdvgg_tfrecord.asan.so'), p\n"
        "print('VARIANT_PATH_OK')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "VARIANT_PATH_OK" in out.stdout
    # bogus variant fails loudly, never falls back to uninstrumented
    env["DVGGF_NATIVE_SANITIZER"] = "msan"
    out = subprocess.run(
        [sys.executable, "-c",
         "from distributed_vgg_f_tpu.data import native_build\n"
         "try:\n"
         "    native_build.build_native_lib('tfrecord_index.cc',"
         " 'libdvgg_tfrecord.so')\n"
         "except ValueError as e:\n"
         "    print('REFUSED', e)\n"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert "REFUSED" in out.stdout, out.stdout + out.stderr


# ----------------------------------------------- ASan+UBSan byte parity
@pytest.mark.slow
def test_asan_ubsan_byte_parity_suite():
    """The EXISTING parity contract (SIMD≡scalar, scaled≡full, restart≡
    sequential, u8 wire, batch loaders — tests/test_native_jpeg_parity.py)
    re-run with every native call under ASan+UBSan. halt_on_error turns
    any heap error or UB into a hard child failure."""
    _require("asan")
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_native_jpeg_parity.py",
         "-q", "-p", "no:cacheprovider", "-p", "no:randomly"],
        cwd=REPO, env=_san_env("asan"), capture_output=True, text=True,
        timeout=600)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-4000:]
    assert " passed" in out.stdout
    assert "ERROR: AddressSanitizer" not in out.stderr
    assert "runtime error:" not in out.stderr  # UBSan's report prefix


# --------------------------------------------------- native stress drivers
@pytest.mark.slow
def test_asan_native_stress_driver(tmp_path):
    """The C++ concurrency stress under ASan+UBSan WITH leak checking —
    pure native code, so detect_leaks=1 is signal, not CPython noise."""
    _require("asan")
    driver = _make("stress_driver.asan")
    env = dict(os.environ)
    env["ASAN_OPTIONS"] = "detect_leaks=1:halt_on_error=1:exitcode=66"
    env["UBSAN_OPTIONS"] = "halt_on_error=1:print_stacktrace=1"
    out = subprocess.run([driver, str(tmp_path)], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "[stress] OK" in out.stderr


@pytest.mark.slow
def test_tsan_native_stress_driver(tmp_path):
    """Pool resize under load, ChunkPool fan-out, producer-consumer and
    handle churn with TSan in the main executable — the suite that would
    have caught a claim-loop/retire race the day it landed."""
    _require("tsan")
    driver = _make("stress_driver.tsan")
    env = dict(os.environ)
    opts = "halt_on_error=1:exitcode=66"
    supp = os.path.join(NATIVE, "tsan.supp")
    if os.path.exists(supp):
        opts += f":suppressions={supp}"
    env["TSAN_OPTIONS"] = opts
    out = subprocess.run([driver, str(tmp_path)], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "[stress] OK" in out.stderr
    assert "WARNING: ThreadSanitizer" not in out.stderr


# ------------------------------------------- Python-hosted TSan stress
_PY_STRESS = r"""
import io, os, threading, time, urllib.request
import numpy as np
from PIL import Image

from distributed_vgg_f_tpu.data import native_jpeg
from distributed_vgg_f_tpu.data.prefetch import HostPrefetchIterator
from distributed_vgg_f_tpu.data.snapshot_cache import SnapshotStore
from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.telemetry.exporter import TelemetryExporter

assert native_jpeg.load_native_jpeg() is not None, "no native lib"
maps = open("/proc/self/maps").read()
assert "libdvgg_jpeg.tsan.so" in maps, "tsan variant not mapped"

root = os.environ["STRESS_DIR"]
rs = np.random.RandomState(0)
files, labels = [], []
for i in range(10):
    p = os.path.join(root, f"s{i}.jpg")
    Image.fromarray((rs.rand(144, 144, 3) * 255).astype(np.uint8)).save(
        p, "JPEG", quality=88)
    files.append(p)
    labels.append(i % 5)
mean = np.zeros(3, np.float32)
std = np.ones(3, np.float32)
errors = []

# [1] pool resize under load + host-prefetch producer-consumer ring:
# consumer drains through HostPrefetchIterator's worker thread while the
# main thread hammers the ABI v8 resize — the autotuner's actuation path.
it = native_jpeg.NativeJpegTrainIterator(
    files, labels, 4, 64, seed=7, mean=mean, std=std, num_threads=3)
host = HostPrefetchIterator(iter(it), depth=2)
done = threading.Event()

def consume():
    try:
        for _ in range(30):
            next(host)
    except Exception as e:  # noqa: BLE001 — report into the main thread
        errors.append(f"consumer: {e}")
    finally:
        done.set()

t = threading.Thread(target=consume)
t.start()
k = 0
while not done.is_set():
    it.set_num_threads(1 + k % 8)
    native_jpeg.decode_stats()
    native_jpeg.restart_stats()
    k += 1
    time.sleep(0.005)
t.join()
host.close()

# [2] ChunkPool fan-out: one marker-bearing image split across the native
# chunk pool, decoded concurrently by several client threads (the predict
# /grain-worker pattern).
plain = open(files[0], "rb").read()
marked = native_jpeg.reencode_restart(plain, 0)
assert marked, "reencode failed"
native_jpeg.set_restart(True)
native_jpeg.set_restart_fanout(4)

def fan(tid):
    for i in range(6):
        out = native_jpeg.decode_single_image(
            marked, 96, mean, std, rng_seed=tid * 100 + i)
        if out is None:
            errors.append(f"fan{tid}: decode failed")

fans = [threading.Thread(target=fan, args=(i,)) for i in range(3)]
for f in fans: f.start()
for f in fans: f.join()
native_jpeg.set_restart_fanout(1)

# [3] snapshot-store repair decodes under concurrency: the store keeps
# its documented single-owner thread (one thread writes/reads/evicts),
# while the REPAIR surface — hflip=False decode_single of the same source
# bytes — runs concurrently from sibling threads, exactly the native-side
# overlap a warm epoch with degraded entries produces.
store = SnapshotStore(os.path.join(root, "snap"), "gen0", 1 << 28, 16)

def repair(tid):
    for i in range(8):
        arr = native_jpeg.decode_single_image(
            plain, 48, mean, std, rng_seed=tid * 50 + i, hflip=False)
        if arr is None:
            errors.append(f"repair{tid}: decode failed")

repairs = [threading.Thread(target=repair, args=(j,)) for j in range(2)]
for r in repairs: r.start()
for i in range(16):
    arr = native_jpeg.decode_single_image(
        plain, 48, mean, std, rng_seed=i, hflip=False)
    assert arr is not None
    store.write(i, arr, (1, 2, 3))
    if store.has(i):
        got = store.read(i)
        if got is None:
            errors.append(f"store round-trip lost item {i}")
for r in repairs: r.join()
store.flush()

# [4] exporter scrape-under-load: HTTP scrapes pull the decode poller
# (which calls the instrumented stats exports) while decodes run.
native_jpeg.register_decode_poller()
exp = TelemetryExporter()
port = exp.start()
stop = threading.Event()
def scrape():
    while not stop.is_set():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            r.read()
        time.sleep(0.002)
s = threading.Thread(target=scrape)
s.start()
for i in range(24):
    native_jpeg.decode_single_image(plain, 64, mean, std, rng_seed=i)
stop.set()
s.join()
exp.stop()
it.close()

assert not errors, errors
print("PY_STRESS_OK")
"""


@pytest.mark.slow
def test_tsan_python_concurrency_stress(tmp_path):
    """The Python-orchestrated concurrent surfaces — live resize during a
    host-prefetch drain, fan-out decode_single clients, snapshot-store
    repair decodes, exporter scrape-under-load — through the TSan build.
    ignore_noninstrumented_modules keeps CPython/numpy internals out of
    the report; races involving the instrumented .so still fire."""
    _require("tsan")
    env = _san_env("tsan")
    env["STRESS_DIR"] = str(tmp_path)
    out = subprocess.run([sys.executable, "-c", _PY_STRESS], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=900)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-4000:]
    assert "PY_STRESS_OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr


_SVC_STRESS = r"""
import os, socket, threading, time
import numpy as np
from PIL import Image

from distributed_vgg_f_tpu.data import native_jpeg
from distributed_vgg_f_tpu.data.ingest_service import (
    IngestWorker, PositionKeyedProducer, ServiceProtocolError,
    recv_message, send_message)

assert native_jpeg.load_native_jpeg() is not None, "no native lib"
maps = open("/proc/self/maps").read()
assert "libdvgg_jpeg.tsan.so" in maps, "tsan variant not mapped"

root = os.environ["STRESS_DIR"]
rs = np.random.RandomState(4)
files, labels = [], []
for i in range(8):
    p = os.path.join(root, f"w{i}.jpg")
    Image.fromarray((rs.rand(120, 120, 3) * 255).astype(np.uint8)).save(
        p, "JPEG", quality=88)
    files.append(p)
    labels.append(i)
mean = np.zeros(3, np.float32)
std = np.ones(3, np.float32)
errors = []

# [1] concurrent clients against ONE worker: each connection handler
# drives produce() -> the instrumented decode_single fan-out, while the
# worker's thread pool is resized from the main thread (the per-worker
# autotuner's actuation surface).
worker = IngestWorker(PositionKeyedProducer(
    files=files, labels=labels, batch=4, image_size=48, seed=2,
    mean=mean, std=std, image_dtype="uint8", threads=2),
    worker_index=0, num_workers=1)
addr = ("127.0.0.1", worker.port)

def client(tid):
    try:
        s = socket.create_connection(addr, timeout=30)
        s.settimeout(30)
        for i in range(10):
            send_message(s, {"op": "get", "cursor": tid * 100 + i})
            resp, arrays = recv_message(s)
            if not resp.get("ok") or arrays["image"].shape != (4, 48, 48, 3):
                errors.append(f"client{tid}: bad response at {i}")
        s.close()
    except Exception as e:  # noqa: BLE001 — report into the main thread
        errors.append(f"client{tid}: {e}")

clients = [threading.Thread(target=client, args=(i,)) for i in range(4)]
for c in clients: c.start()
k = 0
while any(c.is_alive() for c in clients):
    worker._producer.set_num_threads(1 + k % 6)
    k += 1
    time.sleep(0.01)
for c in clients: c.join()
assert not errors, errors

# [2] worker shutdown under in-flight reads: hammer gets from several
# threads, then close() the worker mid-stream — every client must see a
# clean EOF/reset (ServiceProtocolError/OSError), never a hang or a torn
# frame accepted as data.
outcomes = []
def doomed(tid):
    try:
        s = socket.create_connection(addr, timeout=30)
        s.settimeout(30)
        for i in range(1000):
            send_message(s, {"op": "get", "cursor": i})
            resp, arrays = recv_message(s)
        outcomes.append("finished")
    except (ServiceProtocolError, OSError):
        outcomes.append("clean-eof")
    except Exception as e:  # noqa: BLE001
        errors.append(f"doomed{tid}: unexpected {type(e).__name__}: {e}")

doom = [threading.Thread(target=doomed, args=(i,)) for i in range(3)]
for d in doom: d.start()
time.sleep(0.25)
worker.close()
for d in doom: d.join()
assert not errors, errors
assert outcomes.count("clean-eof") >= 1, outcomes
print("SVC_STRESS_OK")
"""


@pytest.mark.slow
def test_tsan_ingest_service_socket_stress(tmp_path):
    """The disaggregated-ingest worker's concurrent surfaces (r16):
    several clients hammering one worker's length-prefixed socket plane
    (connection handlers -> produce() -> instrumented decode_single
    fan-out) while the decode pool resizes, then worker shutdown under
    in-flight reads — the framing layer's torn-frame/hang hazards under
    TSan."""
    _require("tsan")
    env = _san_env("tsan")
    env["STRESS_DIR"] = str(tmp_path)
    out = subprocess.run([sys.executable, "-c", _SVC_STRESS], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=900)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-4000:]
    assert "SVC_STRESS_OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr


@pytest.mark.slow
def test_tsan_device_ring_prefetch(tmp_path):
    """Device-ring producer-consumer (DevicePrefetchIterator's device_put
    thread) over the instrumented loader — the trainer's actual ingest
    topology, under TSan."""
    _require("tsan")
    code = (
        "import os\n"
        "import numpy as np\n"
        "from PIL import Image\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from distributed_vgg_f_tpu.data import native_jpeg\n"
        "from distributed_vgg_f_tpu.data.prefetch import "
        "DevicePrefetchIterator\n"
        "from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, "
        "build_mesh\n"
        "assert native_jpeg.load_native_jpeg() is not None\n"
        "root = os.environ['STRESS_DIR']\n"
        "rs = np.random.RandomState(1)\n"
        "files, labels = [], []\n"
        "for i in range(6):\n"
        "    p = os.path.join(root, f'd{i}.jpg')\n"
        "    Image.fromarray((rs.rand(96, 96, 3) * 255).astype(np.uint8))"
        ".save(p, 'JPEG')\n"
        "    files.append(p); labels.append(i)\n"
        "mesh = build_mesh(MeshSpec())\n"
        "it = native_jpeg.NativeJpegTrainIterator(files, labels, 8, 48,"
        " seed=3, mean=np.zeros(3, np.float32), std=np.ones(3, np.float32),"
        " num_threads=2)\n"
        "pre = DevicePrefetchIterator(iter(it), mesh, buffer_size=2)\n"
        "for _ in range(8):\n"
        "    b = next(pre)\n"
        "pre.close()\n"
        "it.close()\n"
        "print('RING_OK')\n"
    )
    env = _san_env("tsan")
    env["STRESS_DIR"] = str(tmp_path)
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-4000:]
    assert "RING_OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr
