"""Config presets, CLI overrides, and LR schedule boundaries (SURVEY.md §4)."""

import pytest

from distributed_vgg_f_tpu.config import (
    PRESETS,
    apply_overrides,
    get_config,
    parse_cli,
)
from distributed_vgg_f_tpu.train.schedule import build_schedule


def test_all_presets_build():
    for name in PRESETS:
        cfg = get_config(name)
        assert cfg.total_steps > 0
        assert cfg.scaled_lr > 0


def test_baseline_config_names_covered():
    # One preset per BASELINE.json "configs" entry.
    for required in ["vggf_cifar10_smoke", "vggf_imagenet_dp", "vgg16_imagenet",
                     "resnet50_imagenet", "vit_s16_imagenet"]:
        assert required in PRESETS


def test_overrides_and_cli():
    cfg = get_config("vggf_imagenet_dp")
    cfg2 = apply_overrides(cfg, {"data.global_batch_size": "2048",
                                 "optim.base_lr": "0.02"})
    assert cfg2.data.global_batch_size == 2048
    assert cfg2.optim.base_lr == 0.02
    cfg3 = parse_cli(["--config", "vggf_cifar10_smoke",
                      "--set", "train.steps=7"])
    assert cfg3.train.steps == 7
    assert cfg3.total_steps == 7


def test_bool_overrides():
    cfg = get_config("vggf_imagenet_dp")
    # The README's own example: --set mesh.shard_opt_state=... must work BOTH ways.
    on = apply_overrides(cfg, {"mesh.shard_opt_state": "true"})
    assert on.mesh.shard_opt_state is True
    off = apply_overrides(on, {"mesh.shard_opt_state": "false"})
    assert off.mesh.shard_opt_state is False
    assert apply_overrides(cfg, {"mesh.shard_opt_state": "1"}).mesh.shard_opt_state is True
    assert apply_overrides(cfg, {"mesh.shard_opt_state": "0"}).mesh.shard_opt_state is False
    assert apply_overrides(cfg, {"train.debug_nans": True}).train.debug_nans is True
    with pytest.raises(ValueError):
        apply_overrides(cfg, {"mesh.shard_opt_state": "maybe"})


def test_extra_dict_overrides():
    """The config-preset comment's own example: model.extra keys (e.g.
    re-enabling ViT attention-weight dropout) must be settable by dotted
    path, with best-effort typing for keys that have no existing value."""
    cfg = get_config("vit_s16_imagenet")
    cfg2 = apply_overrides(cfg, {"model.extra.attention_dropout_rate": "0.1"})
    assert cfg2.model.extra["attention_dropout_rate"] == 0.1
    assert isinstance(cfg2.model.extra["attention_dropout_rate"], float)
    # existing-key overrides mirror the current value's type
    cfg3 = apply_overrides(cfg2, {"model.extra.attention_dropout_rate": "0"})
    assert cfg3.model.extra["attention_dropout_rate"] == 0.0
    # untyped fresh keys: bool words and ints parse, strings stay strings
    cfg4 = apply_overrides(cfg, {"model.extra.attention_layout": "token_major",
                                 "model.extra.depth": "6"})
    assert cfg4.model.extra["attention_layout"] == "token_major"
    assert cfg4.model.extra["depth"] == 6
    # the model actually builds with the overridden extras
    from distributed_vgg_f_tpu.models import build_model
    model = build_model(cfg4.model)
    assert model.depth == 6 and model.attention_layout == "token_major"
    with pytest.raises(KeyError):
        apply_overrides(cfg, {"model.extra.missing.nested": "1"})


def test_sequence_overrides():
    cfg = get_config("vggf_imagenet_dp")
    cfg2 = apply_overrides(cfg, {"optim.decay_epochs": "20,40,60"})
    assert cfg2.optim.decay_epochs == (20.0, 40.0, 60.0)
    cfg3 = apply_overrides(cfg, {"data.mean_rgb": "0,0,0"})
    assert cfg3.data.mean_rgb == (0.0, 0.0, 0.0)
    cfg4 = apply_overrides(cfg, {"optim.decay_epochs": [10.0, 20.0]})
    assert cfg4.optim.decay_epochs == (10.0, 20.0)


def test_cli_bool_override_roundtrip():
    cfg = parse_cli(["--config", "vggf_imagenet_dp",
                     "--set", "mesh.shard_opt_state=true",
                     "--set", "train.resume_data_fast_forward=false"])
    assert cfg.mesh.shard_opt_state is True
    assert cfg.train.resume_data_fast_forward is False


def test_unknown_config_raises():
    with pytest.raises(KeyError):
        get_config("nope")


def test_step_schedule_boundaries():
    cfg = get_config("vggf_imagenet_dp")
    sched = build_schedule(cfg)
    spe = cfg.steps_per_epoch
    lr0 = float(sched(0))
    assert abs(lr0 - cfg.scaled_lr) < 1e-9
    # after first decay epoch boundary (30 epochs) LR drops 10x
    lr_after = float(sched(int(30 * spe) + 1))
    assert abs(lr_after - cfg.scaled_lr * 0.1) < 1e-9
    lr_after2 = float(sched(int(60 * spe) + 1))
    assert abs(lr_after2 - cfg.scaled_lr * 0.01) < 1e-9


def test_warmup_schedule():
    cfg = get_config("vit_s16_imagenet")
    sched = build_schedule(cfg)
    spe = cfg.steps_per_epoch
    warmup_steps = int(cfg.optim.warmup_epochs * spe)
    assert float(sched(0)) < float(sched(warmup_steps // 2)) < float(
        sched(warmup_steps))
    peak = cfg.scaled_lr
    assert abs(float(sched(warmup_steps)) - peak) / peak < 0.01


def test_linear_lr_scaling():
    cfg = get_config("vggf_imagenet_dp")
    assert abs(cfg.scaled_lr - cfg.optim.base_lr *
               cfg.data.global_batch_size / cfg.optim.reference_batch_size) < 1e-12
