"""Two-process graceful preemption: SIGTERM to ONE process must stop BOTH at
the same step with a collective forced checkpoint — the per-step async
stop-consensus collective (parallel/preempt.py), exercised over real OS
processes with Gloo collectives (a lone host saving unilaterally would
strand the other in the Orbax collective). The child runs with
log_every=1_000_000: the stop must arrive within seconds regardless of the
logging cadence (VERDICT r2 #5 time-bounded consensus)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "preempt_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_sigterm_on_one_process_stops_both(tmp_path):
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    outs = [str(tmp_path / f"result_{i}.json") for i in range(2)]
    jsonl = str(tmp_path / "metrics.jsonl")
    ckpt = str(tmp_path / "ckpt")
    procs = [subprocess.Popen(
        [sys.executable, CHILD, str(port), "2", str(i), outs[i], ckpt, jsonl],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        deadline = time.monotonic() + 600
        # the child can't log train events (log_every is huge); it touches a
        # sentinel file after each completed step instead
        sentinel = outs[0] + ".stepped"
        while not os.path.exists(sentinel):
            if any(p.poll() is not None for p in procs):
                dumps = [p.stdout.read().decode(errors="replace")
                         for p in procs if p.poll() is not None]
                pytest.fail("child exited before training started:\n"
                            + dumps[0][-3000:])
            if time.monotonic() > deadline:
                pytest.fail("no training progress within 600s")
            time.sleep(0.2)
        # preempt ONLY process 0; consensus must stop process 1 too — and
        # must do it in bounded time even though the next log_every boundary
        # is ~never (the old log-cadence design would hang here until the
        # communicate() timeout)
        procs[0].send_signal(signal.SIGTERM)
        t_signal = time.monotonic()
        for p in procs:
            out, _ = p.communicate(timeout=300)
            assert p.returncode == 0, out.decode(errors="replace")[-3000:]
        stop_latency = time.monotonic() - t_signal
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = [json.load(open(o)) for o in outs]
    # both processes stopped at the SAME step (consensus), with the forced
    # checkpoint durable at that step, within seconds of the signal
    assert results[0]["step"] == results[1]["step"]
    stop_step = results[0]["step"]
    assert stop_step >= 1
    assert all(r["latest_ckpt"] == stop_step for r in results)
    # falsifiable bound, well under the communicate() timeout: consensus is
    # per-step (~ms CPU steps) + one forced checkpoint — regression to a
    # minutes-scale stop would fail here, not at the timeout
    assert stop_latency < 120
    with open(jsonl) as f:
        events = [json.loads(l) for l in f if l.strip()]
    preempts = [e for e in events if e.get("event") == "preempt"]
    assert len(preempts) == 1 and preempts[0]["step"] == stop_step
