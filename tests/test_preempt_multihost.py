"""Two-process graceful preemption: SIGTERM to ONE process must stop BOTH at
the same log-cadence step with a collective forced checkpoint — the
stop-consensus allgather in Trainer.fit, exercised over real OS processes
with Gloo collectives (a lone host saving unilaterally would strand the
other in the Orbax collective)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "preempt_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_sigterm_on_one_process_stops_both(tmp_path):
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    outs = [str(tmp_path / f"result_{i}.json") for i in range(2)]
    jsonl = str(tmp_path / "metrics.jsonl")
    ckpt = str(tmp_path / "ckpt")
    procs = [subprocess.Popen(
        [sys.executable, CHILD, str(port), "2", str(i), outs[i], ckpt, jsonl],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        deadline = time.monotonic() + 600
        started = False
        while not started:
            if any(p.poll() is not None for p in procs):
                dumps = [p.stdout.read().decode(errors="replace")
                         for p in procs if p.poll() is not None]
                pytest.fail("child exited before training started:\n"
                            + dumps[0][-3000:])
            if time.monotonic() > deadline:
                pytest.fail("no training progress within 600s")
            if os.path.exists(jsonl):
                with open(jsonl) as f:
                    started = any('"event": "train"' in l for l in f)
            time.sleep(0.2)
        # preempt ONLY process 0; consensus must stop process 1 too
        procs[0].send_signal(signal.SIGTERM)
        for p in procs:
            out, _ = p.communicate(timeout=600)
            assert p.returncode == 0, out.decode(errors="replace")[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = [json.load(open(o)) for o in outs]
    # both processes stopped at the SAME step (the allgather consensus), on a
    # log_every boundary, with the forced checkpoint durable at that step
    assert results[0]["step"] == results[1]["step"]
    stop_step = results[0]["step"]
    assert stop_step >= 1 and stop_step % 2 == 0
    assert all(r["latest_ckpt"] == stop_step for r in results)
    with open(jsonl) as f:
        events = [json.loads(l) for l in f if l.strip()]
    preempts = [e for e in events if e.get("event") == "preempt"]
    assert len(preempts) == 1 and preempts[0]["step"] == stop_step
