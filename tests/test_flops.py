"""Analytic jaxpr FLOP counter (utils/flops.py) — the validated basis for
bench.py's `mfu_est` (VERDICT r2 #8): oracle-checked against hand formulas,
and cross-checked against XLA's cost analysis on a compiled train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.utils.flops import (
    conv_fc_reference_flops,
    jaxpr_flops,
)


def test_dot_general_matches_hand_formula():
    a = jnp.zeros((8, 128))
    b = jnp.zeros((128, 64))
    flops = jaxpr_flops(lambda x, y: x @ y, a, b)
    assert flops == 2 * 8 * 128 * 64


def test_batched_dot_counts_batch_dims():
    a = jnp.zeros((4, 8, 16))
    b = jnp.zeros((4, 16, 32))
    flops = jaxpr_flops(lambda x, y: jnp.einsum("bmk,bkn->bmn", x, y), a, b)
    assert flops == 2 * 4 * 8 * 16 * 32


def test_conv_matches_hand_formula():
    x = jnp.zeros((2, 16, 16, 3))
    w = jnp.zeros((5, 5, 3, 32))

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    flops = jaxpr_flops(conv, x, w)
    assert flops == conv_fc_reference_flops(
        [("conv", 16, 16, 5, 5, 3, 32)], batch=2)


def test_grad_roughly_triples_forward():
    """Backward of a dense layer needing BOTH input and weight grads costs
    ~2× forward; fwd+bwd together ≈ 3× forward — the counter must see the
    grad FLOPs inside the traced program."""
    w = jnp.zeros((64, 64))
    x = jnp.zeros((32, 64))

    def loss(w, x):
        return jnp.sum((x @ w) ** 2)

    fwd = jaxpr_flops(loss, w, x)
    both = jaxpr_flops(jax.grad(loss, argnums=(0, 1)), w, x)
    assert both == pytest.approx(3 * fwd, rel=0.05)


def test_vggf_forward_flops_in_architecture_band(devices8):
    """VGG-F at 224²: forward conv+fc FLOPs must land in the CNN-F
    architecture's band (the well-known figure is bounded by the pooling
    geometry — this guards against unit errors of 2× or more)."""
    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models import build_model

    model = build_model(ModelConfig(name="vggf", num_classes=1000,
                                    compute_dtype="float32"))
    x = jnp.zeros((1, 224, 224, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    flops = jaxpr_flops(
        lambda v, img: model.apply(v, img, train=False), variables, x)
    # CNN-F ≈ 2×1.1G MACs of conv + ≈2×59M fc — O(2.4e9); the band allows
    # implementation pad/ceil-mode differences but not unit errors
    assert 1.5e9 < flops < 6e9


@pytest.mark.slow
def test_train_step_analytic_vs_xla_cost_analysis(devices8):
    """The two FLOP sources must agree within a band on the full jitted DP
    train step — divergence means either fusion double-counting (XLA side)
    or a missed primitive (analytic side). XLA's cost analysis is
    PER-PARTITION for SPMD executables (measured: a mesh-8 compile reports
    ~1/8 of the mesh-1 figure) — the convention bench.py's `mfu_est_xla`
    relies on, pinned here."""
    import io

    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
        TrainConfig)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    def measure(n):
        cfg = ExperimentConfig(
            name="flops_test",
            model=ModelConfig(name="vggf", num_classes=10,
                              compute_dtype="float32", dropout_rate=0.0),
            optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
            data=DataConfig(name="synthetic", image_size=32,
                            global_batch_size=16),
            mesh=MeshConfig(num_data=n),
            train=TrainConfig(steps=1, seed=0),
        )
        mesh = build_mesh(MeshSpec(("data",), (n,)),
                          devices=jax.devices()[:n])
        trainer = Trainer(cfg, mesh=mesh,
                          logger=MetricLogger(stream=io.StringIO()))
        state = trainer.init_state()
        rng = trainer.base_rng()
        batch = trainer.shard(next(SyntheticDataset(
            batch_size=16, image_size=32, num_classes=10, seed=0)))
        analytic = jaxpr_flops(trainer.train_step, state, batch, rng)
        compiled = trainer.train_step.lower(state, batch, rng).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        return analytic, float(analysis.get("flops", 0.0))

    # single partition: whole-program == per-partition, tight agreement
    # (XLA also counts elementwise flops, so it reads slightly high or the
    # analytic slightly low — both sources must stay in one band)
    analytic1, xla1 = measure(1)
    assert analytic1 > 0 and xla1 > 0
    assert 0.6 < xla1 / analytic1 < 1.6, (analytic1, xla1)

    # 8 partitions: analytic stays whole-program; XLA drops to roughly a
    # per-partition share (plus replicated per-device elementwise work) —
    # the semantics bench.py's per-chip mfu_est_xla depends on
    analytic8, xla8 = measure(8)
    assert analytic8 == pytest.approx(analytic1, rel=1e-6)
    assert xla8 < 0.5 * xla1, (xla1, xla8)
