"""Cross-topology checkpoint restore (checkpoint/retopology.py): a checkpoint
written on an N-device mesh restores onto M devices, and replicated DP ↔
ZeRO-1 migrate in both directions — params bit-identical, momentum trace
preserved exactly, training continues (VERDICT r2 #4; BASELINE north_star
v4-8 → v4-128)."""

import dataclasses
import io

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.parallel.zero import (
    convert_opt_state,
    flat_param_count,
    padded_flat_size,
)
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _cfg(ckpt_dir, zero1: bool, steps: int = 2) -> ExperimentConfig:
    return ExperimentConfig(
        name="retopo_test",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          momentum=0.9, weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        mesh=MeshConfig(num_data=0, shard_opt_state=zero1),
        train=TrainConfig(steps=steps, seed=0, log_every=100,
                          checkpoint_dir=str(ckpt_dir),
                          checkpoint_every_steps=1),
    )


def _mesh(n: int):
    return build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])


def _quiet():
    return MetricLogger(stream=io.StringIO())


def _train_and_save(cfg, mesh_size: int, steps: int = 2):
    trainer = Trainer(cfg, mesh=_mesh(mesh_size), logger=_quiet())
    state = trainer.init_state()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=cfg.data.global_batch_size,
                          image_size=32, num_classes=10, seed=0)
    for _ in range(steps):
        state, _ = trainer.train_step(state, trainer.shard(next(ds)), rng)
    trainer.checkpoints.save(state, force=True)
    trainer.checkpoints.wait()
    return trainer, state


def _canonical_opt(trainer, state):
    """The opt state in the layout-independent params-tree form (host)."""
    params_struct = jax.eval_shape(lambda p: p, state.params)
    canon = convert_opt_state(jax.device_get(state.opt_state), trainer.tx,
                              params_struct, None)
    return jax.tree.leaves(jax.device_get(canon))


def _assert_states_match(tr_a, state_a, tr_b, state_b):
    assert int(jax.device_get(state_a.step)) == int(
        jax.device_get(state_b.step))
    for a, b in zip(jax.tree.leaves(jax.device_get(state_a.params)),
                    jax.tree.leaves(jax.device_get(state_b.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(_canonical_opt(tr_a, state_a),
                    _canonical_opt(tr_b, state_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _one_more_step(trainer, state):
    ds = SyntheticDataset(batch_size=trainer.cfg.data.global_batch_size,
                          image_size=32, num_classes=10, seed=1)
    new_state, metrics = trainer.train_step(state, trainer.shard(next(ds)),
                                            trainer.base_rng())
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    return new_state


@pytest.mark.parametrize("src_n,dst_n", [(8, 4), (2, 8)])
@pytest.mark.slow
def test_zero1_restore_across_mesh_sizes(devices8, tmp_path, src_n, dst_n):
    """ZeRO-1 N devices → ZeRO-1 M devices: the padded flat opt-state vector
    is repartitioned on load (grow AND shrink)."""
    cfg = _cfg(tmp_path / "ck", zero1=True)
    tr_src, state_src = _train_and_save(cfg, src_n)

    total = flat_param_count(jax.device_get(state_src.params))
    assert padded_flat_size(total, src_n) != padded_flat_size(total, dst_n), \
        "test premise: paddings must differ so the conversion path is " \
        "exercised (pick a num_classes that changes the remainder)"

    tr_dst = Trainer(cfg, mesh=_mesh(dst_n), logger=_quiet())
    state_dst = tr_dst.restore_or_init()
    _assert_states_match(tr_src, state_src, tr_dst, state_dst)

    # physically sharded over the NEW mesh
    padded_t = padded_flat_size(total, dst_n)
    vec = [l for l in jax.tree.leaves(state_dst.opt_state)
           if getattr(l, "ndim", 0) == 1 and l.shape[0] == padded_t]
    assert vec, "expected a repartitioned momentum trace"
    for leaf in vec:
        assert leaf.sharding.spec == P("data")
        assert {s.data.shape for s in leaf.addressable_shards} == \
            {(padded_t // dst_n,)}

    _one_more_step(tr_dst, state_dst)


@pytest.mark.slow
def test_ema_state_across_mesh_sizes(devices8, tmp_path):
    """EMA trees ride the cross-topology restore like params (replicated):
    save ZeRO-1 + EMA on 8 devices, restore on 4 — averages bit-identical,
    training continues with the EMA update live."""
    cfg = _cfg(tmp_path / "ck_ema", zero1=True)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, ema_decay=0.9))
    tr_src, state_src = _train_and_save(cfg, 8)
    assert state_src.ema_params is not None

    tr_dst = Trainer(cfg, mesh=_mesh(4), logger=_quiet())
    state_dst = tr_dst.restore_or_init()
    _assert_states_match(tr_src, state_src, tr_dst, state_dst)
    # host snapshot BEFORE stepping — the train step donates its input state
    ema_restored = jax.device_get(state_dst.ema_params)
    for a, b in zip(jax.tree.leaves(jax.device_get(state_src.ema_params)),
                    jax.tree.leaves(ema_restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state_dst2 = _one_more_step(tr_dst, state_dst)
    # EMA kept moving after the restore
    assert any(not np.array_equal(a, b) for a, b in zip(
        jax.tree.leaves(ema_restored),
        jax.tree.leaves(jax.device_get(state_dst2.ema_params))))


def test_zero1_to_replicated_migration(devices8, tmp_path):
    cfg_z = _cfg(tmp_path / "ck_z", zero1=True)
    tr_z, state_z = _train_and_save(cfg_z, 8)

    cfg_r = dataclasses.replace(
        cfg_z, mesh=MeshConfig(num_data=0, shard_opt_state=False))
    tr_r = Trainer(cfg_r, mesh=_mesh(8), logger=_quiet())
    state_r = tr_r.restore_or_init()
    _assert_states_match(tr_z, state_z, tr_r, state_r)
    # replicated layout: opt-state leaves mirror the params tree
    p_shapes = [l.shape for l in jax.tree.leaves(state_r.params)]
    trace_shapes = [l.shape for l in jax.tree.leaves(state_r.opt_state)
                    if getattr(l, "ndim", 0) >= 1]
    assert trace_shapes == p_shapes
    _one_more_step(tr_r, state_r)


@pytest.mark.slow
def test_replicated_to_zero1_migration(devices8, tmp_path):
    cfg_r = _cfg(tmp_path / "ck_r", zero1=False)
    tr_r, state_r = _train_and_save(cfg_r, 8)

    cfg_z = dataclasses.replace(
        cfg_r, mesh=MeshConfig(num_data=0, shard_opt_state=True))
    tr_z = Trainer(cfg_z, mesh=_mesh(8), logger=_quiet())
    state_z = tr_z.restore_or_init()
    _assert_states_match(tr_r, state_r, tr_z, state_z)

    total = flat_param_count(jax.device_get(state_z.params))
    padded = padded_flat_size(total, 8)
    vec = [l for l in jax.tree.leaves(state_z.opt_state)
           if getattr(l, "ndim", 0) == 1 and l.shape[0] == padded]
    assert vec
    for leaf in vec:
        assert leaf.sharding.spec == P("data")
    _one_more_step(tr_z, state_z)


def test_same_topology_uses_fast_path(devices8, tmp_path, monkeypatch):
    """Shapes equal → plain Orbax restore; the conversion must not run."""
    import distributed_vgg_f_tpu.checkpoint.retopology as retopo

    cfg = _cfg(tmp_path / "ck_fast", zero1=True)
    _train_and_save(cfg, 8)

    def _boom(*a, **k):
        raise AssertionError("conversion ran on the fast path")

    monkeypatch.setattr(retopo, "convert_opt_state", _boom)
    tr2 = Trainer(cfg, mesh=_mesh(8), logger=_quiet())
    state = tr2.restore_or_init()
    assert int(jax.device_get(state.step)) == 2


@pytest.mark.slow
def test_restore_from_best_across_mesh_sizes(devices8, tmp_path):
    """The best-eval slot restores across topologies too: a ZeRO-1 run on 8
    devices plants the best slot; a 4-device ZeRO-1 trainer with
    train.restore_from_best=true restores it (score-selected) with the opt
    state repartitioned."""
    cfg = _cfg(tmp_path / "ck_best", zero1=True)
    tr8, state8 = _train_and_save(cfg, 8)
    best = tr8._make_best_manager()
    assert best.save(state8, force=True,
                     extra={"eval_top1": 0.8, "step": 2},
                     metrics={"eval_top1": 0.8})
    best.wait()

    cfg4 = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, restore_from_best=True))
    tr4 = Trainer(cfg4, mesh=_mesh(4), logger=_quiet())
    state4 = tr4.restore_or_init()
    assert tr4._restored_from_best
    _assert_states_match(tr8, state8, tr4, state4)
    _one_more_step(tr4, state4)


def test_mismatched_optimizer_chain_fails_loudly(devices8, tmp_path):
    """A checkpoint whose opt-state shapes match neither the current
    topology nor a reconstruction of the saved layout (here: written by a
    momentum-free optimizer) must raise a clear error, not restore garbage."""
    import optax

    from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager
    from distributed_vgg_f_tpu.checkpoint.retopology import (
        restore_any_topology)
    from distributed_vgg_f_tpu.train.state import TrainState

    cfg = _cfg(tmp_path / "ck_mismatch", zero1=True)
    tr = Trainer(cfg, mesh=_mesh(8), logger=_quiet())
    template = tr.init_state()

    # write a checkpoint with a DIFFERENT optimizer chain (no momentum trace)
    import jax.numpy as jnp
    plain_tx = optax.sgd(learning_rate=0.1)
    alien = TrainState.create(tr.model, plain_tx, jax.random.key(0),
                              jnp.zeros((1, 32, 32, 3), jnp.float32))
    mgr = CheckpointManager(str(tmp_path / "alien"), max_to_keep=1)
    assert mgr.save(alien, force=True)
    mgr.wait()

    with pytest.raises(ValueError, match="optimizer chain"):
        restore_any_topology(
            mgr, template, tr.tx,
            opt_shardings=tr._state_sharding().opt_state,
            target_padded=tr._padded)
