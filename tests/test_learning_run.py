"""End-to-end learning demonstration (VERDICT r1 missing #2).

`benchmarks/runs/smoke_cifar10/metrics.jsonl` is the committed log of a real
fit→eval→checkpoint run of the `vggf_cifar10_smoke` config (BASELINE config
#1; synthetic class-separable CIFAR fallback, data/cifar10.py) on this
machine's TPU chip — produced by:

    python train.py --config vggf_cifar10_smoke \
        --set train.steps=3000 --set train.eval_every_steps=500 \
        --set train.checkpoint_dir=<run dir>

This test asserts the artifact shows the framework actually LEARNING through
the full loop: eval top-1 climbs from chance (~10%) to >60%.
"""

import json
import os

import pytest

RUNS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks", "runs")


def _load_records(rel_path: str):
    """Records from a committed run log, `rel_path` relative to RUNS_DIR
    (a directory name implies its metrics.jsonl)."""
    if not rel_path.endswith(".jsonl"):
        rel_path = os.path.join(rel_path, "metrics.jsonl")
    path = os.path.join(RUNS_DIR, rel_path)
    if not os.path.exists(path):
        pytest.fail(f"committed learning-run log missing: {path}")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture(scope="module")
def run_records():
    return _load_records("smoke_cifar10")


def test_run_covers_full_loop(run_records):
    kinds = {r["event"] for r in run_records}
    assert "start" in kinds
    assert "train" in kinds
    assert "eval" in kinds


def test_eval_top1_climbs_past_60_percent(run_records):
    evals = [r for r in run_records if r["event"] == "eval"]
    assert len(evals) >= 3, "need a curve, not a point"
    top1 = [e["eval_top1"] for e in evals]
    # ends well above the VERDICT bar, having climbed from the first eval
    # (the task is learned fast — 58.5% by the first eval at step 500)
    assert top1[-1] > 0.60, f"final eval top-1 {top1[-1]:.3f} <= 0.60"
    assert top1[-1] > top1[0]
    # the curve climbs: final beats every point in the first half
    half = top1[:max(1, len(top1) // 2)]
    assert top1[-1] > max(half)


def test_eval_scored_exact_split(run_records):
    evals = [r for r in run_records if r["event"] == "eval"]
    assert all(e["eval_examples"] == 10_000 for e in evals)


def test_train_loss_decreases(run_records):
    train = [r for r in run_records if r["event"] == "train"]
    assert len(train) >= 10
    first = sum(r["loss"] for r in train[:3]) / 3
    last = sum(r["loss"] for r in train[-3:]) / 3
    assert last < first * 0.7


# ---------------------------------------------------------------------------
# Round-2 artifact: learning through the REAL ImageNet input path (native
# TFRecord index -> ranged libjpeg decode -> packed space-to-depth batches ->
# train -> exact eval -> checkpoint). See the run dir's README for the exact
# command and dataset.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def imagenet_run_records():
    return _load_records("imagenet_path_smoke")


def test_imagenet_path_learns_to_100_percent(imagenet_run_records):
    evals = [r for r in imagenet_run_records if r["event"] == "eval"]
    assert len(evals) >= 6
    top1 = [e["eval_top1"] for e in evals]
    assert top1[0] < 0.7            # starts partially trained at the least
    assert max(top1) == 1.0         # reaches perfect on the separable task
    assert all(t == 1.0 for t in top1[-4:])  # and HOLDS (no late divergence)
    # exact eval: every pass scores exactly the 160-example split
    assert all(e["eval_examples"] == 160 for e in evals)


def test_imagenet_path_full_loop(imagenet_run_records):
    kinds = {r["event"] for r in imagenet_run_records}
    assert {"start", "train", "eval"} <= kinds
    start = next(r for r in imagenet_run_records if r["event"] == "start")
    assert start["config"] == "vggf_imagenet_dp"


# ---------------------------------------------------------------------------
# Round-2 zoo artifacts: every non-flagship BASELINE model family learning
# end-to-end on the chip over the same separable dataset (see
# benchmarks/runs/zoo_smoke/README.md for commands and the VGG-16 clipping
# note).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("run_file,config,final_floor", [
    ("resnet50.jsonl", "resnet50_imagenet", 0.98),
    ("vit_s16.jsonl", "vit_s16_imagenet", 0.99),
    ("vgg16.jsonl", "vgg16_imagenet", 0.99),
])
def test_zoo_family_learns(run_file, config, final_floor):
    recs = _load_records(os.path.join("zoo_smoke", run_file))
    start = next(r for r in recs if r["event"] == "start")
    assert start["config"] == config
    evals = [r for r in recs if r["event"] == "eval"]
    assert len(evals) >= 5
    assert all(e["eval_examples"] == 160 for e in evals)
    top1 = [e["eval_top1"] for e in evals]
    assert top1[-1] >= final_floor, f"{run_file}: final {top1[-1]:.3f}"
