"""Per-op achievable-MFU arithmetic (utils/mxu_model.py) — VERDICT r4 #3.

Two obligations: (1) the roofline algebra is right (hand-checkable fills,
bracket ordering, wall identification); (2) the model inventories match the
REAL shipped models — each inventory's forward FLOPs are pinned against the
jaxpr counter tracing the actual Flax module, so the arithmetic cannot
drift from the code it claims to describe.
"""

import jax
import jax.numpy as jnp
import pytest

from distributed_vgg_f_tpu.utils.mxu_model import (
    GemmView, INVENTORIES, achievable_mfu, bwd_views, ceiling_bracket,
    conv_view, headroom_table, mxu_fill, mxu_fill_bound, serial_mfu,
    train_views)


def test_mxu_fill_hand_cases():
    # aligned GEMM fills completely
    assert mxu_fill(1024, 256, 512) == pytest.approx(1.0)
    # K=64 wastes half the contraction depth; N=64 half the lanes
    assert mxu_fill(1024, 64, 64) == pytest.approx(0.25)
    # T=197 on sublanes: 197/200; on lanes: 197/256
    assert mxu_fill(197, 128, 128) == pytest.approx(197 / 200)
    assert mxu_fill(1024, 128, 197) == pytest.approx(197 / 256)
    # fills never exceed 1 and never hit 0
    for m, k, n in [(1, 1, 1), (7, 13, 1000), (8192, 3, 64)]:
        assert 0 < mxu_fill(m, k, n) <= 1.0


def test_bwd_views_are_the_gemm_calculus():
    v = GemmView("x", m=512, k=64, n=256)
    dA, dB = bwd_views(v)
    # dA = dC·Bᵀ: (M, N, K); dB = Aᵀ·dC: (K, M, N)
    assert (dA.m, dA.k, dA.n) == (512, 256, 64)
    assert (dB.m, dB.k, dB.n) == (64, 512, 256)
    # each backward GEMM costs exactly the forward's FLOPs
    assert dA.flops == v.flops and dB.flops == v.flops
    assert len(train_views([v])) == 3


def test_conv_view_bytes_are_real_tensors_not_im2col():
    # 3x3 conv, 64ch, 56x56, batch 8: the im2col operand (M·K) would be
    # 9x the input tensor; the byte model must charge the real tensors
    v = conv_view("c", batch=8, out_hw=56, cin=64, cout=64, kh=3)
    expect = 2 * (8 * 56 * 56 * 64 + 3 * 3 * 64 * 64 + 8 * 56 * 56 * 64)
    assert v.hbm_bytes == expect
    assert v.hbm_bytes < 2 * v.m * v.k  # im2col would dwarf it


def test_bracket_ordering_and_walls():
    views = train_views(INVENTORIES["resnet50"](256))
    fill = mxu_fill_bound(views)
    roof = achievable_mfu(views)
    serial = serial_mfu(views)
    # serial <= overlap <= fill-only, all in (0, 1]
    assert 0 < serial <= roof <= fill <= 1.0
    lo, hi = ceiling_bracket(views, 0.802)
    assert (lo, hi) == (pytest.approx(serial * 0.802),
                        pytest.approx(roof * 0.802))
    # the r4-measured 0.364 sits inside the derived bracket — THE claim
    assert lo <= 0.364 <= hi
    # the trace's top sinks (stage1/2 backward convs) must surface as
    # HBM-walled rows high in the headroom table
    rows = headroom_table(views)
    top8 = rows[:8]
    assert any(r["wall"] == "hbm" and r["name"].startswith(("s1", "s2"))
               for r in top8), top8


def test_vit_bracket_holds_measurement():
    views = train_views(INVENTORIES["vit_s16"](256))
    lo, hi = ceiling_bracket(views, 0.5687)
    assert lo <= 0.267 <= hi
    # the attention einsums' 64-wide head dim is a visible fill loss
    score = next(v for v in views if v.name == "scores_qk")
    assert score.fill < 0.45  # 0.5 (K=64) x 197/256 (N) x 197/200 (M)


def test_bad_matmul_fraction_rejected():
    views = train_views(INVENTORIES["vggf"](32))
    with pytest.raises(ValueError, match="matmul_fraction"):
        ceiling_bracket(views, 0.0)
    with pytest.raises(ValueError, match="matmul_fraction"):
        ceiling_bracket(views, 1.2)


# ---------------------------------------------------------------------------
# Inventories vs the real models: forward FLOPs must match the jaxpr count
# ---------------------------------------------------------------------------


def _model_fwd_flops(name: str, batch: int, num_classes: int = 1000):
    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models import build_model
    from distributed_vgg_f_tpu.utils.flops import jaxpr_flops

    model = build_model(ModelConfig(name=name, num_classes=num_classes,
                                    compute_dtype="float32"))
    x = jnp.zeros((batch, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), x, train=False))
    variables = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), variables)
    return jaxpr_flops(
        lambda v, im: model.apply(v, im, train=False), variables, x)


@pytest.mark.parametrize("name,batch", [
    ("resnet50", 2), ("vit_s16", 2), ("vggf", 2), ("vgg16", 2)])
def test_inventory_matches_traced_model(name, batch):
    """The inventory is only a derivation if it describes the shipped
    module: forward GEMM FLOPs within 2% of the traced jaxpr count (the
    slack covers count-free extras like ViT's cls-token row and attention
    scale)."""
    inventory = sum(v.flops for v in INVENTORIES[name](batch))
    traced = _model_fwd_flops(name, batch)
    assert traced > 0
    assert abs(inventory - traced) / traced < 0.02, (
        f"{name}: inventory {inventory:.3e} vs traced {traced:.3e}")


@pytest.mark.parametrize("name,batch", [
    ("resnet50", 256), ("vit_s16", 256), ("vgg16", 128)])
def test_views_from_jaxpr_matches_hand_inventory(name, batch):
    """The automatic extractor (any-model roofline) against the validated
    hand inventories, at the real bench operating points with bf16
    compute: FLOPs exact, bounds within 1%. (VGG-F is excluded from the
    bound equality: its traced program runs LRN statistics and the stem
    pack in fp32, which the extractor charges faithfully and the bf16
    hand inventory deliberately does not.)"""
    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models import build_model
    from distributed_vgg_f_tpu.utils.mxu_model import (
        achievable_mfu, serial_mfu, views_from_jaxpr)

    model = build_model(ModelConfig(name=name, num_classes=1000,
                                    compute_dtype="bfloat16"))
    x = jnp.zeros((batch, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), x, train=False))
    variables = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), variables)
    auto = views_from_jaxpr(
        lambda v, im: model.apply(v, im, train=False), variables, x)
    hand = INVENTORIES[name](batch)
    assert sum(v.flops for v in auto) == pytest.approx(
        sum(v.flops for v in hand), rel=1e-6)
    assert achievable_mfu(auto) == pytest.approx(
        achievable_mfu(hand), rel=0.01)
    assert serial_mfu(auto) == pytest.approx(serial_mfu(hand), rel=0.01)


def test_roofline_report_any_model():
    """The one-call surface works on a computation this module has no
    inventory for (incl. backward via grad) and names the binding wall."""
    from distributed_vgg_f_tpu.utils.mxu_model import roofline_report

    def step(w1, w2, x):
        h = jnp.maximum(x @ w1, 0.0)
        return jnp.sum((h @ w2) ** 2)

    w1 = jnp.zeros((256, 512), jnp.bfloat16)
    w2 = jnp.zeros((512, 64), jnp.bfloat16)
    x = jnp.zeros((1024, 256), jnp.bfloat16)
    rep = roofline_report(jax.grad(step, argnums=(0, 1)), w1, w2, x)
    assert rep["gemm_views"] >= 4          # fwd x2 + bwd pairs
    assert 0 < rep["roofline_serial_bound"] \
        <= rep["roofline_overlap_bound"] <= rep["mxu_fill_bound"] <= 1
    assert all(r["wall"] in ("mxu", "hbm") for r in rep["top_ops"])


def test_views_from_jaxpr_depthwise_conv_groups():
    """A depthwise conv is `groups` independent N=1 GEMMs, not one wide
    one — modeling it as N=cout would overstate fill ~groups× for
    MobileNet-style models (code-review r5)."""
    from jax import lax

    from distributed_vgg_f_tpu.utils.mxu_model import views_from_jaxpr

    def depthwise(x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), "SAME", feature_group_count=32,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    x = jnp.zeros((2, 8, 8, 32), jnp.bfloat16)
    w = jnp.zeros((3, 3, 1, 32), jnp.bfloat16)
    v, = views_from_jaxpr(depthwise, x, w)
    assert (v.m, v.k, v.n, v.count) == (2 * 8 * 8, 9, 1, 32)
    assert v.flops == 2.0 * 128 * 9 * 1 * 32
    assert v.fill < 0.01                  # N=1 of 128 lanes
