"""Device-prefetch tests (SURVEY.md §7 hard parts: input pipeline throughput —
the H2D overlap must not change training semantics)."""

import io

import jax
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.data.prefetch import (
    DevicePrefetchIterator, maybe_prefetch)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh, \
    shard_host_batch
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


@pytest.fixture()
def mesh(devices8):
    return build_mesh(MeshSpec(("data",), (8,)), devices=devices8)


def test_prefetch_yields_same_batches_as_sync(mesh):
    src_a = SyntheticDataset(batch_size=16, image_size=8, num_classes=10, seed=3)
    src_b = SyntheticDataset(batch_size=16, image_size=8, num_classes=10, seed=3)
    pre = DevicePrefetchIterator(src_a, mesh, buffer_size=2)
    try:
        for _ in range(4):
            got = next(pre)
            want = shard_host_batch(next(src_b), mesh)
            for k in want:
                np.testing.assert_array_equal(jax.device_get(got[k]),
                                              jax.device_get(want[k]))
            assert got["image"].sharding.spec == want["image"].sharding.spec
    finally:
        pre.close()


def test_prefetch_propagates_stop_iteration(mesh):
    def finite():
        yield {"image": np.zeros((8, 4, 4, 3), np.float32),
               "label": np.zeros((8,), np.int32)}

    pre = DevicePrefetchIterator(finite(), mesh, buffer_size=2)
    next(pre)
    with pytest.raises(StopIteration):
        next(pre)
    # Exhausted iterator stays exhausted.
    with pytest.raises(StopIteration):
        next(pre)


def test_prefetch_propagates_source_error(mesh):
    def broken():
        yield {"image": np.zeros((8, 4, 4, 3), np.float32),
               "label": np.zeros((8,), np.int32)}
        raise RuntimeError("decode failed")

    pre = DevicePrefetchIterator(broken(), mesh, buffer_size=2)
    next(pre)
    with pytest.raises(RuntimeError, match="decode failed"):
        next(pre)


def test_maybe_prefetch_zero_is_synchronous(mesh):
    src = SyntheticDataset(batch_size=16, image_size=8, num_classes=10, seed=0)
    it = maybe_prefetch(src, mesh, buffer_size=0)
    batch = next(it)
    assert batch["image"].sharding.spec == shard_host_batch(
        next(src), mesh)["image"].sharding.spec


def _tiny_cfg(prefetch: int) -> ExperimentConfig:
    return ExperimentConfig(
        name="prefetch_equiv",
        model=ModelConfig(name="vggf", num_classes=10, compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=256),
        mesh=MeshConfig(num_data=8),
        train=TrainConfig(steps=4, seed=0, log_every=1,
                          prefetch_to_device=prefetch),
    )


@pytest.mark.slow
def test_fit_with_prefetch_matches_sync(devices8):
    """Training with the H2D overlap must be bit-identical to without it."""
    params = {}
    for prefetch in (2, 0):
        mesh = build_mesh(MeshSpec(("data",), (8,)), devices=devices8)
        trainer = Trainer(_tiny_cfg(prefetch), mesh=mesh,
                          logger=MetricLogger(stream=io.StringIO()))
        state = trainer.fit(trainer.init_state())
        params[prefetch] = jax.device_get(state.params)
    flat_a = jax.tree.leaves(params[2])
    flat_b = jax.tree.leaves(params[0])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(a, b)


def test_prefetch_refuses_buffer_reusing_source(mesh):
    """Buffer-ownership contract (r7): an iterator that recycles its output
    arrays (native_jpeg/native_loader enable_output_buffer_reuse — bench-
    only) must be refused by device prefetch, whose async device_put may
    still be reading (or aliasing) the host batch when the ring would
    overwrite it."""

    class _RingSource:
        reuses_output_buffers = True

        def __iter__(self):
            return self

        def __next__(self):
            return {"image": np.zeros((8, 4, 4, 3), np.float32)}

    with pytest.raises(ValueError, match="reuse"):
        DevicePrefetchIterator(_RingSource(), mesh, buffer_size=2)
    # the synchronous fallback path (buffer_size=0) has no overlap and
    # stays usable for such sources
    it = maybe_prefetch(_RingSource(), mesh, buffer_size=0)
    assert next(iter(it)) is not None
