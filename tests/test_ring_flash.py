"""Ring × flash (parallel/ring_flash.py): the Pallas blockwise kernels under
the ppermute ring schedule must be exactly full attention — forward AND
gradients — on 2/4/8-device meshes, both masking modes. Kernels run in the
Pallas interpreter on the CPU mesh (same convention as test_lrn_pallas.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.ops import flash_attention as fa
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.parallel.ring_attention import (
    full_attention_reference)
from distributed_vgg_f_tpu.parallel.ring_flash import ring_flash_attention


@pytest.fixture(autouse=True)
def _interpret_kernels():
    old = fa.INTERPRET
    fa.INTERPRET = True
    yield
    fa.INTERPRET = old


def _qkv(dtype=jnp.float32, b=2, t=64, h=2, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32).astype(dtype)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full_attention(devices8, causal):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv()
    got = np.asarray(ring_flash_attention(q, k, v, mesh, causal=causal))
    want = np.asarray(full_attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [2, 4])
def test_ring_flash_gradients(devices8, n, causal):
    """Backward = second ring pass with dK/dV accumulators traveling with
    their blocks; one final hop brings them home. Must equal the oracle's
    gradients on every mesh size."""
    mesh = build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])
    q, k, v = _qkv(t=32, seed=7 + n)

    g_ring = jax.grad(lambda *a: jnp.sum(
        ring_flash_attention(*a, mesh, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


def test_ring_flash_gradients_8dev_causal(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=64, seed=3)
    g_ring = jax.grad(lambda *a: jnp.sum(
        ring_flash_attention(*a, mesh, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for g, r in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-5, atol=5e-5)


def test_ring_flash_bf16(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(jnp.bfloat16)
    got = np.asarray(ring_flash_attention(q, k, v, mesh), np.float32)
    want = np.asarray(full_attention_reference(q, k, v), np.float32)
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)


def test_ring_flash_non_pow2_local_length(devices8):
    """The code-review r3 scenario: T=384 on a 2-device mesh leaves
    t_loc=192 per device — the kernels must auto-pick a dividing block
    (64) instead of failing a min(128, t)-clamp divisibility check."""
    mesh = build_mesh(MeshSpec(("data",), (2,)), devices=jax.devices()[:2])
    q, k, v = _qkv(t=384, b=1, h=1, d=8, seed=5)
    got = np.asarray(ring_flash_attention(q, k, v, mesh, causal=True))
    want = np.asarray(full_attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_flash_rejects_indivisible(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=60)
    with pytest.raises(ValueError, match="not divisible"):
        ring_flash_attention(q, k, v, mesh)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_prime_local_length(devices8, causal):
    """T=394 on 2 devices → t_loc=197, PRIME: the motivating case of
    VERDICT r4 weak #4. Each shard now pads to 256 with block 128 (the
    plan pad_to_block commits to — asserted here, block ≥ 64) instead of
    degrading to a block-1 grid. Exact incl. grads across the ring."""
    from distributed_vgg_f_tpu.ops.flash_attention import pad_to_block

    t_pad, block = pad_to_block(394 // 2)
    assert block >= 64 and (t_pad, block) == (256, 128)

    mesh = build_mesh(MeshSpec(("data",), (2,)), devices=jax.devices()[:2])
    q, k, v = _qkv(t=394, b=1, h=1, d=16, seed=11)
    got = np.asarray(ring_flash_attention(q, k, v, mesh, causal=causal))
    want = np.asarray(full_attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    g_ring = jax.grad(lambda *a: jnp.sum(
        ring_flash_attention(*a, mesh, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")
