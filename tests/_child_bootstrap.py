"""Shared pre-import bootstrap for multi-process test CHILDREN.

Every subprocess child must pin the CPU platform and its virtual device
count BEFORE importing jax (this machine's sitecustomize pins the TPU
tunnel; pytest's conftest exports its own 8-device XLA_FLAGS that children
may need to override), and multi-process children must wire the Gloo
coordinator. One helper, so the bootstrap cannot silently diverge between
children (code-review r3: four hand-copies had already grown differences —
only one had the shared compile cache).

Must be imported (and `bootstrap()` called) before anything that imports
jax.
"""

from __future__ import annotations

import hashlib
import os
import platform
import re


def default_cache_dir() -> str:
    """Persistent-compile-cache path keyed by the host's CPU feature set.

    XLA:CPU cache entries are AOT machine code for the COMPILING host's
    featureset; on a box whose VM migrates across heterogeneous hardware a
    stale entry loads with a `cpu_aot_loader` feature-mismatch warning and
    then miscomputes (observed r3: cached ViT train step returned loss=nan
    with finite logits — every fresh compile was correct). Keying the dir by
    a fingerprint of /proc/cpuinfo flags makes a migrated host start a new
    cache instead of executing another machine's code."""
    fingerprint = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    fingerprint = hashlib.md5(line.encode()).hexdigest()[:10]
                    break
    except OSError:
        pass
    return os.environ.get("DVGGF_TEST_CACHE_DIR",
                          f"/tmp/dvggf_test_xla_cache_{fingerprint}")


def bootstrap(num_local_devices: int, *, coordinator_port=None,
              num_processes: int | None = None,
              process_id: int | None = None):
    """Pin CPU + device count and (when a coordinator port is given)
    initialize the distributed runtime. SINGLE-process children share the
    suite's persistent compile cache (safe because train/step.py disables
    buffer donation on CPU — cached donating executables reloaded after an
    Orbax restore corrupt the heap, see conftest.py); multi-process
    children deliberately run WITHOUT one (see the skew rationale below).
    Returns the configured `jax` module."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count="
        f"{num_local_devices}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    # Compile-skew discipline. Multi-process children get NO persistent
    # compile cache — every rank compiles every program, which is SLOWER but
    # SYMMETRIC. With a cache, jax writes entries only from process 0
    # (jax/_src/compiler.py _cache_write) and on this backend the ranks'
    # cache keys differ anyway (verified: share_binary_between_hosts
    # deadlocks waiting for a key the other rank never publishes), so rank 0
    # hits in ~0.5 s while other ranks recompile ~10 s — and that skew,
    # stacked across phases, lands a waiting rank in Gloo's fixed ~30 s TCP
    # read window mid-collective (reproduced deterministically with
    # DVGGF_CHILD_DEBUG=1 phase timestamps). Symmetric compilation keeps
    # inter-rank skew at execution noise (~1-2 s).
    if coordinator_port is None:  # the direct multi-process signal —
        # process_id could legitimately be None with env auto-detection
        jax.config.update("jax_compilation_cache_dir", default_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    if coordinator_port is not None:
        from distributed_vgg_f_tpu.parallel.distributed import (
            initialize_distributed)
        initialize_distributed(
            coordinator_address=f"127.0.0.1:{coordinator_port}",
            num_processes=num_processes, process_id=process_id)
    return jax


def run_ring_phase(jax, nproc: int, pid: int, n_local: int, *,
                   seed: int = 42, batch: int = 1) -> dict:
    """Sequence-parallel attention across REAL process boundaries — shared
    by the 2- and 4-process children (one copy, code-review r3): einsum
    ring and ring × flash (interpreted Pallas kernels), causal forward
    exactness vs the oracle, and finiteness of ALL THREE flash-backward
    cotangents (the dK/dV accumulators travel the ring with their blocks);
    plus the Ulysses all-to-all layout — `lax.all_to_all` crosses the
    process boundary, a different Gloo collective than the ring's
    neighbor ppermute. Returns {"ring_ok", "ring_flash_ok",
    "ring_flash_grad_finite", "ulysses_ok", "ulysses_grads_ok"}."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_vgg_f_tpu.ops import flash_attention as fa
    from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
    from distributed_vgg_f_tpu.parallel.ring_attention import (
        full_attention_reference, ring_attention)
    from distributed_vgg_f_tpu.parallel.ring_flash import ring_flash_attention

    n_dev = n_local * nproc
    mesh_r = build_mesh(MeshSpec(("data",), (n_dev,)))
    T = 8 * n_dev
    rng_r = np.random.default_rng(seed)   # same arrays on every process
    qg, kg, vg = (rng_r.standard_normal((batch, T, 2, 8)).astype(np.float32)
                  for _ in range(3))
    sharding = NamedSharding(mesh_r, P(None, "data"))
    t_proc = T // nproc

    def to_global(x, t_per_proc=t_proc):
        # shared by the ring block (T = 8·n_dev) and the ulysses block
        # (T = 4·n_dev): this process's contiguous sequence slice, lifted
        # into the mesh-global sharded array
        return jax.make_array_from_process_local_data(
            sharding, x[:, pid * t_per_proc:(pid + 1) * t_per_proc])

    def local_slice(arr):
        return np.concatenate(
            [s.data for s in sorted(arr.addressable_shards,
                                    key=lambda s: s.index[1].start)], axis=1)

    want = np.asarray(full_attention_reference(
        *(jax.numpy.asarray(x) for x in (qg, kg, vg)),
        causal=True))[:, pid * t_proc:(pid + 1) * t_proc]
    got = ring_attention(*(to_global(x) for x in (qg, kg, vg)),
                         mesh_r, causal=True)
    ring_ok = bool(np.allclose(local_slice(got), want, rtol=2e-5, atol=2e-5))

    old_interpret = fa.INTERPRET
    fa.INTERPRET = True
    try:
        flash_got = ring_flash_attention(
            *(to_global(x) for x in (qg, kg, vg)), mesh_r, causal=True)
        ring_flash_ok = bool(np.allclose(local_slice(flash_got), want,
                                         rtol=2e-5, atol=2e-5))
        grads = jax.grad(lambda q, k, v: jax.numpy.sum(
            ring_flash_attention(q, k, v, mesh_r) ** 2), argnums=(0, 1, 2))(
            *(to_global(x) for x in (qg, kg, vg)))
        ring_flash_grad_finite = all(
            bool(np.isfinite(np.concatenate(
                [s.data for s in g.addressable_shards], axis=None)).all())
            for g in grads)
    finally:
        fa.INTERPRET = old_interpret

    # Ulysses: heads shard across the axis, so H = n_dev (the layout's own
    # constraint); T stays a multiple of the axis. Same every-process
    # arrays, same per-process sequence slicing as the ring block above.
    from distributed_vgg_f_tpu.parallel.ulysses import ulysses_attention

    t_u = 4 * n_dev
    qu, ku, vu = (rng_r.standard_normal(
        (batch, t_u, n_dev, 8)).astype(np.float32) for _ in range(3))
    tu_proc = t_u // nproc
    want_u = np.asarray(full_attention_reference(
        *(jax.numpy.asarray(x) for x in (qu, ku, vu)),
        causal=True))[:, pid * tu_proc:(pid + 1) * tu_proc]
    got_u = ulysses_attention(*(to_global(x, tu_proc) for x in (qu, ku, vu)),
                              mesh_r, causal=True)
    ulysses_ok = bool(np.allclose(local_slice(got_u), want_u,
                                  rtol=2e-5, atol=2e-5))
    # backward: the output all_to_all transposes to its inverse, so grads
    # send a SECOND set of all_to_alls across the process boundary. Checked
    # against the oracle's gradients SLICED per process (the want_u
    # pattern), causal=True like the forward check — finiteness alone would
    # pass a Gloo-boundary transpose-ordering bug producing wrong-but-
    # finite values (ADVICE r4).
    grads_u = jax.grad(lambda q, k, v: jax.numpy.sum(
        ulysses_attention(q, k, v, mesh_r, causal=True) ** 2),
        argnums=(0, 1, 2))(
        *(to_global(x, tu_proc) for x in (qu, ku, vu)))
    want_gu = jax.grad(lambda q, k, v: jax.numpy.sum(
        full_attention_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(*(jax.numpy.asarray(x) for x in (qu, ku, vu)))
    ulysses_grads_ok = all(
        bool(np.allclose(
            local_slice(g),
            np.asarray(w)[:, pid * tu_proc:(pid + 1) * tu_proc],
            rtol=5e-5, atol=5e-5))
        for g, w in zip(grads_u, want_gu))
    return {"ring_ok": ring_ok, "ring_flash_ok": ring_flash_ok,
            "ring_flash_grad_finite": ring_flash_grad_finite,
            "ulysses_ok": ulysses_ok,
            "ulysses_grads_ok": ulysses_grads_ok}
