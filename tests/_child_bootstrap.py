"""Shared pre-import bootstrap for multi-process test CHILDREN.

Every subprocess child must pin the CPU platform and its virtual device
count BEFORE importing jax (this machine's sitecustomize pins the TPU
tunnel; pytest's conftest exports its own 8-device XLA_FLAGS that children
may need to override), and multi-process children must wire the Gloo
coordinator. One helper, so the bootstrap cannot silently diverge between
children (code-review r3: four hand-copies had already grown differences —
only one had the shared compile cache).

Must be imported (and `bootstrap()` called) before anything that imports
jax.
"""

from __future__ import annotations

import os
import re


def bootstrap(num_local_devices: int, *, coordinator_port=None,
              num_processes: int | None = None,
              process_id: int | None = None):
    """Pin CPU + device count, share the suite's persistent compile cache,
    and (when a coordinator port is given) initialize the distributed
    runtime. Returns the configured `jax` module."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count="
        f"{num_local_devices}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DVGGF_TEST_CACHE_DIR",
                                     "/tmp/dvggf_test_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    if coordinator_port is not None:
        from distributed_vgg_f_tpu.parallel.distributed import (
            initialize_distributed)
        initialize_distributed(
            coordinator_address=f"127.0.0.1:{coordinator_port}",
            num_processes=num_processes, process_id=process_id)
    return jax
