"""Profiling subsystem (utils/profiling.py): trace window start/stop mechanics
and end-to-end capture through Trainer.fit (SURVEY.md §5 tracing)."""

import glob
import io
import os

from distributed_vgg_f_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger
from distributed_vgg_f_tpu.utils.profiling import StepProfiler, annotate


def test_step_profiler_window(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: calls.append(("stop",)))
    prof = StepProfiler(str(tmp_path), start_step=3, num_steps=2)
    for i in range(10):
        prof.step(i)
    prof.stop()  # idempotent
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert prof.captured


def test_step_profiler_stops_on_interrupt(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: calls.append("stop"))
    prof = StepProfiler(str(tmp_path), start_step=0, num_steps=100)
    prof.step(0)   # trace opens, window never completes
    prof.stop()    # the trainer's finally-block path
    assert calls == ["start", "stop"]


def test_trainer_fit_captures_real_trace(tmp_path):
    logdir = str(tmp_path / "trace")
    cfg = ExperimentConfig(
        name="profile_test",
        model=ModelConfig(name="vggf", num_classes=10, compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=8,
                        num_train_examples=32),
        mesh=MeshConfig(num_data=8),
        train=TrainConfig(steps=4, seed=0, log_every=100, profile=True,
                          profile_dir=logdir, profile_start_step=1,
                          profile_num_steps=2),
    )
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    trainer.fit(num_steps=4)
    # jax.profiler writes plugins/profile/<run>/ with .xplane.pb files
    traces = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                       recursive=True)
    assert traces, f"no trace files under {logdir}"


def test_annotate_is_usable_inline():
    with annotate("host-feed"):
        pass
