"""Data pipeline tests (SURVEY.md §4): determinism under fixed seed, sharding,
CIFAR-10 pickle loading, ImageNet TFRecord JPEG pipeline on generated fakes."""

import os
import pickle

import numpy as np
import pytest

from distributed_vgg_f_tpu.config import DataConfig
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset


def test_synthetic_deterministic():
    a = SyntheticDataset(8, 16, 10, seed=5)
    b = SyntheticDataset(8, 16, 10, seed=5)
    ba, bb = next(a), next(b)
    np.testing.assert_array_equal(ba["image"], bb["image"])
    np.testing.assert_array_equal(ba["label"], bb["label"])
    c = SyntheticDataset(8, 16, 10, seed=6)
    assert not np.array_equal(next(c)["image"], ba["image"])


def _write_fake_cifar(tmp_path):
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        data = {b"data": rng.integers(0, 256, size=(100, 3072), dtype=np.int64
                                      ).astype(np.uint8),
                b"labels": rng.integers(0, 10, size=100).tolist()}
        with open(tmp_path / f"data_batch_{i}", "wb") as f:
            pickle.dump(data, f)
    data = {b"data": rng.integers(0, 256, size=(80, 3072), dtype=np.int64
                                  ).astype(np.uint8),
            b"labels": rng.integers(0, 10, size=80).tolist()}
    with open(tmp_path / "test_batch", "wb") as f:
        pickle.dump(data, f)


def test_synthetic_labels_follow_model_head():
    """Synthetic labels must stay inside the MODEL's class count: a
    1000-class label against a 10-class head is an out-of-range CE gather
    (surfaced r3 as loss=nan with finite grads under a model.num_classes
    override)."""
    cfg = DataConfig(name="synthetic", image_size=32, global_batch_size=16)
    ds = build_dataset(cfg, "train", seed=0, num_classes=10)
    labels = np.concatenate([next(ds)["label"] for _ in range(8)])
    assert labels.max() < 10 and labels.min() >= 0
    # default (no model hint): the ImageNet-shaped 1000-class space
    ds1k = build_dataset(cfg, "train", seed=0)
    labels1k = np.concatenate([next(ds1k)["label"] for _ in range(8)])
    assert labels1k.max() >= 10


def test_cifar10_from_pickle_files(tmp_path):
    _write_fake_cifar(tmp_path)
    cfg = DataConfig(name="cifar10", data_dir=str(tmp_path), image_size=32,
                     global_batch_size=16, num_train_examples=500)
    ds = build_dataset(cfg, "train", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (16, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (16,)
    # normalized: values roughly centred
    assert abs(float(batch["image"].mean())) < 2.0
    # eval: finite re-iterable, 80 examples in 5 batches of 16, all valid
    ev = build_dataset(cfg, "eval", seed=0)
    batches = list(ev)
    assert len(batches) == 5
    assert batches[0]["image"].shape == (16, 32, 32, 3)
    assert all(b["valid"].all() for b in batches)


def test_cifar10_synthetic_fallback_and_sharding():
    cfg = DataConfig(name="cifar10", data_dir="", image_size=32,
                     global_batch_size=32, num_train_examples=50_000)
    ds0 = build_dataset(cfg, "train", seed=0, num_shards=2, shard_index=0)
    ds1 = build_dataset(cfg, "train", seed=0, num_shards=2, shard_index=1)
    b0, b1 = next(ds0), next(ds1)
    # each host shard gets local_batch = global/num_shards
    assert b0["image"].shape[0] == 16 and b1["image"].shape[0] == 16
    assert not np.array_equal(b0["image"], b1["image"])


def test_cifar10_train_determinism():
    cfg = DataConfig(name="cifar10", data_dir="", image_size=32,
                     global_batch_size=16)
    a = build_dataset(cfg, "train", seed=3)
    b = build_dataset(cfg, "train", seed=3)
    for _ in range(3):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])


# --------------------------------------------------------------------------
# ImageNet TFRecord pipeline on generated fake JPEG records
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fake_imagenet_dir(tmp_path_factory):
    tf = pytest.importorskip("tensorflow")
    root = tmp_path_factory.mktemp("fake_imagenet")
    rng = np.random.default_rng(0)

    def write(split, num_files, per_file):
        for i in range(num_files):
            path = os.path.join(
                root, f"{split}-{i:05d}-of-{num_files:05d}")
            with tf.io.TFRecordWriter(path) as w:
                for _ in range(per_file):
                    img = rng.integers(0, 256, size=(48, 64, 3)).astype(np.uint8)
                    jpeg = tf.io.encode_jpeg(img).numpy()
                    label = int(rng.integers(1, 1001))
                    ex = tf.train.Example(features=tf.train.Features(feature={
                        "image/encoded": tf.train.Feature(
                            bytes_list=tf.train.BytesList(value=[jpeg])),
                        "image/class/label": tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[label])),
                    }))
                    w.write(ex.SerializeToString())

    write("train", 4, 8)
    write("validation", 2, 8)
    return str(root)


def test_imagenet_train_pipeline(fake_imagenet_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagenet_dir,
                     image_size=64, global_batch_size=8, shuffle_buffer=16)
    ds = build_dataset(cfg, "train", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (8, 64, 64, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].min() >= 0 and batch["label"].max() <= 999
    # train pipeline repeats forever
    for _ in range(6):
        next(ds)


def test_imagenet_eval_pipeline(fake_imagenet_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagenet_dir,
                     image_size=64, global_batch_size=4)
    ds = build_dataset(cfg, "eval", seed=0)
    assert ds.is_finite
    batches = list(ds)
    # 16 validation examples in 4 full batches of 4, every row valid
    assert len(batches) == 4
    assert batches[0]["image"].shape == (4, 64, 64, 3)
    assert sum(int(b["valid"].sum()) for b in batches) == 16


def test_imagenet_missing_dir_raises(tmp_path):
    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path),
                     image_size=64, global_batch_size=4)
    with pytest.raises(FileNotFoundError):
        build_dataset(cfg, "train", seed=0)


def test_image_dtype_bfloat16_all_pipelines():
    import ml_dtypes
    bf16 = np.dtype(ml_dtypes.bfloat16)

    syn = build_dataset(DataConfig(name="synthetic", image_size=8,
                                   global_batch_size=4,
                                   image_dtype="bfloat16"), "train")
    assert next(syn)["image"].dtype == bf16

    cif = build_dataset(DataConfig(name="cifar10", image_size=32,
                                   global_batch_size=4,
                                   image_dtype="bfloat16"), "train")
    batch = next(cif)
    assert batch["image"].dtype == bf16
    assert batch["image"].shape == (4, 32, 32, 3)


# --------------------------------------------------------------------------
# ImageNet raw-JPEG directory-per-class layout
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fake_imagefolder_dir(tmp_path_factory):
    tf = pytest.importorskip("tensorflow")
    root = tmp_path_factory.mktemp("fake_imagefolder")
    rng = np.random.default_rng(1)
    for split, per_class in (("train", 6), ("validation", 3)):
        for cls in ("n01440764", "n01443537", "n01484850"):
            d = os.path.join(root, split, cls)
            os.makedirs(d)
            for i in range(per_class):
                img = rng.integers(0, 256, size=(40, 56, 3)).astype(np.uint8)
                with open(os.path.join(d, f"{cls}_{i}.JPEG"), "wb") as f:
                    f.write(tf.io.encode_jpeg(img).numpy())
    return str(root)


def test_imagefolder_train_pipeline(fake_imagefolder_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagefolder_dir,
                     image_size=32, global_batch_size=4, shuffle_buffer=8)
    ds = build_dataset(cfg, "train", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (4, 32, 32, 3)
    # labels are sorted-class-directory indices
    assert batch["label"].min() >= 0 and batch["label"].max() <= 2
    for _ in range(8):  # repeats past one epoch (18 images)
        next(ds)


@pytest.fixture(scope="module")
def fake_flat_val_dir(tmp_path_factory):
    """Real-ImageNet-style layout: train/<wnid>/ dirs + FLAT val/ + label map."""
    tf = pytest.importorskip("tensorflow")
    root = tmp_path_factory.mktemp("fake_flat_imagenet")
    rng = np.random.default_rng(2)
    wnids = ("n01440764", "n01443537", "n01484850")
    for cls in wnids:
        d = os.path.join(root, "train", cls)
        os.makedirs(d)
        img = rng.integers(0, 256, size=(40, 56, 3)).astype(np.uint8)
        with open(os.path.join(d, f"{cls}_0.JPEG"), "wb") as f:
            f.write(tf.io.encode_jpeg(img).numpy())
    val = os.path.join(root, "val")
    os.makedirs(val)
    lines = []
    for i in range(7):
        img = rng.integers(0, 256, size=(40, 56, 3)).astype(np.uint8)
        name = f"ILSVRC2012_val_{i:08d}.JPEG"
        with open(os.path.join(val, name), "wb") as f:
            f.write(tf.io.encode_jpeg(img).numpy())
        lines.append(f"{name} {wnids[i % 3]}")
    with open(os.path.join(root, "val_labels.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")
    return str(root), wnids


def test_flat_val_layout_with_wnid_mapping(fake_flat_val_dir):
    root, wnids = fake_flat_val_dir
    cfg = DataConfig(name="imagenet", data_dir=root,
                     image_size=32, global_batch_size=4)
    ds = build_dataset(cfg, "eval", seed=0)
    assert ds.is_finite
    batches = list(ds)
    # 7 examples in 2 batches of 4, final batch padded with one invalid row
    assert len(batches) == 2
    assert sum(int(b["valid"].sum()) for b in batches) == 7
    # wnid i%3 -> sorted-train-dir index i%3: valid labels span exactly 0..2
    labels = np.concatenate([b["label"][b["valid"]] for b in batches])
    assert sorted(set(labels.tolist())) == [0, 1, 2]


def test_flat_val_mapping_inside_split_dir(fake_flat_val_dir, tmp_path):
    """The mapping file may live in val/ itself — it must be auto-detected
    there and never be counted as a validation image."""
    import shutil

    root, _ = fake_flat_val_dir
    clone = tmp_path / "map_in_val"
    shutil.copytree(root, clone)
    shutil.move(str(clone / "val_labels.txt"),
                str(clone / "val" / "val_labels.txt"))
    cfg = DataConfig(name="imagenet", data_dir=str(clone),
                     image_size=32, global_batch_size=4)
    batches = list(build_dataset(cfg, "eval", seed=0))
    assert sum(int(b["valid"].sum()) for b in batches) == 7


def test_flat_val_layout_without_mapping_raises(fake_flat_val_dir, tmp_path):
    import shutil

    root, _ = fake_flat_val_dir
    clone = tmp_path / "no_map"
    shutil.copytree(root, clone)
    os.remove(clone / "val_labels.txt")
    cfg = DataConfig(name="imagenet", data_dir=str(clone),
                     image_size=32, global_batch_size=4)
    with pytest.raises(FileNotFoundError, match="label mapping"):
        build_dataset(cfg, "eval", seed=0)


def test_flat_val_ground_truth_int_format(fake_flat_val_dir, tmp_path):
    import shutil

    root, _ = fake_flat_val_dir
    clone = tmp_path / "gt_ints"
    shutil.copytree(root, clone)
    os.remove(clone / "val_labels.txt")
    with open(clone / "ILSVRC2012_validation_ground_truth.txt", "w") as f:
        f.write("\n".join(str(i % 3) for i in range(7)) + "\n")
    cfg = DataConfig(name="imagenet", data_dir=str(clone),
                     image_size=32, global_batch_size=4)
    batches = list(build_dataset(cfg, "eval", seed=0))
    labels = np.concatenate([b["label"][b["valid"]] for b in batches])
    assert sorted(set(labels.tolist())) == [0, 1, 2]


def test_imagefolder_eval_and_host_sharding(fake_imagefolder_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagefolder_dir,
                     image_size=32, global_batch_size=4)
    a = build_dataset(cfg, "eval", seed=0, num_shards=2, shard_index=0)
    b = build_dataset(cfg, "eval", seed=0, num_shards=2, shard_index=1)
    batches_a, batches_b = list(a), list(b)
    ba, bb = batches_a[0], batches_b[0]
    assert ba["image"].shape == (2, 32, 32, 3)  # local batch = global/2
    assert not np.array_equal(ba["image"], bb["image"])
    # 9 validation examples split 5/4: the shards' padded streams still cover
    # exactly 9 valid rows between them (final-batch pad-and-mask).
    valid_total = sum(int(x["valid"].sum()) for x in batches_a + batches_b)
    assert valid_total == 9
