"""Data pipeline tests (SURVEY.md §4): determinism under fixed seed, sharding,
CIFAR-10 pickle loading, ImageNet TFRecord JPEG pipeline on generated fakes."""

import os
import pickle

import numpy as np
import pytest

from distributed_vgg_f_tpu.config import DataConfig
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset


def test_synthetic_deterministic():
    a = SyntheticDataset(8, 16, 10, seed=5)
    b = SyntheticDataset(8, 16, 10, seed=5)
    ba, bb = next(a), next(b)
    np.testing.assert_array_equal(ba["image"], bb["image"])
    np.testing.assert_array_equal(ba["label"], bb["label"])
    c = SyntheticDataset(8, 16, 10, seed=6)
    assert not np.array_equal(next(c)["image"], ba["image"])


def _write_fake_cifar(tmp_path):
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        data = {b"data": rng.integers(0, 256, size=(100, 3072), dtype=np.int64
                                      ).astype(np.uint8),
                b"labels": rng.integers(0, 10, size=100).tolist()}
        with open(tmp_path / f"data_batch_{i}", "wb") as f:
            pickle.dump(data, f)
    data = {b"data": rng.integers(0, 256, size=(80, 3072), dtype=np.int64
                                  ).astype(np.uint8),
            b"labels": rng.integers(0, 10, size=80).tolist()}
    with open(tmp_path / "test_batch", "wb") as f:
        pickle.dump(data, f)


def test_cifar10_from_pickle_files(tmp_path):
    _write_fake_cifar(tmp_path)
    cfg = DataConfig(name="cifar10", data_dir=str(tmp_path), image_size=32,
                     global_batch_size=16, num_train_examples=500)
    ds = build_dataset(cfg, "train", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (16, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (16,)
    # normalized: values roughly centred
    assert abs(float(batch["image"].mean())) < 2.0
    ev = build_dataset(cfg, "eval", seed=0)
    evb = next(ev)
    assert evb["image"].shape == (16, 32, 32, 3)


def test_cifar10_synthetic_fallback_and_sharding():
    cfg = DataConfig(name="cifar10", data_dir="", image_size=32,
                     global_batch_size=32, num_train_examples=50_000)
    ds0 = build_dataset(cfg, "train", seed=0, num_shards=2, shard_index=0)
    ds1 = build_dataset(cfg, "train", seed=0, num_shards=2, shard_index=1)
    b0, b1 = next(ds0), next(ds1)
    # each host shard gets local_batch = global/num_shards
    assert b0["image"].shape[0] == 16 and b1["image"].shape[0] == 16
    assert not np.array_equal(b0["image"], b1["image"])


def test_cifar10_train_determinism():
    cfg = DataConfig(name="cifar10", data_dir="", image_size=32,
                     global_batch_size=16)
    a = build_dataset(cfg, "train", seed=3)
    b = build_dataset(cfg, "train", seed=3)
    for _ in range(3):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])


# --------------------------------------------------------------------------
# ImageNet TFRecord pipeline on generated fake JPEG records
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fake_imagenet_dir(tmp_path_factory):
    tf = pytest.importorskip("tensorflow")
    root = tmp_path_factory.mktemp("fake_imagenet")
    rng = np.random.default_rng(0)

    def write(split, num_files, per_file):
        for i in range(num_files):
            path = os.path.join(
                root, f"{split}-{i:05d}-of-{num_files:05d}")
            with tf.io.TFRecordWriter(path) as w:
                for _ in range(per_file):
                    img = rng.integers(0, 256, size=(48, 64, 3)).astype(np.uint8)
                    jpeg = tf.io.encode_jpeg(img).numpy()
                    label = int(rng.integers(1, 1001))
                    ex = tf.train.Example(features=tf.train.Features(feature={
                        "image/encoded": tf.train.Feature(
                            bytes_list=tf.train.BytesList(value=[jpeg])),
                        "image/class/label": tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[label])),
                    }))
                    w.write(ex.SerializeToString())

    write("train", 4, 8)
    write("validation", 2, 8)
    return str(root)


def test_imagenet_train_pipeline(fake_imagenet_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagenet_dir,
                     image_size=64, global_batch_size=8, shuffle_buffer=16)
    ds = build_dataset(cfg, "train", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (8, 64, 64, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].min() >= 0 and batch["label"].max() <= 999
    # train pipeline repeats forever
    for _ in range(6):
        next(ds)


def test_imagenet_eval_pipeline(fake_imagenet_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagenet_dir,
                     image_size=64, global_batch_size=4)
    ds = build_dataset(cfg, "eval", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (4, 64, 64, 3)


def test_imagenet_missing_dir_raises(tmp_path):
    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path),
                     image_size=64, global_batch_size=4)
    with pytest.raises(FileNotFoundError):
        build_dataset(cfg, "train", seed=0)


def test_image_dtype_bfloat16_all_pipelines():
    import ml_dtypes
    bf16 = np.dtype(ml_dtypes.bfloat16)

    syn = build_dataset(DataConfig(name="synthetic", image_size=8,
                                   global_batch_size=4,
                                   image_dtype="bfloat16"), "train")
    assert next(syn)["image"].dtype == bf16

    cif = build_dataset(DataConfig(name="cifar10", image_size=32,
                                   global_batch_size=4,
                                   image_dtype="bfloat16"), "train")
    batch = next(cif)
    assert batch["image"].dtype == bf16
    assert batch["image"].shape == (4, 32, 32, 3)


# --------------------------------------------------------------------------
# ImageNet raw-JPEG directory-per-class layout
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fake_imagefolder_dir(tmp_path_factory):
    tf = pytest.importorskip("tensorflow")
    root = tmp_path_factory.mktemp("fake_imagefolder")
    rng = np.random.default_rng(1)
    for split, per_class in (("train", 6), ("validation", 3)):
        for cls in ("n01440764", "n01443537", "n01484850"):
            d = os.path.join(root, split, cls)
            os.makedirs(d)
            for i in range(per_class):
                img = rng.integers(0, 256, size=(40, 56, 3)).astype(np.uint8)
                with open(os.path.join(d, f"{cls}_{i}.JPEG"), "wb") as f:
                    f.write(tf.io.encode_jpeg(img).numpy())
    return str(root)


def test_imagefolder_train_pipeline(fake_imagefolder_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagefolder_dir,
                     image_size=32, global_batch_size=4, shuffle_buffer=8)
    ds = build_dataset(cfg, "train", seed=0)
    batch = next(ds)
    assert batch["image"].shape == (4, 32, 32, 3)
    # labels are sorted-class-directory indices
    assert batch["label"].min() >= 0 and batch["label"].max() <= 2
    for _ in range(8):  # repeats past one epoch (18 images)
        next(ds)


def test_imagefolder_eval_and_host_sharding(fake_imagefolder_dir):
    cfg = DataConfig(name="imagenet", data_dir=fake_imagefolder_dir,
                     image_size=32, global_batch_size=4)
    a = build_dataset(cfg, "eval", seed=0, num_shards=2, shard_index=0)
    b = build_dataset(cfg, "eval", seed=0, num_shards=2, shard_index=1)
    ba, bb = next(a), next(b)
    assert ba["image"].shape == (2, 32, 32, 3)  # local batch = global/2
    assert not np.array_equal(ba["image"], bb["image"])
