"""Numerical parity of the Flax VGG-F forward vs an independently-written torch
implementation on identical weights/inputs (SURVEY.md §4: tolerance ~1e-4 fp32).

The torch model is constructed from the SAME architecture description
(CNN-F, Chatfield et al. 2014) and loaded with the Flax params (layout-mapped),
so a mismatch implies a genuine architecture/numerics divergence.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_vgg_f_tpu.config import ModelConfig
from distributed_vgg_f_tpu.models import build_model

torch = pytest.importorskip("torch")
nn = torch.nn


class TorchVGGF(nn.Module):
    def __init__(self, num_classes=1000):
        super().__init__()
        # LRN params mirror the flax defaults (TF convention: alpha unscaled →
        # torch's alpha = tf_alpha * size)
        n = 5
        self.features = nn.Sequential(
            nn.Conv2d(3, 64, 11, stride=4), nn.ReLU(),
            nn.LocalResponseNorm(n, alpha=1e-4 * n, beta=0.75, k=2.0),
            nn.MaxPool2d(3, 2, ceil_mode=True),
            nn.Conv2d(64, 256, 5, padding=2), nn.ReLU(),
            nn.LocalResponseNorm(n, alpha=1e-4 * n, beta=0.75, k=2.0),
            nn.MaxPool2d(3, 2, ceil_mode=True),
            nn.Conv2d(256, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2d(256, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2d(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2d(3, 2, ceil_mode=True),
        )
        self.classifier = nn.Sequential(
            nn.Linear(6 * 6 * 256, 4096), nn.ReLU(),
            nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        x = torch.flatten(x, 1)
        return self.classifier(x)


def _load_flax_params_into_torch(tmodel: TorchVGGF, params):
    convs = [tmodel.features[i] for i in (0, 4, 8, 10, 12)]
    for conv, name in zip(convs, ["conv1", "conv2", "conv3", "conv4", "conv5"]):
        k = np.asarray(params[name]["kernel"])        # (H, W, Cin, Cout)
        conv.weight.data = torch.from_numpy(k.transpose(3, 2, 0, 1).copy())
        conv.bias.data = torch.from_numpy(np.asarray(params[name]["bias"]))
    # fc6: flax flattens NHWC → (H,W,C); torch flattens NCHW → (C,H,W)
    k6 = np.asarray(params["fc6"]["kernel"]).reshape(6, 6, 256, 4096)
    k6 = k6.transpose(2, 0, 1, 3).reshape(6 * 6 * 256, 4096)
    lins = [tmodel.classifier[i] for i in (0, 2, 4)]
    lins[0].weight.data = torch.from_numpy(k6.T.copy())
    lins[0].bias.data = torch.from_numpy(np.asarray(params["fc6"]["bias"]))
    for lin, name in zip(lins[1:], ["fc7", "fc8"]):
        k = np.asarray(params[name]["kernel"])
        lin.weight.data = torch.from_numpy(k.T.copy())
        lin.bias.data = torch.from_numpy(np.asarray(params[name]["bias"]))


def test_vggf_forward_matches_torch():
    model = build_model(ModelConfig(name="vggf", num_classes=1000,
                                    compute_dtype="float32"))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 224, 224, 3), dtype=np.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    flax_logits = np.asarray(model.apply(variables, jnp.asarray(x),
                                         train=False))

    tmodel = TorchVGGF()
    _load_flax_params_into_torch(tmodel, variables["params"])
    tmodel.eval()
    with torch.no_grad():
        torch_logits = tmodel(
            torch.from_numpy(x.transpose(0, 3, 1, 2).copy())).numpy()

    np.testing.assert_allclose(flax_logits, torch_logits, rtol=1e-3, atol=1e-3)
    # logits are non-degenerate
    assert np.std(flax_logits) > 1e-4
