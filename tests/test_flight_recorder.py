"""Flight recorder (telemetry/flight.py): bounded window ring, crash-note
protocol, black-box dump + schema, and the ISSUE 8 acceptance — a
schema-valid black box on EVERY chaos-suite crash class (non-finite abort,
data stall, injected crash, unhandled exception), wired through the real
trainer crash paths. The multi-host version rides the two-process child
(tests/test_multihost.py phase E)."""

import dataclasses
import io
import json
import os
import time

import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    OptimConfig,
    TelemetryConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.resilience import InjectedFault
from distributed_vgg_f_tpu.resilience.errors import (
    DataStallError,
    NonFiniteStepError,
)
from distributed_vgg_f_tpu.telemetry import flight as flight_mod
from distributed_vgg_f_tpu.telemetry import schema
from distributed_vgg_f_tpu.telemetry.flight import FlightRecorder
from distributed_vgg_f_tpu.utils.logging import MetricLogger


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    flight_mod.get_flight().clear()
    yield
    telemetry.reset()
    flight_mod.get_flight().clear()
    telemetry.configure(enabled=True)


def _cfg(tmp, steps=4, tele_kw=None, **train_kw):
    return ExperimentConfig(
        name="flight_test",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        train=TrainConfig(steps=steps, log_every=1, seed=0, **train_kw),
        telemetry=TelemetryConfig(flight_dir=str(tmp / "flight"),
                                  **(tele_kw or {})),
    )


# ------------------------------------------------------------------- units
def test_window_ring_bounded_and_resizable():
    fr = FlightRecorder(max_windows=4)
    for step in range(10):
        fr.record_window(step=step, wall_s=1.0,
                         stall={"verdict": "compute_bound"})
    windows = fr.windows()
    assert [w["step"] for w in windows] == [6, 7, 8, 9]   # newest kept
    fr.set_max_windows(2)
    assert [w["step"] for w in fr.windows()] == [8, 9]
    with pytest.raises(ValueError):
        FlightRecorder(max_windows=0)
    with pytest.raises(ValueError):
        fr.set_max_windows(0)


def test_latest_stall_skips_verdictless_windows():
    fr = FlightRecorder()
    assert fr.latest_stall() is None
    fr.record_window(step=1, wall_s=1.0,
                     stall={"verdict": "infeed_bound"})
    fr.record_window(step=2, wall_s=1.0)          # no verdict
    assert fr.latest_stall()["step"] == 1


def test_note_names_the_crash_and_is_consumed_once():
    fr = FlightRecorder()
    fr.note_crash("data_stall", "watchdog timed out")
    bb = fr.build_black_box(exc=RuntimeError("x"))
    assert bb["reason"] == "data_stall"
    assert bb["reason_detail"] == "watchdog timed out"
    # consumed: a SECOND crash without a new note must not inherit it
    assert fr.build_black_box()["reason"] == "unhandled_exception"
    with pytest.raises(ValueError):
        fr.note_crash("meteor_strike")


def test_stale_note_does_not_mislabel_a_later_crash(monkeypatch):
    """A note from a fault the run SURVIVED (e.g. a caught DataStallError)
    must not name an unrelated crash an hour later."""
    fr = FlightRecorder()
    fr.note_crash("data_stall", "survived this one")
    real = time.monotonic

    monkeypatch.setattr(time, "monotonic",
                        lambda: real() + flight_mod.NOTE_FRESH_S + 1)
    assert fr.build_black_box()["reason"] == "unhandled_exception"


def test_dump_schema_validates_and_is_atomic(tmp_path):
    fr = FlightRecorder()
    fr.record_window(step=7, wall_s=2.5, stall={"verdict": "infeed_bound",
                                                "infeed_fraction": 0.9},
                     counters={"prefetch/batches": 10},
                     spans={"infeed": 2.2})
    fr.note_crash("injected_crash", "chaos")
    path = fr.dump(str(tmp_path), exc=InjectedFault("boom"), process=3,
                   config_fingerprint="sha256:abcd", config_name="t",
                   versions={"native_jpeg_abi": 7},
                   registry=telemetry.get_registry(),
                   recorder=telemetry.get_recorder())
    assert os.path.basename(path) == "flight_p00003.json"
    assert schema.validate_flight_file(path) == []
    record = json.load(open(path))
    assert record["reason"] == "injected_crash"
    assert record["exception"]["type"] == "InjectedFault"
    assert record["windows"][0]["spans"]["infeed"] == pytest.approx(2.2)
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    assert fr.dumps == 1


def test_flight_record_schema_catches_drift():
    good = FlightRecorder().build_black_box()
    assert schema.validate_flight_record(good) == []
    assert schema.validate_flight_record({"kind": "flight_black_box"})
    bad = dict(good, reason="gremlins")
    assert any("reason" in e for e in schema.validate_flight_record(bad))
    bad = dict(good, windows=[{"wall_s": -1}])
    assert schema.validate_flight_record(bad)
    bad = dict(good, schema_version="9.0")
    assert any("major" in e for e in schema.validate_flight_record(bad))


# ----------------------------------------------------- trainer crash classes
def _crash(tmp, cfg_kw, exc_type):
    from distributed_vgg_f_tpu.train.trainer import Trainer

    quiet = MetricLogger(stream=io.StringIO())
    tr = Trainer(_cfg(tmp, **cfg_kw), logger=quiet)
    with pytest.raises(exc_type):
        tr.fit(tr.init_state())
    path = tmp / "flight" / "flight_p00000.json"
    assert path.exists(), "crash produced no black box"
    assert schema.validate_flight_file(str(path)) == []
    return json.load(open(path))


def test_black_box_on_nonfinite_abort(devices8, tmp_path):
    record = _crash(tmp_path,
                    dict(steps=8, fault_injection="nan@1+",
                         skip_nonfinite=True, max_nonfinite_steps=2),
                    NonFiniteStepError)
    assert record["reason"] == "nonfinite_abort"
    assert record["exception"]["type"] == "NonFiniteStepError"
    # the ring holds the pre-crash windows, and the registry's final state
    # shows the guard fighting
    assert record["windows"]
    assert record["counters_final"]["resilience/nonfinite_skips"] >= 2
    assert record["config_name"] == "flight_test"
    assert record["config_fingerprint"].startswith("sha256:")
    assert record["versions"]["metrics_schema"] == schema.SCHEMA_VERSION


def test_black_box_on_injected_crash(devices8, tmp_path):
    record = _crash(tmp_path, dict(steps=4, fault_injection="crash@2"),
                    InjectedFault)
    assert record["reason"] == "injected_crash"
    assert record["counters_final"]["fault/crash"] == 1


def test_black_box_on_data_stall(devices8, tmp_path):
    # The stall must OUTLAST the first-step compile: the injector sleeps
    # in the prefetch worker thread, so while the consumer is stuck in
    # its own trace/compile the queue quietly refills behind it and a
    # short stall never surfaces (on a slow single-core box a 2 s stall
    # hid entirely inside a ~15 s compile and the watchdog never fired).
    # 60 s is beyond any observed compile; the test still finishes in
    # ~watchdog budget (0.2 s * 3) past the compile because the raise
    # comes from the consumer's timeout, not from the sleep ending — the
    # daemon worker is left sleeping and close() does not join it.
    record = _crash(tmp_path,
                    dict(steps=4, fault_injection="stall@2:60",
                         data_timeout_s=0.2, data_timeout_retries=1),
                    DataStallError)
    assert record["reason"] == "data_stall"
    assert record["counters_final"]["prefetch/timeouts"] >= 1


def test_black_box_on_unhandled_exception(devices8, tmp_path):
    """Anything that never announced itself still dumps — with the honest
    residual label, the exception verbatim, and the retained windows."""
    from distributed_vgg_f_tpu.train.trainer import Trainer

    quiet = MetricLogger(stream=io.StringIO())
    tr = Trainer(_cfg(tmp_path, steps=6), logger=quiet)

    def exploding(n=4):
        ds = tr.make_dataset("train")
        for _ in range(n):
            yield next(ds)
        raise OSError("disk fell off")

    with pytest.raises(OSError):
        tr.fit(tr.init_state(), dataset=exploding())
    path = tmp_path / "flight" / "flight_p00000.json"
    record = json.load(open(path))
    assert schema.validate_flight_file(str(path)) == []
    assert record["reason"] == "unhandled_exception"
    assert record["exception"]["type"] == "OSError"
    assert len(record["windows"]) >= 3


def test_dump_dir_resolution_and_skip_event(devices8, tmp_path):
    """flight_dir > sidecar_dir > checkpoint_dir/flight; with none, the
    dump is skipped with a logged event, never an error."""
    from distributed_vgg_f_tpu.train.trainer import Trainer

    # sidecar_dir fallback
    cfg = _cfg(tmp_path, steps=4, fault_injection="crash@2")
    cfg = dataclasses.replace(cfg, telemetry=TelemetryConfig(
        sidecar_dir=str(tmp_path / "sidecars")))
    tr = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    with pytest.raises(InjectedFault):
        tr.fit(tr.init_state())
    assert (tmp_path / "sidecars" / "flight_p00000.json").exists()

    # nothing configured → logged skip
    stream = io.StringIO()
    jsonl = str(tmp_path / "skip.jsonl")
    cfg2 = dataclasses.replace(cfg, telemetry=TelemetryConfig())
    with MetricLogger(jsonl_path=jsonl, stream=stream) as logger:
        tr2 = Trainer(cfg2, logger=logger)
        with pytest.raises(InjectedFault):
            tr2.fit(tr2.init_state())
    events = [json.loads(line)["event"] for line in open(jsonl)]
    assert "flight_dump_skipped" in events


def test_disabled_telemetry_dumps_nothing(devices8, tmp_path):
    from distributed_vgg_f_tpu.train.trainer import Trainer

    cfg = _cfg(tmp_path, steps=4, fault_injection="crash@2")
    cfg = dataclasses.replace(cfg, telemetry=TelemetryConfig(
        enabled=False, flight_dir=str(tmp_path / "flight")))
    tr = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    with pytest.raises(InjectedFault):
        tr.fit(tr.init_state())
    assert not (tmp_path / "flight").exists()


def test_clean_run_dumps_no_black_box(devices8, tmp_path):
    from distributed_vgg_f_tpu.train.trainer import Trainer

    tr = Trainer(_cfg(tmp_path, steps=3),
                 logger=MetricLogger(stream=io.StringIO()))
    tr.fit(tr.init_state())
    assert not (tmp_path / "flight").exists()
    # ...but the ring retained the run's windows for /stallz
    assert len(flight_mod.get_flight().windows()) == 3
