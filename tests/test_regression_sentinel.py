"""Regression sentinel (telemetry/regress.py + benchmarks/
regression_sentinel.py): committed-receipt consistency as a tier-1 gate
(ISSUE 8 satellite), tolerance-band derivation, basis matching, the
synthetically-degraded-artifact failure (acceptance: −10% must exit
non-zero), and trajectory freshness."""

import copy
import json
import os
import subprocess
import sys

import pytest

from distributed_vgg_f_tpu.telemetry import regress, schema
from distributed_vgg_f_tpu.telemetry.regress import (
    PINS,
    Basis,
    build_trajectory,
    check_artifact,
    check_committed,
    check_trajectory_file,
    gating_pin_for,
    pin_value,
    row_basis,
    tolerance_band,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SENTINEL = os.path.join(REPO, "benchmarks", "regression_sentinel.py")
R9_RUN = os.path.join(REPO, "benchmarks", "runs", "host_r10",
                      "decode_r10_on_320noise_rst1_run1.json")


# ------------------------------------------------------------ tier-1 gates
def test_committed_receipts_back_every_pin():
    """ISSUE 8 satellite: pins == committed receipts, schema-valid, basis-
    matched, monotone-or-receipted — the fast consistency gate."""
    assert check_committed(REPO) == []


def test_committed_trajectory_is_fresh():
    assert check_trajectory_file(REPO) == []


# ------------------------------------------------------------------- bands
def test_tolerance_band_derivation():
    assert tolerance_band([0.06]) == pytest.approx(0.03)   # half the spread
    assert tolerance_band([0.01]) == 0.02                  # floor
    assert tolerance_band([0.30]) == 0.06                  # cap
    assert tolerance_band([]) == 0.02
    assert tolerance_band([0.04, 0.08, None]) == pytest.approx(0.04)


# ------------------------------------------------------------------- basis
def test_row_basis_extraction_pre_and_post_r8():
    # pre-r8 row: no wire, no source — the dtype WAS the wire, the
    # protocol WAS 320x256 noise
    b = row_basis({"image_dtype": "bfloat16", "space_to_depth": True})
    assert b == Basis("host_bf16", True, "noise", (320, 256), False)
    # r9+ row: u8 wire with restart-marked sources; image_dtype is the
    # device-finish column, NOT host work — excluded from the key
    row = {"wire": "u8", "image_dtype": "bfloat16", "space_to_depth": True,
           "restart_kind": "restart",
           "source": {"source_hw": [320, 256], "source_kind": "noise",
                      "restart_interval": 1}}
    assert row_basis(row) == Basis("u8", True, "noise", (320, 256), True)
    # restart path enabled but markerless sources = sequential basis
    row2 = dict(row, source={"source_hw": [320, 256],
                             "source_kind": "noise",
                             "restart_interval": -1})
    assert not row_basis(row2).restart_markers


def test_newest_gating_pin_wins_per_basis():
    bf16 = Basis("host_bf16", True, "noise", (320, 256), False)
    assert gating_pin_for(bf16).name == "HOST_DECODE_RATE_R7"  # not R6
    u8 = Basis("u8", True, "noise", (320, 256), False)
    assert gating_pin_for(u8).name == "HOST_DECODE_RATE_R8"
    u8r = Basis("u8", True, "noise", (320, 256), True)
    assert gating_pin_for(u8r).name == "HOST_DECODE_RATE_R9"
    # r5's f32 basis is deliberately non-gating (dead host class)
    f32 = Basis("host_f32", False, "noise", (320, 256), False)
    assert gating_pin_for(f32) is None


# ---------------------------------------------------------- artifact gating
def _degraded(factor):
    obj = json.load(open(R9_RUN))
    obj["value"] = round(obj["value"] * factor, 2)
    for row in obj["layouts"]:
        if row.get("mode") == "decode_bench":
            row["images_per_sec_per_core"] *= factor
    return obj


def test_healthy_committed_artifact_passes_as_new():
    errors, report = check_artifact(R9_RUN, REPO)
    assert errors == []
    assert report["pin"] == "HOST_DECODE_RATE_R9"
    assert report["vs_pin"] == pytest.approx(1.0, abs=0.001)


def test_ten_percent_degradation_fails():
    """The acceptance case: −10% must land below every derivable band."""
    errors, report = check_artifact(_degraded(0.9), REPO)
    assert any("REGRESSION" in e for e in errors)
    assert report["tolerance"] <= 0.06 < 0.10


def test_within_band_wobble_passes():
    errors, _ = check_artifact(_degraded(0.99), REPO)
    assert errors == []


def test_unpinned_basis_is_note_unless_required():
    obj = _degraded(1.0)
    for row in obj["layouts"]:
        row["source"] = {"source_hw": [768, 768], "source_kind": "textured",
                        "restart_interval": 1}
    errors, report = check_artifact(obj, REPO)
    assert errors == [] and report["pin"] is None
    errors, _ = check_artifact(obj, REPO, require_pin=True)
    assert any("no gating pin" in e for e in errors)


def test_failed_bench_artifact_is_rejected():
    errors, _ = check_artifact(
        {"metric": regress.HOST_METRIC, "value": None,
         "error": "tpu_unavailable"}, REPO)
    assert any("no numeric contract value" in e for e in errors)


def test_schema_version_major_rejected_in_artifact():
    obj = _degraded(1.0)
    obj["schema_version"] = "9.9"
    errors, _ = check_artifact(obj, REPO)
    assert any("major" in e for e in errors)


# --------------------------------------------------- drift / pin corruption
def test_silent_pin_decrease_is_caught(monkeypatch):
    """A pin moved DOWN without a drift receipt must fail the committed
    check — that is the 'silently giving back r6-r10's wins' case."""
    from distributed_vgg_f_tpu.utils import scaling_model
    monkeypatch.setattr(scaling_model, "HOST_DECODE_RATE_R9", 1100.0)
    errors = check_committed(REPO)
    # the pin no longer equals its provenance AND breaks monotonicity
    assert any("min(provenance)" in e for e in errors)
    assert any("NO drift receipt" in e for e in errors)


def test_receipted_drift_is_allowed():
    """r6→r7 decreases (991.15 < 1031.36) and passes ONLY because the pin
    carries the committed drift receipt."""
    r7 = next(p for p in PINS if p.name == "HOST_DECODE_RATE_R7")
    r6 = next(p for p in PINS if p.name == "HOST_DECODE_RATE_R6")
    assert pin_value(r7) < pin_value(r6)
    assert r7.drift_note and "host_r7" in r7.drift_note


# -------------------------------------------------------------- trajectory
def test_trajectory_shape_and_provenance_marking():
    t = build_trajectory(REPO)
    assert schema.validate_trajectory(t) == []
    rounds = {r["pin"]: r for r in t["host_decode"]}
    assert set(rounds) == {p.name for p in PINS}
    r9 = rounds["HOST_DECODE_RATE_R9"]
    prov = [a for a in r9["artifacts"] if a["pin_provenance"]]
    assert len(prov) == 3
    assert min(a["value"] for a in prov) == pytest.approx(r9["value"])
    # controls in the same dir ride along unmarked
    assert any(not a["pin_provenance"] for a in r9["artifacts"])
    # device half: every BENCH_r*.json is represented
    assert len(t["device"]) == 5
    # deterministic: a second build is byte-identical (no timestamps)
    assert build_trajectory(REPO) == t


# --------------------------------------------------------------------- CLI
def test_cli_exit_codes(tmp_path):
    """One subprocess pass covering the CI contract: --check-committed
    exits 0; a degraded artifact exits 1."""
    degraded = tmp_path / "degraded.json"
    degraded.write_text(json.dumps(_degraded(0.9)))
    ok = subprocess.run(
        [sys.executable, SENTINEL, "--check-committed"],
        capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert '"sentinel": "pass"' in ok.stdout
    bad = subprocess.run(
        [sys.executable, SENTINEL, "--check", str(degraded)],
        capture_output=True, text=True, timeout=120)
    assert bad.returncode == 1
    assert "REGRESSION" in bad.stdout
