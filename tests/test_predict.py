"""Predict mode (train/predict.py + `train.py --mode predict`): classify
JPEGs with a trained checkpoint — output structure, file ordering, checkpoint
requirement, and the CLI surface."""

import io
import json
import os

import numpy as np
import pytest

pytest.importorskip("tensorflow")

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)


@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory):
    import tensorflow as tf
    root = tmp_path_factory.mktemp("predict_imgs")
    rng = np.random.default_rng(0)
    for i in range(5):
        img = rng.integers(0, 256, size=(80, 100, 3)).astype(np.uint8)
        with open(root / f"img_{i}.jpg", "wb") as f:
            f.write(tf.io.encode_jpeg(img, quality=90).numpy())
    return str(root)


def _trainer(tmp_path, num_classes=7):
    import distributed_vgg_f_tpu.train.trainer as trainer_mod
    cfg = ExperimentConfig(
        name="predict_test",
        model=ModelConfig(name="vggf", num_classes=num_classes,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(name="synthetic", image_size=64, global_batch_size=8,
                        num_train_examples=8),
        mesh=MeshConfig(num_data=0),  # all visible (8 virtual CPU) devices
        train=TrainConfig(steps=1, seed=0,
                          checkpoint_dir=str(tmp_path / "ckpt")),
    )
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    return trainer_mod.Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))


@pytest.mark.slow
def test_predict_outputs(jpeg_dir, tmp_path):
    from distributed_vgg_f_tpu.train.predict import run_predict
    tr = _trainer(tmp_path)
    state = tr.init_state()
    tr.checkpoints.save(state, force=True)
    tr.checkpoints.wait()

    out = io.StringIO()
    results = run_predict(tr, [jpeg_dir], top_k=3, batch=2, stream=out)
    files = sorted(os.path.join(jpeg_dir, f) for f in os.listdir(jpeg_dir))
    assert [r["file"] for r in results] == files
    for r in results:
        assert len(r["top_k"]) == 3
        probs = [t["prob"] for t in r["top_k"]]
        assert probs == sorted(probs, reverse=True)
        assert all(0.0 <= p <= 1.0 for p in probs)
        assert all(0 <= t["class"] < 7 for t in r["top_k"])
    # printed JSONL mirrors the return value
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    assert lines == results
    # deterministic across runs
    again = run_predict(tr, [jpeg_dir], top_k=3, batch=2, stream=io.StringIO())
    assert again == results


@pytest.mark.slow
def test_predict_collects_explicit_files(jpeg_dir, tmp_path):
    from distributed_vgg_f_tpu.train.predict import collect_images, run_predict
    tr = _trainer(tmp_path)
    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    one = os.path.join(jpeg_dir, "img_2.jpg")
    assert collect_images([one]) == [one]
    with pytest.raises(FileNotFoundError):
        collect_images([os.path.join(jpeg_dir, "missing.jpg")])
    res = run_predict(tr, [one], stream=io.StringIO())
    assert len(res) == 1 and res[0]["file"] == one


def test_predict_cli_requires_checkpoint(jpeg_dir, tmp_path):
    import train as train_cli
    with pytest.raises(SystemExit, match="no checkpoint"):
        train_cli.main([
            "--config", "vggf_cifar10_smoke", "--mode", "predict",
            "--images", jpeg_dir,
            "--set", f"train.checkpoint_dir={tmp_path / 'none'}",
            "--set", "model.num_classes=3",
            "--set", "data.image_size=32",
        ])


def test_predict_cli_end_to_end(jpeg_dir, tmp_path, capsys):
    import train as train_cli
    tr = _trainer(tmp_path, num_classes=5)
    # reuse the helper's checkpoint dir by pointing the CLI at it
    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    train_cli.main([
        "--config", "vggf_cifar10_smoke", "--mode", "predict",
        "--images", os.path.join(jpeg_dir, "img_0.jpg"),
        "--set", f"train.checkpoint_dir={tmp_path / 'ckpt'}",
        "--set", "model.num_classes=5",
        "--set", "model.compute_dtype=float32",
        "--set", "data.image_size=64",
    ])
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["file"].endswith("img_0.jpg")
    assert len(rec["top_k"]) == 5
