"""Predict mode (train/predict.py + `train.py --mode predict`): classify
JPEGs with a trained checkpoint — output structure, file ordering, checkpoint
requirement, and the CLI surface."""

import io
import json
import os

import numpy as np
import pytest

pytest.importorskip("tensorflow")

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)


@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory):
    import tensorflow as tf
    root = tmp_path_factory.mktemp("predict_imgs")
    rng = np.random.default_rng(0)
    for i in range(5):
        img = rng.integers(0, 256, size=(80, 100, 3)).astype(np.uint8)
        with open(root / f"img_{i}.jpg", "wb") as f:
            f.write(tf.io.encode_jpeg(img, quality=90).numpy())
    return str(root)


def _trainer(tmp_path, num_classes=7):
    import distributed_vgg_f_tpu.train.trainer as trainer_mod
    cfg = ExperimentConfig(
        name="predict_test",
        model=ModelConfig(name="vggf", num_classes=num_classes,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(name="synthetic", image_size=64, global_batch_size=8,
                        num_train_examples=8),
        mesh=MeshConfig(num_data=0),  # all visible (8 virtual CPU) devices
        train=TrainConfig(steps=1, seed=0,
                          checkpoint_dir=str(tmp_path / "ckpt")),
    )
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    return trainer_mod.Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))


@pytest.mark.slow
def test_predict_outputs(jpeg_dir, tmp_path):
    from distributed_vgg_f_tpu.train.predict import run_predict
    tr = _trainer(tmp_path)
    state = tr.init_state()
    tr.checkpoints.save(state, force=True)
    tr.checkpoints.wait()

    out = io.StringIO()
    results = run_predict(tr, [jpeg_dir], top_k=3, batch=2, stream=out)
    files = sorted(os.path.join(jpeg_dir, f) for f in os.listdir(jpeg_dir))
    assert [r["file"] for r in results] == files
    for r in results:
        assert len(r["top_k"]) == 3
        probs = [t["prob"] for t in r["top_k"]]
        assert probs == sorted(probs, reverse=True)
        assert all(0.0 <= p <= 1.0 for p in probs)
        assert all(0 <= t["class"] < 7 for t in r["top_k"])
    # printed JSONL mirrors the return value
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    assert lines == results
    # deterministic across runs
    again = run_predict(tr, [jpeg_dir], top_k=3, batch=2, stream=io.StringIO())
    assert again == results


@pytest.mark.slow
def test_predict_collects_explicit_files(jpeg_dir, tmp_path):
    from distributed_vgg_f_tpu.train.predict import collect_images, run_predict
    tr = _trainer(tmp_path)
    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    one = os.path.join(jpeg_dir, "img_2.jpg")
    assert collect_images([one]) == [one]
    with pytest.raises(FileNotFoundError):
        collect_images([os.path.join(jpeg_dir, "missing.jpg")])
    res = run_predict(tr, [one], stream=io.StringIO())
    assert len(res) == 1 and res[0]["file"] == one


def test_decode_failure_uses_shared_corrupt_fill(jpeg_dir, tmp_path,
                                                 monkeypatch):
    """The r9 corrupt-image contract, UNIFIED (ISSUE 14 satellite): the
    tf.data fallback's decode-failure fill is the shared
    data/snapshot_cache.corrupt_fill — host-float zero-fill, i.e. the
    same ~post-normalize-zero a u8-wire mean-fill reads as — and the
    corrupt image's prediction is exactly the zero-input forward."""
    import jax

    from distributed_vgg_f_tpu.data import native_jpeg, snapshot_cache
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    from distributed_vgg_f_tpu.train.predict import (
        build_forward,
        restore_predict_params,
        run_predict,
    )
    tr = _trainer(tmp_path)

    def no_native(*a, **k):
        raise RuntimeError("native disabled for the fallback-fill pin")

    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    monkeypatch.setattr(native_jpeg, "NativeJpegEvalIterator", no_native)
    calls = []
    real_fill = snapshot_cache.corrupt_fill

    def spy_fill(out, image_dtype, mean):
        calls.append(image_dtype)
        return real_fill(out, image_dtype, mean)

    monkeypatch.setattr(snapshot_cache, "corrupt_fill", spy_fill)
    corrupt = tmp_path / "corrupt.jpg"
    corrupt.write_bytes(b"not a jpeg at all")
    recs = run_predict(tr, [str(corrupt)], top_k=3, batch=1,
                       stream=io.StringIO())
    # the fallback went through the SHARED helper, host-wire dtype
    assert calls == ["float32"]
    # and the record is the zero-input forward, bit for bit (batch=1 on
    # both sides: same geometry, same jitted executable)
    cfg = tr.cfg
    params, batch_stats = restore_predict_params(tr)
    finish = make_device_finish(cfg.data.mean_rgb, cfg.data.stddev_rgb,
                                image_dtype=cfg.data.image_dtype)
    fwd = jax.jit(build_forward(tr.model, params, batch_stats, finish))
    size = cfg.data.image_size
    ref = np.asarray(fwd(np.zeros((1, size, size, 3), np.float32)))[0]
    top = np.argsort(ref)[::-1][:3]
    assert [t["class"] for t in recs[0]["top_k"]] == [int(c) for c in top]
    assert [t["prob"] for t in recs[0]["top_k"]] == \
        [round(float(ref[c]), 6) for c in top]


def test_predict_npy_array_path(tmp_path):
    """Raw u8 array inputs (the serving wire payload) skip decode, route
    through the bucketed serving engine, and refuse to mix with JPEGs."""
    from distributed_vgg_f_tpu.train.predict import run_predict
    tr = _trainer(tmp_path)
    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    rng = np.random.default_rng(0)
    files = []
    for i in range(3):
        p = tmp_path / f"a_{i}.npy"
        np.save(p, rng.integers(0, 256, (64, 64, 3)).astype(np.uint8))
        files.append(str(p))
    out = io.StringIO()
    recs = run_predict(tr, files, top_k=3, batch=2, stream=out)
    assert [r["file"] for r in recs] == files
    for r in recs:
        probs = [t["prob"] for t in r["top_k"]]
        assert probs == sorted(probs, reverse=True)
        assert all(0.0 <= p <= 1.0 for p in probs)
    # printed JSONL mirrors the return value (full-precision probs
    # round-trip through JSON exactly)
    lines = [json.loads(line) for line in out.getvalue().splitlines()]
    assert lines == recs
    # wrong shape fails loudly
    bad = tmp_path / "bad.npy"
    np.save(bad, np.zeros((8, 8, 3), np.uint8))
    with pytest.raises(ValueError, match="uint8"):
        run_predict(tr, [str(bad)], stream=io.StringIO())
    # mixing arrays with images is an error, not an interleave
    jpg = tmp_path / "x.jpg"
    jpg.write_bytes(b"whatever")
    with pytest.raises(ValueError, match="cannot mix"):
        run_predict(tr, [files[0], str(jpg)], stream=io.StringIO())


def test_predict_cli_requires_checkpoint(jpeg_dir, tmp_path):
    import train as train_cli
    with pytest.raises(SystemExit, match="no checkpoint"):
        train_cli.main([
            "--config", "vggf_cifar10_smoke", "--mode", "predict",
            "--images", jpeg_dir,
            "--set", f"train.checkpoint_dir={tmp_path / 'none'}",
            "--set", "model.num_classes=3",
            "--set", "data.image_size=32",
        ])


def test_predict_cli_end_to_end(jpeg_dir, tmp_path, capsys):
    import train as train_cli
    tr = _trainer(tmp_path, num_classes=5)
    # reuse the helper's checkpoint dir by pointing the CLI at it
    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    train_cli.main([
        "--config", "vggf_cifar10_smoke", "--mode", "predict",
        "--images", os.path.join(jpeg_dir, "img_0.jpg"),
        "--set", f"train.checkpoint_dir={tmp_path / 'ckpt'}",
        "--set", "model.num_classes=5",
        "--set", "model.compute_dtype=float32",
        "--set", "data.image_size=64",
    ])
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["file"].endswith("img_0.jpg")
    assert len(rec["top_k"]) == 5
