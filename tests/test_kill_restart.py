"""Kill-and-restart integration test (SURVEY.md §5, failure detection /
recovery): SIGKILL a training process mid-run, restart it against the same
checkpoint directory, and require it to resume from a durable checkpoint and
finish — the reference's Supervisor auto-restore-from-checkpoint semantics
(SURVEY.md §3.5) under a real crash, including tolerance of any half-written
async-save temp dirs the kill leaves behind."""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "kill_restart_child.py")
# Large enough that run 1 is still mid-training when the parent observes the
# first durable checkpoint (step 10, the child's checkpoint interval) and kills
# it — the remaining ~190 post-compile CPU steps take seconds against a 0.1s
# poll, so the race window is negligible.
TOTAL_STEPS = 200


def _durable_steps(ckpt_dir: str):
    """Finalized checkpoint steps: orbax commits a step via atomic rename to a
    plain integer-named directory (temp dirs carry a suffix)."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(int(d) for d in os.listdir(ckpt_dir) if re.fullmatch(r"\d+", d))


@pytest.mark.slow
def test_kill_and_restart_resumes(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    result = str(tmp_path / "result.json")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    cmd = [sys.executable, CHILD, ckpt_dir, result, str(TOTAL_STEPS)]

    # Run 1: train until the first checkpoint is durable on disk, then SIGKILL.
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 600
        while not _durable_steps(ckpt_dir):
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                pytest.fail(f"run 1 exited before any checkpoint:\n{out[-3000:]}")
            if time.monotonic() > deadline:
                pytest.fail("run 1 produced no checkpoint within 600s")
            time.sleep(0.1)
        killed_at = _durable_steps(ckpt_dir)[-1]
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert not os.path.exists(result), "run 1 must not have finished cleanly"
    assert killed_at >= 1

    # Run 2: same command, same directory — must restore and complete.
    out2 = subprocess.run(cmd, env=env, capture_output=True, timeout=900)
    assert out2.returncode == 0, out2.stdout.decode(errors="replace")[-3000:]
    start = re.search(rb"CHILD_START (\d+)", out2.stdout)
    assert start is not None
    with open(result) as f:
        summary = json.load(f)
    assert summary["start_step"] == int(start.group(1))
    assert summary["start_step"] >= killed_at >= 1, \
        "restart did not resume from the durable checkpoint"
    assert summary["final_step"] == TOTAL_STEPS
    assert _durable_steps(ckpt_dir)[-1] == TOTAL_STEPS
