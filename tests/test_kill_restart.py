"""Kill-and-restart integration test (SURVEY.md §5, failure detection /
recovery): SIGKILL a training process mid-run, restart it against the same
checkpoint directory, and require it to resume from a durable checkpoint and
finish — the reference's Supervisor auto-restore-from-checkpoint semantics
(SURVEY.md §3.5) under a real crash, including tolerance of any half-written
async-save temp dirs the kill leaves behind."""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "kill_restart_child.py")
# Large enough that run 1 is still mid-training when the parent observes the
# first durable checkpoint (step 10, the child's checkpoint interval) and kills
# it — the remaining ~190 post-compile CPU steps take seconds against a 0.1s
# poll, so the race window is negligible.
TOTAL_STEPS = 200


def _durable_steps(ckpt_dir: str):
    """Finalized checkpoint steps: orbax commits a step via atomic rename to a
    plain integer-named directory (temp dirs carry a suffix)."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(int(d) for d in os.listdir(ckpt_dir) if re.fullmatch(r"\d+", d))


def _write_fake_tfrecords(root, *, num_files=3, per_file=12):
    import numpy as np
    tf = pytest.importorskip("tensorflow")
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(num_files):
        path = os.path.join(root, f"train-{i:05d}-of-{num_files:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_file):
                img = rng.integers(0, 256, size=(48, 64, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(
                            value=[int(rng.integers(1, 11))])),
                }))
                w.write(ex.SerializeToString())


@pytest.mark.slow
def test_kill_restart_imagenet_pipeline_bit_identical(tmp_path):
    """SIGKILL + restart on the REAL tf.data ImageNet JPEG pipeline: the
    restarted run must restore the data-iterator snapshot (O(1), no replay)
    and end with params BIT-identical to an uninterrupted run — which can only
    happen if the post-resume data stream is exactly the uninterrupted one
    (SURVEY.md §5 data-iterator state)."""
    data_dir = str(tmp_path / "tfrecords")
    _write_fake_tfrecords(data_dir)
    ckpt_dir = str(tmp_path / "ckpt")
    result = str(tmp_path / "result.json")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    steps = 40
    cmd = [sys.executable, CHILD, ckpt_dir, result, str(steps), data_dir]

    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 600
        # wait past the initial step-1 save to a MID-STREAM checkpoint (>= 10)
        while not any(s >= 10 for s in _durable_steps(ckpt_dir)):
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                pytest.fail(f"run 1 exited before any checkpoint:\n{out[-3000:]}")
            if time.monotonic() > deadline:
                pytest.fail("run 1 produced no checkpoint within 600s")
            time.sleep(0.1)
        killed_at = _durable_steps(ckpt_dir)[-1]
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert killed_at >= 10

    out2 = subprocess.run(cmd, env=env, capture_output=True, timeout=900)
    assert out2.returncode == 0, out2.stdout.decode(errors="replace")[-3000:]
    # the restart must have used the O(1) iterator snapshot, not replay
    assert b"[data_iterator_restore]" in out2.stdout
    assert b"restored=True" in out2.stdout
    with open(result) as f:
        resumed = json.load(f)
    assert resumed["start_step"] >= killed_at >= 1
    assert resumed["final_step"] == steps

    # Run 3: uninterrupted, fresh directories, same seed/data.
    ckpt3 = str(tmp_path / "ckpt_uninterrupted")
    result3 = str(tmp_path / "result3.json")
    out3 = subprocess.run(
        [sys.executable, CHILD, ckpt3, result3, str(steps), data_dir],
        env=env, capture_output=True, timeout=900)
    assert out3.returncode == 0, out3.stdout.decode(errors="replace")[-3000:]
    with open(result3) as f:
        uninterrupted = json.load(f)
    assert resumed["fingerprint"] == uninterrupted["fingerprint"], \
        "killed+resumed run diverged from the uninterrupted run"


@pytest.mark.slow
def test_kill_and_restart_resumes(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    result = str(tmp_path / "result.json")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    cmd = [sys.executable, CHILD, ckpt_dir, result, str(TOTAL_STEPS)]

    # Run 1: train until the first checkpoint is durable on disk, then SIGKILL.
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 600
        while not _durable_steps(ckpt_dir):
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                pytest.fail(f"run 1 exited before any checkpoint:\n{out[-3000:]}")
            if time.monotonic() > deadline:
                pytest.fail("run 1 produced no checkpoint within 600s")
            time.sleep(0.1)
        killed_at = _durable_steps(ckpt_dir)[-1]
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert not os.path.exists(result), "run 1 must not have finished cleanly"
    assert killed_at >= 1

    # Run 2: same command, same directory — must restore and complete.
    out2 = subprocess.run(cmd, env=env, capture_output=True, timeout=900)
    assert out2.returncode == 0, out2.stdout.decode(errors="replace")[-3000:]
    start = re.search(rb"CHILD_START (\d+)", out2.stdout)
    assert start is not None
    with open(result) as f:
        summary = json.load(f)
    assert summary["start_step"] == int(start.group(1))
    assert summary["start_step"] >= killed_at >= 1, \
        "restart did not resume from the durable checkpoint"
    assert summary["final_step"] == TOTAL_STEPS
    assert _durable_steps(ckpt_dir)[-1] == TOTAL_STEPS
