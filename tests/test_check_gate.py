"""tools/check.sh — the repo's static correctness gate — runs green as a
tier-1 test, so every default loop exercises the same single entry point
the TPU session scripts and CI call. The gate is stdlib-only static
analysis (linter + ABI checker + committed-receipt sentinel): no
toolchain, no native build, no jax — there is nothing host-specific to
skip for, and a broken gate must fail the suite, not be skipped around.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "tools", "check.sh")

_SH = shutil.which("sh")


@pytest.mark.skipif(_SH is None, reason="no POSIX sh on PATH")
def test_static_gate_green():
    out = subprocess.run(
        [_SH, GATE], cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHON": sys.executable})
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-4000:]
    assert "ALL GREEN" in out.stdout
    # all three passes actually ran — a gate that silently dropped a pass
    # would rot into a rubber stamp
    assert "invariant linter" in out.stdout
    assert "ABI contract checker" in out.stdout
    assert "regression sentinel" in out.stdout


@pytest.mark.skipif(_SH is None, reason="no POSIX sh on PATH")
def test_static_gate_fails_on_violation(tmp_path):
    """End-to-end mutation: a tree with a seeded invariant violation must
    fail the GATE (not just the rule) — proves check.sh propagates exit
    codes. Uses the linter's --repo redirect against a dirty fixture via
    the same CLI the gate calls."""
    bad = tmp_path / "distributed_vgg_f_tpu" / "telemetry" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import numpy\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--repo", str(tmp_path),
         "--rule", "telemetry-import-isolation"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
