"""Distillation head + student training loop (train/distill.py, r23):
loss algebra (alpha=0 ≡ CE, KL term vanishes at equal logits, T² keeps
soft-gradient scale), the npz params round-trip, the student architecture
contract, and a short smoke run that actually reduces the loss."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from distributed_vgg_f_tpu.train.distill import (  # noqa: E402
    EVAL_INDEX_BASE,
    distill_loss,
    load_params,
    save_params,
    teacher_eval_shard,
    train_distilled,
)


def _ce(logits, labels):
    logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1])
    return float(-jnp.mean(jnp.sum(onehot * logp, axis=-1)))


def test_alpha_zero_is_plain_cross_entropy():
    rng = np.random.default_rng(0)
    s = rng.standard_normal((8, 10)).astype(np.float32)
    t = rng.standard_normal((8, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 8)
    loss = float(distill_loss(jnp.asarray(s), jnp.asarray(t),
                              jnp.asarray(labels), alpha=0.0))
    assert loss == pytest.approx(_ce(s, labels), abs=1e-5)


def test_kl_term_vanishes_at_equal_logits():
    rng = np.random.default_rng(1)
    s = rng.standard_normal((4, 7)).astype(np.float32)
    labels = rng.integers(0, 7, 4)
    # alpha=1: pure KL — zero when student == teacher, regardless of T
    for temp in (1.0, 2.0, 8.0):
        loss = float(distill_loss(jnp.asarray(s), jnp.asarray(s),
                                  jnp.asarray(labels), alpha=1.0,
                                  temperature=temp))
        assert abs(loss) < 1e-5
    # and strictly positive when they differ
    t = s + rng.standard_normal(s.shape).astype(np.float32)
    assert float(distill_loss(jnp.asarray(s), jnp.asarray(t),
                              jnp.asarray(labels), alpha=1.0)) > 1e-3


def test_temperature_squared_keeps_gradient_scale():
    """d(T² KL(t/T || s/T))/ds is O(1) in T (Hinton §2) — without the T²
    factor the soft gradient dies as 1/T². Pin: the gradient norm ratio
    between T=1 and T=8 stays within a small factor, not ~64x."""
    rng = np.random.default_rng(2)
    s = jnp.asarray(rng.standard_normal((16, 10)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((16, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 16))

    def gnorm(temp):
        g = jax.grad(lambda x: distill_loss(
            x, t, labels, alpha=1.0, temperature=temp))(s)
        return float(jnp.linalg.norm(g))

    ratio = gnorm(1.0) / gnorm(8.0)
    assert 0.2 < ratio < 8.0


def test_params_npz_round_trip(tmp_path):
    params = {"fc6": {"kernel": np.random.default_rng(0)
                      .standard_normal((4, 3)).astype(np.float32),
                      "bias": np.zeros(3, np.float32)},
              "conv1": {"kernel": np.ones((2, 2, 1, 1), np.float32)}}
    path = str(tmp_path / "w.npz")
    save_params(path, params)
    back = load_params(path)
    assert set(back) == {"fc6", "conv1"}
    np.testing.assert_array_equal(back["fc6"]["kernel"],
                                  params["fc6"]["kernel"])
    np.testing.assert_array_equal(back["conv1"]["kernel"],
                                  params["conv1"]["kernel"])


def test_student_halves_widths_and_param_count():
    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models.registry import build_model

    def n_params(name):
        model = build_model(ModelConfig(name=name, num_classes=10,
                                        compute_dtype="float32"))
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 32, 32, 3), np.float32),
                            train=False)["params"]
        return sum(int(np.asarray(a).size)
                   for a in jax.tree_util.tree_leaves(params)), params

    full, fparams = n_params("vggf")
    student, sparams = n_params("vggf_student")
    # half width everywhere -> ~4x fewer parameters in the FC-dominated
    # total (heads are ~90% of CNN-F)
    assert student * 3 < full
    assert sparams["fc6"]["kernel"].shape[1] == 2048
    assert fparams["fc6"]["kernel"].shape[1] == 4096


def test_eval_shard_is_disjoint_and_u8():
    images, labels = teacher_eval_shard(32, 10, 64)
    assert images.dtype == np.uint8 and images.shape == (64, 32, 32, 3)
    assert labels.shape == (64,) and set(np.unique(labels)) <= set(range(10))
    assert EVAL_INDEX_BASE >= 1 << 20  # beyond any train range in use


@pytest.mark.slow
def test_short_distill_run_reduces_loss():
    params, history = train_distilled(
        "vggf_student", image_size=32, num_classes=10, steps=30,
        batch_size=16, num_examples=256, log_every=29, seed=0)
    assert history[-1]["loss"] < history[0]["loss"]
    assert "fc6" in params and params["fc6"]["kernel"].shape[1] == 2048
