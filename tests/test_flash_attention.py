"""Flash attention kernel vs naive reference — interpret mode on CPU.

The Pallas interpreter executes the real kernel bodies (same grid, same
scratch carries, same masking) without a TPU; the on-chip timing story lives
in benchmarks/flash_attention_bench.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.ops.flash_attention import flash_self_attention
# ONE oracle for every attention implementation in the repo (ring, ring×flash,
# flash) — formulation drift between hand-rolled copies is itself a bug class
# (code-review r3)
from distributed_vgg_f_tpu.parallel.ring_attention import (
    full_attention_reference as naive_attention)


def _rand_qkv(key, shape, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [64, 128])
def test_forward_matches_naive(causal, block):
    q, k, v = _rand_qkv(jax.random.key(0), (2, 256, 2, 64))
    out = flash_self_attention(q, k, v, causal=causal, block_q=block,
                               block_k=block, interpret=True)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_naive(causal):
    q, k, v = _rand_qkv(jax.random.key(1), (1, 128, 2, 32))
    cot = jax.random.normal(jax.random.key(2), q.shape)

    def flash_loss(q, k, v):
        out = flash_self_attention(q, k, v, causal=causal, block_q=64,
                                   block_k=64, interpret=True)
        return jnp.vdot(out, cot)

    def naive_loss(q, k, v):
        return jnp.vdot(naive_attention(q, k, v, causal=causal), cot)

    grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(naive_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(grads, ref_grads, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_uneven_blocks():
    """block_q != block_k exercises the rectangular masking index math."""
    q, k, v = _rand_qkv(jax.random.key(3), (1, 256, 1, 32))
    out = flash_self_attention(q, k, v, causal=True, block_q=128, block_k=64,
                               interpret=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bf16_inputs_fp32_stats():
    """bf16 q/k/v: the kernel's fp32 softmax statistics keep the result
    within bf16 resolution of an fp32-softmax reference."""
    q, k, v = _rand_qkv(jax.random.key(4), (1, 128, 2, 64), jnp.bfloat16)
    out = flash_self_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = naive_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.02, atol=0.02)


def test_block_clamping_and_divisibility():
    q, k, v = _rand_qkv(jax.random.key(5), (1, 32, 1, 16))
    # blocks clamp to T=32 and just work
    out = flash_self_attention(q, k, v, interpret=True)
    ref = naive_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # EXPLICIT block sizes are strict
    with pytest.raises(ValueError, match="not divisible"):
        q2, k2, v2 = _rand_qkv(jax.random.key(6), (1, 96, 1, 16))
        flash_self_attention(q2, k2, v2, block_q=64, block_k=64,
                             interpret=True)
    # default (None) blocks auto-shrink to a divisor: T=192 → 64
    q3, k3, v3 = _rand_qkv(jax.random.key(7), (1, 192, 1, 16))
    out3 = flash_self_attention(q3, k3, v3, causal=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out3), np.asarray(naive_attention(q3, k3, v3, causal=True)),
        rtol=2e-5, atol=2e-5)


def test_pick_block_odd_lengths():
    """Odd composite lengths must get the largest true divisor, not block 1:
    the halving loop bottoms out at b=1 (and t % 1 == 0), so the divisor
    fallback has to trigger on that case explicitly (ADVICE r3). t=195 and
    the ring_flash-reachable t=197-like odd lengths are the motivating
    shapes (e.g. T=394 ring-split over 2 devices)."""
    from distributed_vgg_f_tpu.ops.flash_attention import pick_block

    assert pick_block(195) == 65          # 195 = 3·5·13 → largest ≤128 is 65
    assert pick_block(105) == 105         # odd t ≤ requested divides itself
    assert pick_block(197) == 1           # prime: 1 really is the only choice
    assert pick_block(192) == 64          # even path unchanged: halving wins
    assert pick_block(256) == 128
    assert pick_block(105, requested=64) == 35   # 105 = 3·5·7, clamp matters
    # EVEN lengths whose large divisors are odd: halving alone bottomed out
    # at a cliff block (130 → 2, 160 → 32) though exact divisors ≥ 64 exist
    # (ADVICE r5)
    assert pick_block(130) == 65
    assert pick_block(160) == 80
    assert pick_block(136) == 68
    # and the resulting block actually runs: odd T end-to-end
    q, k, v = _rand_qkv(jax.random.key(20), (1, 195, 1, 32))
    out = flash_self_attention(q, k, v, causal=True, interpret=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_kv_len_padding_matches_unpadded(causal):
    """Pad 197 → 256 with kv_len=197 (the ViT contract), in BOTH masking
    modes — causal and padding masks compose: outputs on the real rows must
    equal unpadded attention, and grads of the padding must be 0."""
    T, TP = 197, 256
    q, k, v = _rand_qkv(jax.random.key(8), (2, T, 2, 32))
    pad = [(0, 0), (0, TP - T), (0, 0), (0, 0)]
    qp, kp, vp = (jnp.pad(x, pad) for x in (q, k, v))
    cot = jax.random.normal(jax.random.key(9), q.shape)

    def padded_loss(qp, kp, vp):
        out = flash_self_attention(qp, kp, vp, causal=causal, block_q=64,
                                   block_k=64, kv_len=T, interpret=True)
        return jnp.vdot(out[:, :T], cot)

    def naive_loss(q, k, v):
        return jnp.vdot(naive_attention(q, k, v, causal=causal), cot)

    out = flash_self_attention(qp, kp, vp, causal=causal, block_q=64,
                               block_k=64, kv_len=T, interpret=True)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out[:, :T]), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    grads = jax.grad(padded_loss, argnums=(0, 1, 2))(qp, kp, vp)
    ref_grads = jax.grad(naive_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(grads, ref_grads, "qkv"):
        np.testing.assert_allclose(np.asarray(g[:, :T]), np.asarray(r),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")
        assert np.all(np.asarray(g[:, T:]) == 0.0), f"d{name} padding nonzero"


@pytest.mark.parametrize("t,block", [(256, 64), (1024, None), (192, None)])
def test_causal_dma_skip_matches_rectangular(t, block):
    """causal_skip='dma' (flat grid over live lower-triangular pairs,
    scalar-prefetched indices — masked blocks never DMA) must be
    numerically identical to the rectangular grid AND the oracle, forward
    and backward; the backward kernels are shared."""
    q, k, v = _rand_qkv(jax.random.key(21), (2, t, 2, 32))
    kw = dict(causal=True, block_q=block, block_k=block, interpret=True)
    out_dma = flash_self_attention(q, k, v, causal_skip="dma", **kw)
    out_mxu = flash_self_attention(q, k, v, causal_skip="mxu", **kw)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_array_equal(np.asarray(out_dma), np.asarray(out_mxu))
    np.testing.assert_allclose(np.asarray(out_dma), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    cot = jax.random.normal(jax.random.key(22), q.shape)
    g_dma = jax.grad(lambda a, b, c: jnp.vdot(flash_self_attention(
        a, b, c, causal_skip="dma", **kw), cot), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: jnp.vdot(
        naive_attention(a, b, c, causal=True), cot),
        argnums=(0, 1, 2))(q, k, v)
    for gd, gr, name in zip(g_dma, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_causal_dma_skip_validation_and_fallbacks():
    q, k, v = _rand_qkv(jax.random.key(23), (1, 128, 1, 16))
    with pytest.raises(ValueError, match="causal_skip"):
        flash_self_attention(q, k, v, causal_skip="dmaa", interpret=True)
    with pytest.raises(ValueError, match="only applies to causal"):
        flash_self_attention(q, k, v, causal_skip="dma", interpret=True)
    # kv_len forces the rectangular fallback but stays correct
    T, TP = 100, 128
    qs, ks, vs = _rand_qkv(jax.random.key(24), (1, T, 1, 16))
    pad = [(0, 0), (0, TP - T), (0, 0), (0, 0)]
    out = flash_self_attention(
        jnp.pad(qs, pad), jnp.pad(ks, pad), jnp.pad(vs, pad), causal=True,
        kv_len=T, causal_skip="dma", block_q=64, block_k=64, interpret=True)
    ref = naive_attention(qs, ks, vs, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :T]), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_causal_skip_auto_resolution():
    """causal_skip="auto" (the default) follows the measured r4 crossover:
    jagged DMA-skip grids from CAUSAL_SKIP_AUTO_THRESHOLD tokens, the
    rectangular schedule below and for non-causal calls."""
    from distributed_vgg_f_tpu.ops.flash_attention import (
        CAUSAL_SKIP_AUTO_THRESHOLD, resolve_causal_skip_auto)

    th = CAUSAL_SKIP_AUTO_THRESHOLD
    assert resolve_causal_skip_auto(True, th) == "dma"
    assert resolve_causal_skip_auto(True, th * 4) == "dma"
    assert resolve_causal_skip_auto(True, th - 1) == "mxu"
    assert resolve_causal_skip_auto(False, th * 4) == "mxu"
    # and the default path stays exact where auto engages the jagged grid
    q, k, v = _rand_qkv(jax.random.key(25), (1, th, 1, 16))
    out = flash_self_attention(q, k, v, causal=True, interpret=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_extreme_logit_stability():
    """Scores ~±900 overflow exp() without running-max shifting — the
    online-softmax state must reproduce the (max-shifted) oracle, forward
    and backward, with no inf/nan anywhere."""
    q, k, v = _rand_qkv(jax.random.key(12), (1, 128, 1, 32))
    q, k = q * 30.0, k * 30.0
    out = flash_self_attention(q, k, v, block_q=64, block_k=64,
                               interpret=True)
    ref = naive_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    # At near-one-hot softmax, the kernel's (q·k)·scale vs the oracle's
    # (q·scale)·k rounding can legitimately FLIP near-tied argmaxes (~1e-4
    # relative logit noise on |s|≈900), moving those rows by O(|v_a − v_b|)
    # — no fixed tolerance absorbs that. The claim under test is NO
    # OVERFLOW: everything finite, and all but a small near-tie fraction of
    # elements exactly tracking the oracle.
    diff = np.abs(np.asarray(out) - np.asarray(ref))
    assert (diff > 1e-3).mean() < 0.02, (diff > 1e-3).mean()
    g = jax.grad(lambda a, b, c: jnp.sum(flash_self_attention(
        a, b, c, block_q=64, block_k=64, interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    assert all(np.isfinite(np.asarray(x)).all() for x in g)


def test_wide_head_dim():
    """Head dim 256 (wider than one 128-lane register) — layout-sensitive
    in compiled Mosaic, shape-correct under the interpreter either way."""
    q, k, v = _rand_qkv(jax.random.key(13), (1, 128, 1, 256))
    out = flash_self_attention(q, k, v, causal=True, interpret=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_long_sequence_memory_shape():
    """T=1024 runs under the interpreter with only O(T·D) outputs — the
    (T, T) probs tensor is never part of any kernel output or residual."""
    q, k, v = _rand_qkv(jax.random.key(7), (1, 1024, 1, 32))
    out = flash_self_attention(q, k, v, causal=True, interpret=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pad_to_block_plan():
    """The prime-length cliff plan (VERDICT r4 weak #4), divisor-aware
    (ADVICE r5), padding on the 64-multiple lattice (VERDICT r5 #8):
    padding is reserved for lengths with genuinely NO true divisor ≥ 64 —
    pick_block's halving loop only visits t/2^k, so even lengths with
    large ODD divisors (t=130 → 65, t=134 → 67) must keep their exact
    divisor — and when a pad IS taken it targets the next 64-multiple,
    not the next 128-multiple: the b ≥ 64 acceptance threshold already
    declares block-64 grids good, so 129 → 192/block-64 (1.49×), not
    256/block-128 (1.98×). The pad, when taken, is always < block,
    preserving the kernels' no-fully-masked-KV-block invariant."""
    from distributed_vgg_f_tpu.ops.flash_attention import pad_to_block

    assert pad_to_block(197) == (256, 128)   # prime: 256 = 4·64, block 128
    assert pad_to_block(394) == (448, 64)    # 2·197: 448/block-64, was 512
    assert pad_to_block(130) == (130, 65)    # halving says 2; 65 is exact
    assert pad_to_block(134) == (134, 67)    # halving says 2; 67 is exact
    assert pad_to_block(192) == (192, 64)    # decent divisor: untouched
    assert pad_to_block(195) == (195, 65)    # odd-divisor 65 ≥ 64: keep
    assert pad_to_block(97) == (97, 97)      # ≤128 is one block: no cliff
    assert pad_to_block(129) == (192, 64)    # 64-lattice, was 256/128
    assert pad_to_block(64) == (64, 64)
    assert pad_to_block(256) == (256, 128)
    for t in (197, 394, 129, 130, 134, 1034, 2051):
        t_pad, b = pad_to_block(t)
        assert b >= 64 or t_pad == t == b, (t, t_pad, b)
        assert t_pad % b == 0
        if t_pad != t:
            assert t_pad - t < b             # every KV block keeps real keys
    # the lattice guarantee, at every tested length INCLUDING the worst
    # case (129, the smallest padded length): pad overhead ≤ 1.5×
    for t in (64, 65, 97, 127, 128, 129, 130, 131, 134, 191, 192, 193,
              195, 197, 255, 256, 257, 383, 394, 449, 1034, 2051, 4099):
        t_pad, b = pad_to_block(t)
        assert t_pad / t <= 1.5, (t, t_pad, b)
        assert t_pad % b == 0 and t_pad >= t


@pytest.mark.parametrize("causal", [False, True])
def test_even_length_odd_divisor_exact_no_pad(causal):
    """t=130 regression (ADVICE r5): auto blocks must run the EXACT 65-token
    blocks (no internal padding — output and grads vs the oracle), where the
    halving-only plan used to pad 130 → 256/block-128, ~4× the score-matmul
    work."""
    from distributed_vgg_f_tpu.ops.flash_attention import pad_to_block

    assert pad_to_block(130) == (130, 65)
    q, k, v = _rand_qkv(jax.random.key(32), (1, 130, 2, 32))
    out = flash_self_attention(q, k, v, causal=causal, interpret=True)
    assert out.shape == q.shape
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    cot = jax.random.normal(jax.random.key(33), q.shape)
    grads = jax.grad(lambda *a: jnp.vdot(flash_self_attention(
        *a, causal=causal, interpret=True), cot), argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(lambda *a: jnp.vdot(naive_attention(
        *a, causal=causal), cot), argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(grads, ref_grads, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_prime_length_pads_not_block1(causal):
    """t=197 (prime) with auto blocks: internal pad to 256/block-128 — the
    block-1 grid the largest-divisor fallback used to produce is a severe
    TPU perf cliff (VERDICT r4 weak #4). Exact incl. grads vs the unpadded
    oracle; output shape is the caller's 197."""
    q, k, v = _rand_qkv(jax.random.key(30), (1, 197, 2, 32))
    cot = jax.random.normal(jax.random.key(31), q.shape)

    out = flash_self_attention(q, k, v, causal=causal, interpret=True)
    assert out.shape == q.shape
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def flash_loss(q, k, v):
        return jnp.vdot(flash_self_attention(q, k, v, causal=causal,
                                             interpret=True), cot)

    def naive_loss(q, k, v):
        return jnp.vdot(naive_attention(q, k, v, causal=causal), cot)

    grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(naive_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(grads, ref_grads, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")
