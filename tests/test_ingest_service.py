"""Disaggregated ingest (r16): framing, epoch-keyed shard ownership, the
service-off kill-switch, service ≡ local byte-identity (synthetic replay AND
native position-keyed decode), worker-death failover, all-dead local
fallback / typed stall, restore_state position-exactness, the /ingestz
endpoint, config validation, and the worker@N fault injector."""

import dataclasses
import logging
import os
import socket

import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import apply_overrides, get_config
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.data import ingest_service as isvc
from distributed_vgg_f_tpu.data.ingest_service import (
    IngestWorker, PositionKeyedProducer, SequentialReplayProducer,
    ServiceProtocolError, ingest_label, recv_message, send_message,
    shard_owner)
from distributed_vgg_f_tpu.data.service_client import ServiceIngestClient
from distributed_vgg_f_tpu.resilience.errors import DataStallError


def _synthetic_cfg(**over):
    cfg = get_config("vggf_synthetic")
    return apply_overrides(cfg, {
        "data.global_batch_size": 8, "data.image_size": 32, **over})


def _factory(data_cfg, seed=3):
    return lambda: build_dataset(data_cfg, "train", seed=seed,
                                 num_classes=1000)


def _replay_workers(data_cfg, n, seed=3):
    return [IngestWorker(SequentialReplayProducer(_factory(data_cfg, seed)),
                         worker_index=i, num_workers=n,
                         receipt={"seed": seed, "shard_index": 0,
                                  "num_shards": 1})
            for i in range(n)]


# ---------------------------------------------------------------- framing

def _sock_pair():
    a, b = socket.socketpair()
    return a, b


def test_frame_roundtrip_arrays():
    a, b = _sock_pair()
    try:
        arrays = {"image": np.arange(24, dtype=np.uint8).reshape(2, 3, 4),
                  "label": np.array([5, -1], np.int32),
                  "f": np.linspace(0, 1, 6, dtype=np.float32).reshape(2, 3)}
        send_message(a, {"op": "get", "cursor": 7}, arrays=arrays)
        header, got = recv_message(b)
        assert header["op"] == "get" and header["cursor"] == 7
        for k, v in arrays.items():
            assert got[k].dtype == v.dtype
            assert np.array_equal(got[k], v)
    finally:
        a.close(), b.close()


def test_frame_checksum_rejects_corruption():
    a, b = _sock_pair()
    try:
        # hand-build a frame whose blob bytes are flipped after the
        # checksum was computed: the receiver must refuse, never hand bad
        # pixels up
        import json
        import struct
        import zlib
        blob = bytes(range(16))
        hdr = json.dumps({"ok": True, "arrays": [
            {"key": "image", "dtype": "uint8", "shape": [16],
             "nbytes": 16, "adler32": zlib.adler32(blob)}]}).encode()
        bad = bytes([blob[0] ^ 0xFF]) + blob[1:]
        total = 4 + len(hdr) + len(bad)
        a.sendall(struct.pack(">Q", total) + struct.pack(">I", len(hdr))
                  + hdr + bad)
        with pytest.raises(ServiceProtocolError, match="checksum"):
            recv_message(b)
    finally:
        a.close(), b.close()


def test_frame_truncation_and_oversize_rejected():
    import struct
    a, b = _sock_pair()
    try:
        a.sendall(struct.pack(">Q", 100) + b"short")
        a.close()
        with pytest.raises(ServiceProtocolError):
            recv_message(b)
    finally:
        b.close()
    a, b = _sock_pair()
    try:
        a.sendall(struct.pack(">Q", 1 << 40))
        with pytest.raises(ServiceProtocolError, match="implausible"):
            recv_message(b)
    finally:
        a.close(), b.close()


# -------------------------------------------------------------- ownership

def test_shard_owner_deterministic_and_in_range():
    owners = [shard_owner(c, 4, seed=9, batches_per_epoch=50)
              for c in range(200)]
    assert owners == [shard_owner(c, 4, seed=9, batches_per_epoch=50)
                      for c in range(200)]
    assert set(owners) <= set(range(4))
    # within one epoch the split is static per residue class (no handoff)
    for c in range(0, 46):
        assert owners[c] == owners[c % 4]


def test_shard_owner_epoch_rebalances():
    # across epochs the permutation re-draws: some cursor's owner changes
    # (the heterogeneous-fleet rebalance), while single-worker is always 0
    changed = any(
        shard_owner(c, 4, seed=9, batches_per_epoch=8)
        != shard_owner(c + 8, 4, seed=9, batches_per_epoch=8)
        for c in range(8))
    assert changed
    assert all(shard_owner(c, 1, seed=9, batches_per_epoch=8) == 0
               for c in range(30))


def test_ingest_label():
    assert ingest_label(4) == "service_4w"
    assert ingest_label(4, enabled=False) == "local"
    cfg = _synthetic_cfg()
    assert cfg.data.service.label == "local"


# ----------------------------------------------------------- kill-switch

def test_service_off_is_local_byte_identical():
    """data.service.enabled=false ≡ local ingest: build_dataset returns
    the ordinary pipeline object (not a client) and the stream is
    byte-identical whether the service config is default or configured-
    but-disabled."""
    cfg = _synthetic_cfg()
    d_disabled = dataclasses.replace(
        cfg.data, service=dataclasses.replace(
            cfg.data.service, enabled=False,
            workers=("127.0.0.1:1",)))
    a = build_dataset(cfg.data, "train", seed=3, num_classes=1000)
    b = build_dataset(d_disabled, "train", seed=3, num_classes=1000)
    assert not isinstance(a, ServiceIngestClient)
    assert type(a) is type(b)
    for _ in range(4):
        x, y = next(a), next(b)
        assert np.array_equal(x["image"], y["image"])
        assert np.array_equal(x["label"], y["label"])


# ------------------------------------------------- service ≡ local stream

def test_service_matches_local_synthetic():
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    client = ServiceIngestClient(
        [w.endpoint for w in workers], seed=3, batches_per_epoch=16,
        expect={"seed": 3, "shard_index": 0})
    local = iter(_factory(cfg.data)())
    try:
        for b in range(10):
            got, want = next(client), next(local)
            assert np.array_equal(got["image"], want["image"]), b
            assert np.array_equal(got["label"], want["label"]), b
    finally:
        client.close()
        for w in workers:
            w.close()


def test_build_dataset_routes_to_client_and_validates_identity():
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    try:
        d_on = dataclasses.replace(
            cfg.data, service=dataclasses.replace(
                cfg.data.service, enabled=True,
                workers=tuple(w.endpoint for w in workers)))
        client = build_dataset(d_on, "train", seed=3, num_classes=1000)
        assert isinstance(client, ServiceIngestClient)
        local = build_dataset(cfg.data, "train", seed=3, num_classes=1000)
        for _ in range(4):
            got, want = next(client), next(local)
            assert np.array_equal(got["image"], want["image"])
        client.close()
        # a fleet serving a DIFFERENT stream must fail the handshake, not
        # silently train on wrong data
        with pytest.raises(ValueError, match="stream-identity"):
            build_dataset(d_on, "train", seed=4, num_classes=1000)
    finally:
        for w in workers:
            w.close()


def test_restore_state_position_exact():
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16)
    try:
        assert client.supports_state
        assert client.restore_state(5)
        ref = iter(_factory(cfg.data)())
        for _ in range(5):
            next(ref)
        for _ in range(3):
            assert np.array_equal(next(client)["image"],
                                  next(ref)["image"])
        # after the first draw the seek is refused (native contract)
        assert not client.restore_state(0)
    finally:
        client.close()
        for w in workers:
            w.close()


# ---------------------------------------------------------------- chaos

def test_worker_death_fails_over_byte_identically():
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16)
    local = iter(_factory(cfg.data)())
    reg = telemetry.get_registry()
    before = reg.counter_value("ingest_service/failovers", 0)
    try:
        for _ in range(3):
            assert np.array_equal(next(client)["image"],
                                  next(local)["image"])
        killed = client.kill_one_worker_for_chaos()
        assert killed in [w.endpoint for w in workers]
        for b in range(3, 10):
            assert np.array_equal(next(client)["image"],
                                  next(local)["image"]), b
        assert reg.counter_value("ingest_service/failovers", 0) > before
        assert client.describe()["workers_live"] == 1
    finally:
        client.close()
        for w in workers:
            w.close()


def test_all_workers_dead_falls_back_to_local(caplog):
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    client = ServiceIngestClient(
        [w.endpoint for w in workers], seed=3, batches_per_epoch=16,
        local_factory=_factory(cfg.data))
    local = iter(_factory(cfg.data)())
    try:
        for _ in range(2):
            assert np.array_equal(next(client)["image"],
                                  next(local)["image"])
        client.kill_one_worker_for_chaos()
        client.kill_one_worker_for_chaos()
        with caplog.at_level(logging.WARNING,
                             "distributed_vgg_f_tpu.data.service_client"):
            for b in range(2, 8):
                assert np.array_equal(next(client)["image"],
                                      next(local)["image"]), b
        assert any("falling back to LOCAL ingest" in r.message
                   for r in caplog.records)
        assert client.describe()["local_fallback_active"]
    finally:
        client.close()
        for w in workers:
            w.close()


def test_all_workers_dead_no_fallback_raises_typed_stall():
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 1)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16, fetch_ahead=1)
    try:
        next(client)
        client.kill_one_worker_for_chaos()
        with pytest.raises(DataStallError, match="decode workers"):
            for _ in range(4):
                next(client)
        # the flight recorder saw a data_stall note (the chaos suite's
        # classification contract: this is a diagnosed stall, never an
        # unhandled_exception)
        from distributed_vgg_f_tpu.telemetry.flight import get_flight
        note = get_flight()._consume_note()
        assert note is not None and note["kind"] == "data_stall"
    finally:
        client.close()
        for w in workers:
            w.close()


def test_fault_plan_worker_token_and_hook():
    from distributed_vgg_f_tpu.resilience import faults
    plan = faults.FaultPlan.parse("worker@3")
    assert plan.worker_kill_step == 3 and plan.has_data_faults
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("worker@2:5")  # no modifier allowed
    killed = []
    faults.set_worker_kill_hook(lambda: killed.append(1) or "w0")
    try:
        src = iter([{"image": np.zeros((2, 2)), "label": np.zeros(2)}] * 4)
        reg = telemetry.get_registry()
        before = reg.counter_value("fault/worker_kill", 0)
        out = list(plan.wrap_iterator(src))
        assert len(out) == 4 and killed == [1]
        assert reg.counter_value("fault/worker_kill", 0) == before + 1
    finally:
        faults.clear_worker_kill_hook(None)
        faults.set_worker_kill_hook(None)


def test_fault_worker_kill_through_live_client():
    """worker@N through the REAL path: the injector's hook is the client's
    chaos kill, the worker dies mid-epoch via the production shutdown op,
    and the wrapped stream continues byte-identically (failover)."""
    from distributed_vgg_f_tpu.resilience import faults
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16)
    local = iter(_factory(cfg.data)())
    plan = faults.FaultPlan.parse("worker@2")
    wrapped = plan.wrap_iterator(client)
    reg = telemetry.get_registry()
    before = reg.counter_value("fault/worker_kill", 0)
    try:
        for b in range(6):
            assert np.array_equal(next(wrapped)["image"],
                                  next(local)["image"]), b
        assert reg.counter_value("fault/worker_kill", 0) == before + 1
        assert client.describe()["workers_live"] == 1
    finally:
        client.close()
        for w in workers:
            w.close()


class _BrokenProducer:
    """produce() raises deterministically — the worker stays up and
    replies ok:false to every get (a misconfigured worker box)."""

    def produce(self, cursor):
        raise RuntimeError("worker misconfigured")


def test_refused_requests_fail_over_not_spin():
    """A worker that REFUSES every get (up, but its producer is broken)
    must be treated like a dead one: marked dead after the first refusal
    and its cursors reassigned — retrying the owner forever would hang
    the stream (code-review r16)."""
    cfg = _synthetic_cfg()
    broken = IngestWorker(_BrokenProducer(), worker_index=0, num_workers=2)
    good = IngestWorker(SequentialReplayProducer(_factory(cfg.data)),
                        worker_index=1, num_workers=2)
    client = ServiceIngestClient([broken.endpoint, good.endpoint], seed=3,
                                 batches_per_epoch=16)
    local = iter(_factory(cfg.data)())
    try:
        for b in range(6):
            assert np.array_equal(next(client)["image"],
                                  next(local)["image"]), b
        assert client.describe()["workers_live"] == 1
    finally:
        client.close()
        broken.close()
        good.close()


# ------------------------------------------------------------- /ingestz

def test_ingestz_endpoint_serves_client_state():
    import json
    import urllib.request

    from distributed_vgg_f_tpu.telemetry.exporter import TelemetryExporter
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 2)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16)
    exp = TelemetryExporter()
    port = exp.start()
    try:
        next(client)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/ingestz", timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] is True
        assert payload["label"] == "service_2w"
        assert len(payload["workers"]) == 2
        assert payload["workers_live"] == 2
    finally:
        exp.stop()
        client.close()
        for w in workers:
            w.close()
    # after close, the provider is cleared
    from distributed_vgg_f_tpu.telemetry.exporter import ingest_payload
    assert ingest_payload()["enabled"] is False


# --------------------------------------------------------------- config

def test_service_config_validation():
    from distributed_vgg_f_tpu.config import ServiceConfig
    with pytest.raises(ValueError, match="host:port"):
        ServiceConfig(workers=("localhost",))
    with pytest.raises(ValueError, match="host:port"):
        ServiceConfig(workers=("host:abc",))
    with pytest.raises(ValueError, match="fetch_ahead"):
        ServiceConfig(fetch_ahead=-1)
    with pytest.raises(ValueError, match="timeout"):
        ServiceConfig(request_timeout_s=0)
    # enabled with no workers is rejected at client build (flag-order
    # tolerance: __post_init__ sees one override at a time)
    cfg = _synthetic_cfg(**{"data.service.enabled": True})
    with pytest.raises(ValueError, match="at least one worker"):
        build_dataset(cfg.data, "train", seed=3, num_classes=1000)
    assert ServiceConfig(enabled=True,
                         workers=("h1:1", "h2:2")).label == "service_2w"


def test_worker_stats_and_hello_receipts():
    cfg = _synthetic_cfg()
    workers = _replay_workers(cfg.data, 1)
    client = ServiceIngestClient([w.endpoint for w in workers], seed=3,
                                 batches_per_epoch=16, fetch_ahead=1)
    try:
        for _ in range(3):
            next(client)
        w = workers[0]
        assert w.hello()["seed"] == 3
        stats = w.stats()
        assert stats["batches_served"] >= 3
        assert stats["bytes_served"] > 0
    finally:
        client.close()
        for w in workers:
            w.close()


# ------------------------------------------------ native position-keyed

@pytest.fixture(scope="module")
def jpeg_train_dir(tmp_path_factory):
    native = pytest.importorskip(
        "distributed_vgg_f_tpu.data.native_jpeg")
    if native.load_native_jpeg() is None:
        pytest.skip("native jpeg loader unavailable (toolchain missing)")
    from PIL import Image
    root = tmp_path_factory.mktemp("svc_imagenet")
    rs = np.random.RandomState(0)
    for cls in ("n01", "n02"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i in range(7):
            Image.fromarray((rs.rand(120, 130, 3) * 255).astype(np.uint8)) \
                .save(str(d / f"{i}.jpg"), "JPEG", quality=90)
    return str(root)


def _native_cfg(data_dir, **over):
    cfg = get_config("vggf_imagenet_dp")
    return apply_overrides(cfg, {
        "data.data_dir": data_dir, "data.global_batch_size": 4,
        "data.image_size": 64, "data.autotune.enabled": False,
        "data.augment.enabled": False, "train.seed": 5, **over})


def test_native_service_matches_local_stream(jpeg_train_dir):
    """The acceptance parity gate: 2 position-keyed decode workers serve
    the flagship u8-wire stream byte-identically to the local native
    iterator, across an epoch boundary (14 items, batch 4)."""
    cfg = _native_cfg(jpeg_train_dir)
    local = build_dataset(cfg.data, "train", seed=5, num_classes=1000)
    workers = [isvc.serve_from_config(cfg, worker_index=i, num_workers=2)
               for i in range(2)]
    assert all(isinstance(w._producer, PositionKeyedProducer)
               for w in workers)
    cfg_on = apply_overrides(cfg, {
        "data.service.enabled": True,
        "data.service.workers": ",".join(w.endpoint for w in workers)})
    client = build_dataset(cfg_on.data, "train", seed=5, num_classes=1000)
    try:
        assert client.describe()["label"] == "service_2w"
        for b in range(9):  # 36 items: past 2 epoch boundaries
            got, want = next(client), next(local)
            assert got["image"].dtype == np.uint8  # the u8 wire
            assert np.array_equal(got["image"], want["image"]), b
            assert np.array_equal(got["label"], want["label"]), b
    finally:
        client.close()
        local.close()
        for w in workers:
            w.close()


def test_native_worker_shared_warm_tier(jpeg_train_dir, tmp_path):
    """The shared snapshot tier: a second worker generation over the same
    store serves warm (store hits move, labels identical), inheriting the
    cache's crc/eviction contracts."""
    cfg = _native_cfg(jpeg_train_dir, **{
        "data.snapshot_cache.enabled": True,
        "data.snapshot_cache.dir": str(tmp_path / "tier")})
    reg = telemetry.get_registry()
    w_cold = isvc.serve_from_config(cfg, worker_index=0, num_workers=1)
    cold = [w_cold._producer.produce(b) for b in range(4)]
    hits0 = reg.counter_value("ingest_service/store_hits", 0)
    w_warm = isvc.serve_from_config(cfg, worker_index=0, num_workers=1)
    warm = [w_warm._producer.produce(b) for b in range(3)]  # epoch 0
    hits1 = reg.counter_value("ingest_service/store_hits", 0)
    try:
        # single-writer election: the first claimant of the generation
        # holds the writer flock, later claimants serve read-only
        # (SnapshotStore's append offsets are not multi-writer safe)
        assert w_cold._producer._store_writable
        assert not w_warm._producer._store_writable
        assert hits1 > hits0
        for a, b in zip(cold, warm):
            assert np.array_equal(a["label"], b["label"])
            assert a["image"].shape == b["image"].shape
    finally:
        w_cold.close()
        w_warm.close()


def test_native_producer_self_tuning_knob(jpeg_train_dir):
    """The per-worker PR 8 controller's knob surface: the producer's
    thread pool resizes through the same thread_knob the autotuner binds,
    and produce() keeps working across resizes."""
    from distributed_vgg_f_tpu.data import autotune as _at
    cfg = _native_cfg(jpeg_train_dir)
    w = isvc.serve_from_config(cfg, worker_index=0, num_workers=1,
                               threads=1)
    try:
        p = w._producer
        knob = _at.thread_knob(p, min_value=1, max_value=8)
        assert knob is not None
        assert p.set_num_threads(4) == 4
        batch = p.produce(0)
        assert batch["image"].shape[0] == 4
        assert p.set_num_threads(2) == 2
        assert p.num_threads() == 2
    finally:
        w.close()
