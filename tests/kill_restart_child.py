"""Child process for the kill-and-restart integration test (SURVEY.md §5
failure detection: restart-from-checkpoint semantics, tested by killing a
training process and restarting it).

Usage: python kill_restart_child.py CKPT_DIR RESULT_PATH TOTAL_STEPS [DATA_DIR]

Trains VGG-F with periodic async checkpointing — on synthetic data, or on the
real tf.data ImageNet JPEG pipeline when DATA_DIR (fake TFRecords) is given,
which also exercises deterministic iterator-snapshot resume. On a normal run
it writes {"start_step", "final_step", "fingerprint"} to RESULT_PATH; the
parent test SIGKILLs the first run mid-training, so only the restarted run
gets there. The fingerprint (sha256 of final params) lets the parent assert
the killed+resumed run ends BIT-identical to an uninterrupted one.
"""

import hashlib
import json
import sys

from _child_bootstrap import bootstrap

jax = bootstrap(8)

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.train.trainer import Trainer  # noqa: E402


def main() -> None:
    ckpt_dir, result_path, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
    data_dir = sys.argv[4] if len(sys.argv) > 4 else ""
    if data_dir:
        data = DataConfig(name="imagenet", data_dir=data_dir, image_size=32,
                          global_batch_size=16, shuffle_buffer=32)
    else:
        data = DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                          num_train_examples=512)
    cfg = ExperimentConfig(
        name="kill_restart",
        model=ModelConfig(name="vggf", num_classes=10, compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=data,
        mesh=MeshConfig(num_data=8),
        train=TrainConfig(steps=total_steps, seed=0, log_every=50,
                          checkpoint_dir=ckpt_dir, checkpoint_every_steps=10),
    )
    trainer = Trainer(cfg)
    state = trainer.restore_or_init()
    start_step = int(jax.device_get(state.step))
    print(f"CHILD_START {start_step}", flush=True)
    state = trainer.fit(state)
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    with open(result_path, "w") as f:
        json.dump({"start_step": start_step,
                   "final_step": int(jax.device_get(state.step)),
                   "fingerprint": h.hexdigest()}, f)


if __name__ == "__main__":
    main()
