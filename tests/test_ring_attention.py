"""Ring attention (parallel/ring_attention.py): exact equality with full
attention while the sequence lives sharded across the 8-device mesh, K/V
blocks circulating by ppermute — the sequence-parallel pattern the mesh
layer leaves room for (beyond reference parity; SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.parallel.ring_attention import (
    full_attention_reference,
    ring_attention,
    ring_self_attention,
)


def _qkv(dtype=jnp.float32, b=2, t=64, h=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32).astype(dtype)
                 for k in ks)


def test_ring_matches_full_attention_fp32(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv()
    got = np.asarray(ring_attention(q, k, v, mesh))
    want = np.asarray(full_attention_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_matches_full_attention_bf16(devices8):
    """bf16 inputs: MXU-dtype GEMMs with fp32 streaming accumulation must
    stay within bf16 representation error of the fp32-softmax oracle run on
    the same rounded inputs."""
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(jnp.bfloat16)
    got = np.asarray(ring_attention(q, k, v, mesh), np.float32)
    want = np.asarray(full_attention_reference(q, k, v), np.float32)
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_on_subset_mesh_sizes(devices8, causal):
    """The ring length is the mesh axis size — 2 and 4 device rings must be
    exact too (trace-time unrolled schedules), in both masking modes."""
    for n in (2, 4):
        mesh = build_mesh(MeshSpec(("data",), (n,)),
                          devices=jax.devices()[:n])
        q, k, v = _qkv(t=32, seed=n)
        got = np.asarray(ring_attention(q, k, v, mesh, causal=causal))
        want = np.asarray(full_attention_reference(q, k, v, causal=causal))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_ring_gradients_match_full_attention(devices8, n):
    """The streaming formulation must be differentiable and its gradients
    equal to the oracle's — ring attention is for TRAINING long sequences,
    not just inference."""
    mesh = build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])
    q, k, v = _qkv(t=32, seed=7)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    def full_loss(q, k, v):
        return jnp.sum(full_attention_reference(q, k, v) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ring_rejects_indivisible_sequence(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=60)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh)


def test_causal_ring_matches_full_causal(devices8):
    """Causal masking by GLOBAL position: future K/V blocks contribute
    nothing, the diagonal block is triangular, past blocks pass whole —
    while the ppermute schedule stays identical on every device."""
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=64, seed=3)
    got = np.asarray(ring_attention(q, k, v, mesh, causal=True))
    want = np.asarray(full_attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # and the causal result genuinely differs from bidirectional
    bidir = np.asarray(ring_attention(q, k, v, mesh))
    assert not np.allclose(got, bidir)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_causal_ring_gradients(devices8, n):
    mesh = build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])
    q, k, v = _qkv(t=32, seed=11)

    g_ring = jax.grad(lambda *a: jnp.sum(
        ring_attention(*a, mesh, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)
