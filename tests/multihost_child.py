"""Child process for the two-process distributed trainer test
(SURVEY.md §2.4 / §5 distributed backend: the multi-host path —
`jax.distributed.initialize`, per-host data sharding,
`make_array_from_process_local_data`, cross-process gradient pmean — exercised
for real over two OS processes with Gloo CPU collectives).

Usage: python multihost_child.py PORT NUM_PROCS PROC_ID RESULT_PATH
"""

import json
import sys

from _child_bootstrap import bootstrap

PORT, NPROC, PID, OUT = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                         sys.argv[4])

# exactly 4 local devices per process (the conftest's inherited 8 replaced)
jax = bootstrap(4, coordinator_port=PORT, num_processes=NPROC,
                process_id=PID)

import numpy as np  # noqa: E402

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.train.trainer import Trainer  # noqa: E402
from distributed_vgg_f_tpu.utils.logging import MetricLogger  # noqa: E402
import io  # noqa: E402


import os  # noqa: E402
import time  # noqa: E402

_T0 = time.monotonic()
_DEBUG = os.environ.get("DVGGF_CHILD_DEBUG", "0") not in ("", "0")


def _mark(msg: str) -> None:
    """Phase timestamps (stderr, DVGGF_CHILD_DEBUG=1) — the Gloo TCP layer
    times out after ~30 s mid-collective, so diagnosing a flake means
    knowing each rank's phase entry times."""
    if _DEBUG:
        print(f"[rank {PID}] +{time.monotonic() - _T0:7.2f}s {msg}",
              file=sys.stderr, flush=True)


def main() -> None:
    assert jax.process_count() == NPROC, jax.process_count()
    assert jax.device_count() == 4 * NPROC
    cfg = ExperimentConfig(
        name="multihost_smoke",
        model=ModelConfig(name="vggf", num_classes=10, compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=256),
        mesh=MeshConfig(num_data=4 * NPROC),
        train=TrainConfig(steps=3, seed=0, log_every=1),
    )
    _mark("phase A: trainer build")
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    _mark("phase A: init_state")
    state = trainer.init_state()
    _mark("phase A: fit")
    state = trainer.fit(state)
    _mark("phase A done")

    # Replicated params: every process holds the full value; synchronous DP
    # demands they are BIT-identical across processes after training — hash
    # raw bytes so compensating/permuted divergences cannot slip through.
    import hashlib
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    fingerprint = h.hexdigest()
    counts = jax.device_get(
        trainer.eval_step(state, trainer.shard(next(trainer.make_dataset()))))

    # Exact eval under DELIBERATELY uneven host shards: process 0 holds 21
    # examples (2 batches, second padded), process 1 holds 9 (1 batch, padded).
    # Process 1 exhausts first and must keep feeding all-invalid padding
    # batches so process 0's psum doesn't strand; the exact total 30 proves
    # every real example was scored exactly once across both hosts.
    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable
    shard_n = 21 if PID == 0 else 9
    rng = np.random.default_rng(7 + PID)
    images = rng.standard_normal((shard_n, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=(shard_n,)).astype(np.int32)

    def epoch(images=images, labels=labels):
        for i in range(0, shard_n, 16):
            yield {"image": images[i:i + 16], "label": labels[i:i + 16]}

    uneven_ds = FiniteEvalIterable(epoch, 16, (32, 32, 3), np.float32)
    _mark("phase B: uneven exact eval")
    exact = trainer.evaluate(state, uneven_ds)
    _mark("phase B done")

    # ZeRO-1 across REAL processes: reduce-scatter / sharded-opt-state /
    # all-gather over the Gloo backend — the fake-device tests cover the math,
    # this covers the cross-process collective path. Params stay replicated,
    # so after training they must be bit-identical on both processes.
    import dataclasses
    cfg_z = dataclasses.replace(
        cfg, name="multihost_zero1",
        mesh=MeshConfig(num_data=4 * NPROC, shard_opt_state=True),
        train=dataclasses.replace(cfg.train, steps=2))
    _mark("phase C: zero1 trainer build")
    trainer_z = Trainer(cfg_z, logger=MetricLogger(stream=io.StringIO()))
    _mark("phase C: zero1 init_state")
    state_z = trainer_z.init_state()
    _mark("phase C: zero1 fit")
    state_z = trainer_z.fit(state_z)
    _mark("phase C done")
    hz = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state_z.params)):
        hz.update(np.ascontiguousarray(leaf).tobytes())

    # Sequence parallelism across REAL process boundaries: the ring's
    # ppermute hops cross the Gloo (DCN-analog) backend, not just virtual
    # intra-process devices — einsum ring AND the ring × flash composition
    # (Pallas kernels interpreted on CPU), forward and backward. Shared
    # implementation with the 4-process child: _child_bootstrap.
    from _child_bootstrap import run_ring_phase

    _mark("phase D: cross-process ring attention")
    ring_flags = run_ring_phase(jax, NPROC, PID, 4, seed=42, batch=2)
    _mark("phase D done")

    # Flight recorder under a REAL multi-host crash (ISSUE 8 acceptance):
    # both ranks hit the injected loader crash at the same step, and EACH
    # rank must land its own schema-valid black box — rank 0's view of a
    # fleet crash is not enough. The injectors fire before the step's
    # collective, so no rank strands the other mid-pmean.
    import dataclasses
    from distributed_vgg_f_tpu.resilience import InjectedFault
    from distributed_vgg_f_tpu.telemetry import schema as tele_schema
    flight_dir = os.path.join(os.path.dirname(OUT), "flight")
    cfg_f = dataclasses.replace(
        cfg, name="multihost_flight",
        train=dataclasses.replace(cfg.train, steps=4,
                                  fault_injection="crash@2"),
        telemetry=dataclasses.replace(cfg.telemetry,
                                      flight_dir=flight_dir))
    _mark("phase E: flight-recorder crash")
    trainer_f = Trainer(cfg_f, logger=MetricLogger(stream=io.StringIO()))
    flight_flags = {"flight_crashed": False, "flight_ok": False}
    try:
        trainer_f.fit(trainer_f.init_state())
    except InjectedFault:
        flight_flags["flight_crashed"] = True
        path = os.path.join(flight_dir, f"flight_p{PID:05d}.json")
        if os.path.exists(path):
            record = json.load(open(path))
            flight_flags["flight_ok"] = (
                tele_schema.validate_flight_file(path) == []
                and record["reason"] == "injected_crash"
                and record["process"] == PID
                and len(record["windows"]) >= 1)
    _mark("phase E done")

    with open(OUT, "w") as f:
        json.dump({"pid": PID,
                   **flight_flags,
                   "step": int(jax.device_get(state.step)),
                   "fingerprint": fingerprint,
                   "eval_count": int(counts["count"]),
                   "exact_eval_examples": int(exact["eval_examples"]),
                   "zero1_step": int(jax.device_get(state_z.step)),
                   "zero1_fingerprint": hz.hexdigest(),
                   **ring_flags}, f)


if __name__ == "__main__":
    main()
