"""Live observability endpoint (telemetry/exporter.py): Prometheus
rendering, the four endpoints over synthetic state, /healthz status
transitions, the port-0 + sidecar discovery contract, and the ISSUE 8
acceptance — all four endpoints served from a LIVE training process."""

import io
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    OptimConfig,
    TelemetryConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.telemetry import exporter as exporter_mod
from distributed_vgg_f_tpu.telemetry import flight as flight_mod
from distributed_vgg_f_tpu.telemetry import schema
from distributed_vgg_f_tpu.telemetry.exporter import (
    TelemetryExporter,
    prometheus_name,
    render_prometheus,
)
from distributed_vgg_f_tpu.telemetry.registry import TelemetryRegistry
from distributed_vgg_f_tpu.utils.logging import MetricLogger


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    flight_mod.get_flight().clear()
    yield
    exporter_mod.stop_exporter()
    telemetry.reset()
    flight_mod.get_flight().clear()
    telemetry.configure(enabled=True)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


# -------------------------------------------------------------- prometheus
def test_prometheus_name_sanitization():
    assert prometheus_name("prefetch/wait_ns") == "dvggf_prefetch_wait_ns"
    assert prometheus_name("decode/scale_histogram/4") == \
        "dvggf_decode_scale_histogram_4"
    assert prometheus_name("4weird name!") == "dvggf__4weird_name_"


def test_render_prometheus_types_and_pollers():
    reg = TelemetryRegistry()
    reg.inc("prefetch/batches", 5)
    reg.set_gauge("prefetch/queue_depth", 2)
    reg.register_poller("decode", lambda: {"images": 7,
                                           "scale_histogram": {4: 3}})
    text = render_prometheus(reg)
    assert "# TYPE dvggf_prefetch_batches counter\n" \
           "dvggf_prefetch_batches 5" in text
    assert "# TYPE dvggf_prefetch_queue_depth gauge\n" \
           "dvggf_prefetch_queue_depth 2" in text
    # pollers ARE swept on the /metrics surface
    assert "dvggf_decode_images 7" in text
    assert "dvggf_decode_scale_histogram_4 3" in text


# --------------------------------------------------------------- endpoints
def test_endpoints_over_synthetic_state():
    reg = telemetry.get_registry()
    reg.inc("prefetch/batches", 3)
    telemetry.record("next_batch", "infeed", time.monotonic_ns(), 1000)
    fr = flight_mod.get_flight()
    fr.record_window(step=5, wall_s=1.0,
                     stall={"verdict": "infeed_bound",
                            "infeed_fraction": 0.8},
                     counters={"prefetch/batches": 3})
    exp = TelemetryExporter()
    port = exp.start()
    try:
        status, ctype, body = _get(port, "/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert "dvggf_prefetch_batches 3" in body.decode()
        # exporter's own requests counter is in the namespace it serves
        assert "dvggf_exporter_requests" in _get(port, "/metrics")[2].decode()

        status, ctype, body = _get(port, "/stallz")
        payload = json.loads(body)
        assert payload["latest"]["stall"]["verdict"] == "infeed_bound"
        assert len(payload["history"]) == 1

        status, _, body = _get(port, "/trace")
        trace = json.loads(body)
        assert schema.validate_chrome_trace(trace) == []
        assert any(e.get("name") == "next_batch"
                   for e in trace["traceEvents"])

        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/nope")
        assert err.value.code == 404
        assert "/metrics" in json.loads(err.value.read())["endpoints"]
    finally:
        exp.stop()


def test_healthz_idle_ok_stalled_transitions():
    exp = TelemetryExporter(stalled_after_s=0.3)
    port = exp.start()
    try:
        status, _, body = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "idle"
        exp.heartbeat(17)
        status, _, body = _get(port, "/healthz")
        payload = json.loads(body)
        assert status == 200 and payload["status"] == "ok"
        assert payload["last_step"] == 17
        assert payload["last_step_age_s"] < 0.3
        assert "prefetch/timeouts" in payload["watchdog"]
        time.sleep(0.4)  # heartbeat goes stale
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/healthz")
        assert err.value.code == 503
        assert json.loads(err.value.read())["status"] == "stalled"
    finally:
        exp.stop()


def test_port_zero_binds_free_port_and_restart():
    exp1 = TelemetryExporter()
    p1 = exp1.start()
    exp2 = TelemetryExporter()
    p2 = exp2.start()
    assert p1 != p2 and p1 > 0 and p2 > 0    # no collision at port 0
    exp1.stop()
    exp2.stop()
    assert exp1.port is None


def test_ensure_started_is_a_process_singleton():
    a = exporter_mod.ensure_started()
    b = exporter_mod.ensure_started(port=0)
    assert a is b and a.port == b.port
    exporter_mod.stop_exporter()
    assert exporter_mod.get_exporter() is None


def test_taken_fixed_port_degrades_not_kills(devices8, tmp_path):
    """A fixed exporter_port already in use costs the run its endpoint
    (logged), never the run itself."""
    import socket

    from distributed_vgg_f_tpu.train.trainer import Trainer

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    jsonl = str(tmp_path / "m.jsonl")
    cfg = ExperimentConfig(
        name="exporter_collide",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        train=TrainConfig(steps=2, log_every=1, seed=0),
        telemetry=TelemetryConfig(exporter=True, exporter_port=port),
    )
    try:
        with MetricLogger(jsonl_path=jsonl, stream=io.StringIO()) as logger:
            tr = Trainer(cfg, logger=logger)
            assert tr.exporter is None
            tr.fit(tr.init_state())          # the run itself is unharmed
    finally:
        blocker.close()
    events = [json.loads(line)["event"] for line in open(jsonl)]
    assert "telemetry_exporter_failed" in events


# -------------------------------------------- live training process (ISSUE 8)
def test_endpoints_served_from_live_training_process(devices8, tmp_path):
    """The acceptance shape: /metrics /healthz /stallz /trace answer WHILE
    fit() is running, the bound port is discoverable from the run sidecar,
    and /stallz serves the trainer's real window verdicts."""
    from distributed_vgg_f_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        name="exporter_live",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=256),
        train=TrainConfig(steps=40, log_every=2, seed=0),
        telemetry=TelemetryConfig(exporter=True,
                                  sidecar_dir=str(tmp_path / "sidecars")),
    )
    jsonl = str(tmp_path / "metrics.jsonl")
    with MetricLogger(jsonl_path=jsonl, stream=io.StringIO()) as logger:
        tr = Trainer(cfg, logger=logger)
        assert tr.exporter is not None and tr.exporter.port
        # port discovery: the run sidecar names this process's address
        sidecar = json.loads(
            open(tmp_path / "sidecars" / "exporter_p00000.jsonl")
            .readline())
        assert sidecar["port"] == tr.exporter.port
        assert sidecar["endpoints"] == ["/metrics", "/healthz", "/stallz",
                                        "/trace", "/autotunez",
                                        "/ingestz", "/servingz"]
        port = tr.exporter.port
        state = tr.init_state()
        errors = []
        mid_run = {}

        def probe():
            deadline = time.monotonic() + 60
            try:
                while time.monotonic() < deadline:
                    _, _, body = _get(port, "/healthz")
                    payload = json.loads(body)
                    if (payload["last_step"] or 0) >= 2:
                        # the run is mid-flight: hit every endpoint NOW
                        mid_run["healthz"] = payload
                        mid_run["metrics"] = _get(port,
                                                  "/metrics")[2].decode()
                        mid_run["stallz"] = json.loads(
                            _get(port, "/stallz")[2])
                        mid_run["trace"] = json.loads(
                            _get(port, "/trace")[2])
                        return
                    time.sleep(0.02)
                errors.append("trainer never heartbeat past step 2")
            except Exception as e:  # noqa: BLE001 — surfaced via errors
                errors.append(repr(e))

        prober = threading.Thread(target=probe)
        prober.start()
        tr.fit(state)
        prober.join(timeout=60)
    assert not errors, errors
    assert mid_run["healthz"]["status"] == "ok"
    assert "dvggf_prefetch_batches" in mid_run["metrics"]
    assert "dvggf_step_dispatched" in mid_run["metrics"]
    verdicts = {w["stall"]["verdict"] for w in mid_run["stallz"]["history"]
                if "stall" in w}
    assert verdicts <= set(telemetry.VERDICTS) and verdicts
    assert schema.validate_chrome_trace(mid_run["trace"]) == []
    # the bound port was logged for humans too
    events = [json.loads(line) for line in open(jsonl)]
    exporter_events = [e for e in events if e["event"] ==
                       "telemetry_exporter"]
    assert exporter_events and exporter_events[0]["port"] == port
