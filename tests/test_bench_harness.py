"""Benchmark harness smoke tests (SURVEY.md §4: the judged metric's
measurement code is itself tested) — run bench.py and benchmarks/scaling.py as
real subprocesses on tiny shapes and validate their JSON contracts."""

import json
import os
import re
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, extra_env=None):
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
           **(extra_env or {})}
    return subprocess.run([sys.executable] + args, env=env, cwd=REPO,
                          capture_output=True, timeout=560)


def test_bench_watchdog_hung_backend_fails_fast_without_killing_child():
    """A bench stuck waiting on the wedged single-grant tunnel (the failure
    that cost round 2 its judged number) must yield a machine-readable JSON
    failure within the budget — and must NOT kill the waiting child, because
    a killed waiting client is what wedges the NEXT run (VERDICT r2 #1)."""
    t0 = time.monotonic()
    out = _run(["bench.py", "--budget", "3"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c", "import time; time.sleep(120)"])})
    assert time.monotonic() - t0 < 60
    # rc 0: the committed registry carries a last-good for the default
    # config, so the failure record doubles as a stale-labeled result line
    # (ISSUE 3 satellite; the no-registry case pins rc 1 below)
    assert out.returncode == 0, out.stdout.decode() + out.stderr.decode()
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    assert len(lines) == 1, out.stdout.decode()
    rec = json.loads(lines[0])
    assert rec["error"] == "tpu_unavailable"
    assert rec["value"] is None
    assert rec["metric"] == "vggf_train_images_per_sec_per_chip"
    assert rec["unit"] == "images/sec/chip"
    # the child was left alive on purpose; reap it here (CPU-only sleep)
    child_pid = int(re.search(r"pid (\d+)", rec["detail"]).group(1))
    os.kill(child_pid, 0)  # raises if the watchdog killed it
    os.kill(child_pid, 9)


def test_bench_failure_record_carries_last_known_good():
    """A wedged-tunnel failure record must embed the most recent COMMITTED
    healthy measurement (benchmarks/last_good.json) as `last_committed` with
    `stale: true` — and must NOT promote it into the `value` field, which
    stays null (VERDICT r3 #2: degrade to 'stale number, clearly labeled'
    instead of pure null). With the stale payload attached the record IS a
    usable (clearly-labeled) result line, so the run exits 0 — an rc=1
    here failed the whole session round even though the driver had a
    number to record (BENCH_r05 / ISSUE 3)."""
    out = _run(["bench.py", "--budget", "3"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c", "import time; time.sleep(120)"])})
    assert out.returncode == 0, out.stdout.decode() + out.stderr.decode()
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    rec = json.loads(lines[0])
    assert rec["error"] == "tpu_unavailable"
    assert rec["value"] is None                      # no stale-value gaming
    assert rec["vs_baseline"] is None
    assert rec["stale"] is True
    last = rec["last_committed"]
    assert last["value"] > 0
    assert last["unit"] == "images/sec/chip"
    assert last["ts"] and last["artifact"]
    # the precomputed age: BENCH_r05's stale record made readers do ISO
    # date math by hand — the emitter owes them the number
    age = rec["last_committed_age_days"]
    assert isinstance(age, (int, float)) and age >= 0
    import datetime
    then = datetime.datetime.fromisoformat(last["ts"])
    if then.tzinfo is None:
        then = then.replace(tzinfo=datetime.timezone.utc)
    expect = (datetime.datetime.now(datetime.timezone.utc)
              - then).total_seconds() / 86400.0
    assert abs(age - expect) < 0.1   # same day-math, ~minutes of slack
    # r11 staleness hygiene: the stale payload cites the cited run's
    # ingest-autotune settled-state so future grant-to-grant comparisons
    # are apples-to-apples; the committed registry predates the field, so
    # it must read as UNKNOWN ({"enabled": null}) — never a silent "off"
    assert rec["last_committed_autotune"] == {"enabled": None}
    # reap the deliberately-alive child
    child_pid = int(re.search(r"pid (\d+)", rec["detail"]).group(1))
    os.kill(child_pid, 9)

    # the registry is keyed by the FULL config: the same wedged run at a
    # non-default batch must NOT cite the batch-2048 number (a batch-1024 or
    # variant number labeled "last good" for the default config would be a
    # wrong number wearing a right label — code-review r4)
    out = _run(["bench.py", "--budget", "3", "--batch-size", "512"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c", "import time; time.sleep(120)"])})
    assert out.returncode == 1      # nothing citable for THIS config: rc 1
    rec = json.loads([l for l in out.stdout.decode().splitlines()
                      if l.startswith("{")][0])
    assert "last_committed" not in rec and "stale" not in rec
    child_pid = int(re.search(r"pid (\d+)", rec["detail"]).group(1))
    os.kill(child_pid, 9)


def test_age_days_tolerates_malformed_ts():
    """A registry payload with a pre-field or garbled ts must still emit —
    the age is a convenience, never a new failure mode."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._age_days(None) is None
    assert bench._age_days("not-a-date") is None
    assert bench._age_days("2026-01-01T00:00:00+00:00") > 0
    # naive timestamps are UTC by registry contract, not local time
    assert bench._age_days("2026-01-01T00:00:00") \
        == bench._age_days("2026-01-01T00:00:00+00:00")


def test_stale_payload_cites_recorded_autotune_state(tmp_path):
    """An r11-era registry entry that RECORDED its run's autotune state
    must be cited verbatim in the stale payload — a settled=false
    last-committed number is a mid-convergence rate and the next TPU-grant
    comparison needs to know that before trusting it."""
    reg = tmp_path / "last_good.json"
    reg.write_text(json.dumps({
        "vggf_train_images_per_sec_per_chip|bs=2048": {
            "value": 20000.0, "unit": "images/sec/chip",
            "ts": "2026-08-01T00:00:00+00:00", "artifact": "x",
            "autotune": {"enabled": True, "settled": True,
                         "actuations_total": 7}}}))
    out = _run(["bench.py", "--budget", "3"],
               extra_env={"DVGGF_LAST_GOOD": str(reg),
                          "DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c", "import time; time.sleep(120)"])})
    assert out.returncode == 0
    rec = json.loads([l for l in out.stdout.decode().splitlines()
                      if l.startswith("{")][0])
    assert rec["last_committed_autotune"] == {
        "enabled": True, "settled": True, "actuations_total": 7}
    child_pid = int(re.search(r"pid (\d+)", rec["detail"]).group(1))
    os.kill(child_pid, 9)


def test_bench_failure_survives_corrupt_registry(tmp_path):
    """A corrupted registry (valid JSON, wrong top-level type) must not
    break the machine-readable failure contract (code-review r4)."""
    bad = tmp_path / "last_good.json"
    bad.write_text("[1, 2, 3]")
    out = _run(["bench.py", "--budget", "3"],
               extra_env={"DVGGF_LAST_GOOD": str(bad),
                          "DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c", "import time; time.sleep(120)"])})
    assert out.returncode == 1
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    rec = json.loads(lines[0])
    assert rec["error"] == "tpu_unavailable"
    assert "last_committed" not in rec
    child_pid = int(re.search(r"pid (\d+)", rec["detail"]).group(1))
    os.kill(child_pid, 9)


def test_bench_bad_model_extra_value_fails_fast():
    """An invalid --model-extra VALUE (not just an unknown key) must die as
    a bad_config record BEFORE the watchdog spawns anything that queues on
    the tunnel: the jax.eval_shape pass traces init abstractly, reaching the
    __call__-time validation with no device work (ADVICE r3)."""
    t0 = time.monotonic()
    out = _run(["bench.py", "--model", "vit_s16", "--image-size", "224",
                "--model-extra", "attention_layout=flashh",
                "--budget", "600"])
    assert time.monotonic() - t0 < 120   # interpreter+trace, never the budget
    assert out.returncode == 1
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    rec = json.loads(lines[0])
    assert rec["error"] == "bad_config"
    assert "flashh" in rec["detail"]
    # a VALID variant value passes the same validation and reaches the
    # watchdog (fake child: proves validation didn't false-positive)
    payload = {"metric": "vit_s16_train_images_per_sec_per_chip",
               "value": 1.0, "unit": "images/sec/chip", "vs_baseline": 1.0}
    out = _run(["bench.py", "--model", "vit_s16",
                "--model-extra", "attention_layout=flash", "--budget", "60"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c",
                    f"print({json.dumps(json.dumps(payload))})"])})
    assert out.returncode == 0, (out.stdout + out.stderr).decode(
        errors="replace")[-2000:]


def test_bench_watchdog_forwards_child_result():
    """When the child completes, the parent forwards its stdout (the JSON
    contract line) and exit code untouched."""
    payload = {"metric": "vggf_train_images_per_sec_per_chip",
               "value": 123.4, "unit": "images/sec/chip", "vs_baseline": 1.0}
    out = _run(["bench.py", "--budget", "60"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c",
                    f"print({json.dumps(json.dumps(payload))})"])})
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    assert len(lines) == 1 and json.loads(lines[0]) == payload


def test_bench_watchdog_rescues_result_from_wedged_teardown():
    """A child that PRINTS its result and then wedges in backend teardown/
    grant release still produced the judged number — the watchdog must
    forward it with rc 0, not report tpu_unavailable (code-review r3)."""
    payload = {"metric": "vggf_train_images_per_sec_per_chip",
               "value": 456.7, "unit": "images/sec/chip", "vs_baseline": 1.1}
    # budget must cover interpreter startup (this machine's sitecustomize
    # imports jax in every python process — several seconds) but expire long
    # before the 120 s teardown hang
    out = _run(["bench.py", "--budget", "25"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c",
                    f"import time; print({json.dumps(json.dumps(payload))}, "
                    "flush=True); time.sleep(120)"])})
    assert out.returncode == 0, out.stdout.decode()
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    assert len(lines) == 1 and json.loads(lines[0]) == payload
    # reap the deliberately-abandoned child (regex-escaped: unescaped parens
    # would make the ERE match nothing)
    subprocess.run(["pkill", "-f", r"time\.sleep\(120\)"],
                   capture_output=True)


def test_bench_watchdog_forwards_child_failure_rc():
    out = _run(["bench.py", "--budget", "60"],
               extra_env={"DVGGF_BENCH_CHILD_ARGV": json.dumps(
                   [sys.executable, "-c",
                    "import sys; print('boom'); sys.exit(7)"])})
    assert out.returncode == 7


@pytest.mark.slow
def test_bench_emits_one_json_line(tmp_path):
    # force CPU inside the child the same way conftest does for this process
    runner = tmp_path / "run_bench.py"
    runner.write_text(
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import sys; sys.argv = ['bench.py', '--batch-size', '4',\n"
        "    '--image-size', '32', '--steps', '2', '--warmup', '1']\n"
        "import bench; bench.main()\n")
    out = _run([str(runner)])
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    assert len(lines) == 1, out.stdout.decode()
    rec = json.loads(lines[0])
    # contract keys required; extras (e.g. mfu_est) allowed
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "images/sec/chip"
    assert rec["value"] > 0


@pytest.mark.slow
def test_pipeline_bench_end_to_end(tmp_path):
    """--pipeline imagenet: generates fake JPEG TFRecords, drives the jitted
    step through the real tf.data path, reports e2e vs device-only vs host
    pipeline rates and the infeed stall fraction (VERDICT r1 #1)."""
    runner = tmp_path / "run_bench.py"
    data_dir = tmp_path / "records"
    runner.write_text(
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import sys; sys.argv = ['bench.py', '--pipeline', 'imagenet',\n"
        f"    '--data-dir', {str(data_dir)!r}, '--num-files', '2',\n"
        "    '--per-file', '16', '--batch-size', '4', '--image-size', '32',\n"
        "    '--steps', '2', '--warmup', '1']\n"
        "import bench; bench.main()\n")
    out = _run([str(runner)])
    assert out.returncode == 0, (out.stdout + out.stderr).decode(
        errors="replace")[-3000:]
    lines = [l for l in out.stdout.decode().splitlines() if l.startswith("{")]
    assert len(lines) == 1, out.stdout.decode()
    rec = json.loads(lines[0])
    assert rec["metric"].endswith("e2e_imagenet_images_per_sec_per_chip")
    assert rec["value"] > 0
    assert rec["device_only_images_per_sec_per_chip"] > 0
    assert rec["host_pipeline_images_per_sec"] > 0
    assert 0.0 <= rec["infeed_stall_fraction"] <= 1.0


@pytest.mark.slow
def test_scaling_harness_reports_efficiency():
    out = _run(["benchmarks/scaling.py", "--fake-devices", "4",
                "--image-size", "32", "--per-chip-batch", "2",
                "--steps", "2", "--warmup", "1", "--sizes", "1", "2"],
               extra_env={"XLA_FLAGS": re.sub(
                   r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))})
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    lines = [json.loads(l) for l in out.stdout.decode().splitlines()
             if l.startswith("{")]
    per_size = [l for l in lines if "mesh_size" in l]
    summary = [l for l in lines if "efficiency" in l]
    assert [l["mesh_size"] for l in per_size] == [1, 2]
    assert all(l["images_per_sec_per_chip"] > 0 for l in per_size)
    assert len(summary) == 1 and len(summary[0]["efficiency"]) == 2
    assert summary[0]["efficiency"][0] == 1.0
