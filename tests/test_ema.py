"""Parameter EMA (train.ema_decay): update math, eval/predict routing,
checkpoint roundtrip, and pre-EMA checkpoint migration."""

import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _cfg(ema=0.9, ckpt_dir="", steps=3):
    return ExperimentConfig(
        name="ema_test",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          weight_decay=0.0, decay_epochs=(1000.0,),
                          warmup_epochs=0.0),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        train=TrainConfig(steps=steps, seed=0, log_every=100,
                          ema_decay=ema, checkpoint_dir=ckpt_dir,
                          checkpoint_every_steps=2),
    )


def _quiet():
    return MetricLogger(stream=io.StringIO())


def test_ema_update_math(devices8):
    """After one step: ema == d·params₀ + (1−d)·params₁, exactly."""
    tr = Trainer(_cfg(ema=0.9), logger=_quiet())
    state0 = tr.init_state()
    p0 = jax.device_get(state0.params)
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                          seed=0, fixed=True)
    state1, _ = tr.train_step(state0, tr.shard(next(ds)), tr.base_rng())
    p1 = jax.device_get(state1.params)
    ema1 = jax.device_get(state1.ema_params)
    for e, a, b in zip(jax.tree.leaves(ema1), jax.tree.leaves(p0),
                       jax.tree.leaves(p1)):
        np.testing.assert_allclose(e, 0.9 * a + 0.1 * b, rtol=1e-6, atol=1e-7)


def test_ema_disabled_keeps_structure(devices8):
    tr = Trainer(_cfg(ema=0.0), logger=_quiet())
    state = tr.init_state()
    assert state.ema_params is None
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10, seed=0)
    state, _ = tr.train_step(state, tr.shard(next(ds)), tr.base_rng())
    assert state.ema_params is None


def test_eval_scores_ema_by_default(devices8):
    """evaluate() must score the EMA weights when present: zeroed EMA params
    produce uniform logits, so top1 over a fixed batch differs from the raw
    (trained-ish) params' — and equals a manual eval with zeroed params."""
    tr = Trainer(_cfg(ema=0.9), logger=_quiet())
    state = tr.init_state()
    zeros = jax.tree.map(jnp.zeros_like, state.params)
    state_z = state.replace(ema_params=zeros)

    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable
    rng = np.random.default_rng(3)
    images = rng.standard_normal((32, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=(32,)).astype(np.int32)

    def epoch():
        for i in range(0, 32, 16):
            yield {"image": images[i:i + 16], "label": labels[i:i + 16]}

    def ds():
        return FiniteEvalIterable(epoch, 16, (32, 32, 3), np.float32)

    ema_scores = tr.evaluate(state_z, ds())
    manual = tr.evaluate(state.replace(params=zeros), ds(), use_ema=False)
    raw_scores = tr.evaluate(state_z, ds(), use_ema=False)
    assert ema_scores["eval_top1"] == manual["eval_top1"]
    assert ema_scores["eval_examples"] == raw_scores["eval_examples"] == 32
    import pytest
    with pytest.raises(ValueError, match="ema"):
        tr.evaluate(tr.init_state().replace(ema_params=None), ds(),
                    use_ema=True)


@pytest.mark.slow
def test_ema_checkpoint_roundtrip_and_migration(devices8, tmp_path):
    """EMA state survives checkpoint/restore; a PRE-EMA checkpoint restored
    into an EMA-enabled run seeds the average from the restored params."""
    # 1) train + save WITHOUT ema
    cfg0 = _cfg(ema=0.0, ckpt_dir=str(tmp_path / "ck"), steps=2)
    tr0 = Trainer(cfg0, logger=_quiet())
    state0 = tr0.fit()
    assert state0.ema_params is None

    # 2) restore WITH ema enabled → seeded from params
    cfg1 = dataclasses.replace(
        cfg0, train=dataclasses.replace(cfg0.train, ema_decay=0.9, steps=4))
    tr1 = Trainer(cfg1, logger=_quiet())
    state1 = tr1.restore_or_init()
    assert int(jax.device_get(state1.step)) == 2
    for e, p in zip(jax.tree.leaves(jax.device_get(state1.ema_params)),
                    jax.tree.leaves(jax.device_get(state1.params))):
        np.testing.assert_array_equal(e, p)

    # 3) train on (EMA diverges from params), save, restore → EMA preserved
    state1 = tr1.fit(state1)
    assert int(jax.device_get(state1.step)) == 4
    ema_before = jax.device_get(state1.ema_params)
    p_before = jax.device_get(state1.params)
    assert any(not np.allclose(e, p) for e, p in
               zip(jax.tree.leaves(ema_before), jax.tree.leaves(p_before)))
    tr2 = Trainer(cfg1, logger=_quiet())
    state2 = tr2.restore_or_init()
    for a, b in zip(jax.tree.leaves(jax.device_get(state2.ema_params)),
                    jax.tree.leaves(ema_before)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_ema_checkpoint_reverse_migration(devices8, tmp_path):
    """An EMA checkpoint restored by a run with ema_decay=0 must resume
    cleanly (averages dropped) — the reverse of the seeding direction."""
    cfg1 = _cfg(ema=0.9, ckpt_dir=str(tmp_path / "ck"), steps=2)
    tr1 = Trainer(cfg1, logger=_quiet())
    state1 = tr1.fit()
    assert state1.ema_params is not None
    p_saved = jax.device_get(state1.params)

    cfg0 = dataclasses.replace(
        cfg1, train=dataclasses.replace(cfg1.train, ema_decay=0.0, steps=3))
    tr0 = Trainer(cfg0, logger=_quiet())
    state0 = tr0.restore_or_init()
    assert state0.ema_params is None and state0.ema_batch_stats is None
    assert int(jax.device_get(state0.step)) == 2
    for a, b in zip(jax.tree.leaves(jax.device_get(state0.params)),
                    jax.tree.leaves(p_saved)):
        np.testing.assert_array_equal(a, b)
    state0 = tr0.fit(state0)   # and training continues
    assert int(jax.device_get(state0.step)) == 3


@pytest.mark.slow
def test_ema_averages_bn_stats(devices8):
    """BN models: the moving statistics are averaged alongside the weights
    (eval with averaged weights against raw-trajectory BN stats would
    mismatch the activation distribution — code-review r3)."""
    cfg = ExperimentConfig(
        name="ema_bn",
        model=ModelConfig(name="resnet50", num_classes=10,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=64, global_batch_size=16),
        train=TrainConfig(steps=1, seed=0, ema_decay=0.5),
    )
    tr = Trainer(cfg, logger=_quiet())
    state = tr.init_state()
    bs0 = jax.device_get(state.batch_stats)
    state, _ = tr.train_step(state, tr.shard(next(
        SyntheticDataset(batch_size=16, image_size=64, num_classes=10,
                         seed=0))), tr.base_rng())
    bs1 = jax.device_get(state.batch_stats)
    ema_bs = jax.device_get(state.ema_batch_stats)
    for e, a, b in zip(jax.tree.leaves(ema_bs), jax.tree.leaves(bs0),
                       jax.tree.leaves(bs1)):
        np.testing.assert_allclose(e, 0.5 * a + 0.5 * b, rtol=1e-6, atol=1e-7)


def test_ema_with_zero1_and_accum(devices8):
    """EMA tracks the post-all-gather params under ZeRO-1 + accumulation —
    the three features compose in one step."""
    cfg = _cfg(ema=0.5)
    cfg = dataclasses.replace(
        cfg,
        mesh=MeshConfig(num_data=8, shard_opt_state=True),
        train=dataclasses.replace(cfg.train, grad_accum_steps=2))
    tr = Trainer(cfg, logger=_quiet())
    state = tr.init_state()
    p0 = jax.device_get(state.params)
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                          seed=1, fixed=True)
    state, metrics = tr.train_step(state, tr.shard(next(ds)), tr.base_rng())
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    p1 = jax.device_get(state.params)
    ema = jax.device_get(state.ema_params)
    for e, a, b in zip(jax.tree.leaves(ema), jax.tree.leaves(p0),
                       jax.tree.leaves(p1)):
        np.testing.assert_allclose(e, 0.5 * a + 0.5 * b, rtol=1e-6, atol=1e-7)
