"""Child for the two-process graceful-preemption test: trains "forever" via
Trainer.fit with checkpointing; the parent SIGTERMs ONE process, and the
per-step async stop-consensus collective (parallel/preempt.py) must stop
BOTH processes at the same step with a collective forced save (a lone host
acting on its local flag would strand the other in the Orbax collective).
log_every is deliberately HUGE: consensus must not depend on the logging
cadence (VERDICT r2 #5).

Usage: python preempt_multihost_child.py PORT NPROC PID RESULT CKPT_DIR JSONL
"""

import io
import json
import sys

from _child_bootstrap import bootstrap

PORT, NPROC, PID, OUT, CKPT, JSONL = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    sys.argv[5], sys.argv[6])

jax = bootstrap(4, coordinator_port=PORT, num_processes=NPROC,
                process_id=PID)

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.train.trainer import Trainer  # noqa: E402
from distributed_vgg_f_tpu.utils.logging import MetricLogger  # noqa: E402


def main() -> None:
    cfg = ExperimentConfig(
        name="preempt_multihost",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        mesh=MeshConfig(num_data=0),
        train=TrainConfig(steps=100_000, log_every=1_000_000, seed=0,
                          checkpoint_dir=CKPT,
                          checkpoint_every_steps=1_000_000),
    )
    # process 0 writes the JSONL the parent watches for training progress
    # (and for the preempt event)
    logger = MetricLogger(jsonl_path=JSONL) if PID == 0 else \
        MetricLogger(stream=io.StringIO())
    trainer = Trainer(cfg, logger=logger)
    # With log_every huge, no train events appear; give the parent a
    # progress signal it can watch: a sentinel written after the first step.
    orig_step = trainer.train_step

    def stepping(*a, **k):
        out = orig_step(*a, **k)
        open(OUT + ".stepped", "a").close()
        return out

    trainer.train_step = stepping
    state = trainer.fit()
    final_step = int(jax.device_get(state.step))
    with open(OUT, "w") as f:
        json.dump({"step": final_step,
                   "latest_ckpt": trainer.checkpoints.latest_step()}, f)


if __name__ == "__main__":
    main()
