"""uint8 ingest wire gates (r8): the quantization bound, the kill-switch
fallback contract, the device-finish prologue's single-normalization
invariant, and the wire's train-trajectory equivalence.

Parity structure (PR 3 style):
 - the u8 pixels differ from the float-path bilinear by at most ONE
   intensity level (the fixed-point kernels' 8-bit-fraction weights are
   the wire's only precision loss) — a tolerance gate;
 - with the wire kill-switched off, the host-normalize paths are
   BYTE-IDENTICAL to their pre-u8 (r7) behavior — an equality gate;
 - for identical u8 pixels, host normalize and device finish perform the
   same single-rounded f32 ops, so the CPU train-loss trajectories of the
   two wires are EQUAL, not merely close.
"""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import DataConfig
from distributed_vgg_f_tpu.data.device_ingest import (
    make_device_finish,
    space_to_depth_batch,
)
from distributed_vgg_f_tpu.data.native_jpeg import (
    NativeJpegTrainIterator,
    decode_single_image,
    load_native_jpeg,
    set_wire_u8,
    wire_u8_enabled,
    wire_u8_supported,
)

MEAN = (123.68, 116.78, 103.94)
STD = (58.393, 57.12, 57.375)

_native = load_native_jpeg() is not None

requires_native = pytest.mark.skipif(
    not _native, reason="native jpeg loader unavailable")
requires_wire_u8 = pytest.mark.skipif(
    not (_native and wire_u8_supported()),
    reason="uint8 wire compiled out (-DDVGGF_NO_WIRE_U8) or library "
           "unavailable")


@pytest.fixture(autouse=True)
def _restore_wire():
    """Every test leaves the process-wide u8-wire dispatch as it found it."""
    if not _native:
        yield
        return
    before = wire_u8_enabled()
    yield
    set_wire_u8(before)


def _jpeg_bytes(h=64, w=80, seed=0) -> bytes:
    from PIL import Image
    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 256, size=(h, w, 3))
                    .astype(np.uint8)).save(buf, "JPEG", quality=90)
    return buf.getvalue()


# --------------------------------------------------------- native u8 decode
@requires_wire_u8
@pytest.mark.parametrize("eval_mode", [False, True])
def test_u8_within_one_level_of_float_bilinear(eval_mode):
    """The pinned quantization bound: identity-normalize f32 decode vs the
    fixed-point u8 decode of the SAME crop (same seed → same crop/flip)
    differ by <= 1 intensity level per channel (1/255 of full scale)."""
    data = _jpeg_bytes()
    zero, one = np.zeros(3, np.float32), np.ones(3, np.float32)
    for seed in (0, 7, 23):
        f32 = decode_single_image(data, 32, zero, one, eval_mode=eval_mode,
                                  rng_seed=seed)
        u8 = decode_single_image(data, 32, zero, one, eval_mode=eval_mode,
                                 rng_seed=seed, image_dtype="uint8")
        assert u8.dtype == np.uint8 and u8.shape == (32, 32, 3)
        err = np.abs(u8.astype(np.float32) - f32)
        assert float(err.max()) <= 1.0, (
            f"u8 wire {err.max():.3f} levels off the float bilinear "
            f"(seed {seed}) — the fixed-point kernels drifted past the "
            "pinned 1/255-per-channel bound")


@requires_wire_u8
def test_u8_never_packs_on_host():
    """space-to-depth belongs to the device finish: both creation surfaces
    refuse a u8 + pack4 combination loudly."""
    data = _jpeg_bytes()
    with pytest.raises(ValueError, match="device-finish"):
        decode_single_image(data, 32, MEAN, STD, image_dtype="uint8",
                            pack4=True)


@requires_wire_u8
def test_corrupt_image_fill_is_mean_on_u8_wire(tmp_path):
    """Decode-failure fallback parity across wires: host wires zero-fill
    POST-normalize (a mean image downstream); the u8 wire must ship the
    rounded per-channel MEAN so the device finish lands within half an
    intensity level of that zero — the same failing input may not yield
    materially different training data depending on data.wire."""
    from PIL import Image
    rng = np.random.default_rng(3)
    files = []
    for i in range(3):
        p = tmp_path / f"img_{i}.jpg"
        Image.fromarray(rng.integers(0, 256, size=(40, 44, 3))
                        .astype(np.uint8)).save(p, "JPEG", quality=90)
        files.append(str(p))
    bad = tmp_path / "corrupt.jpg"
    bad.write_bytes(b"\xff\xd8\xff\xe0not a jpeg at all")
    files.append(str(bad))

    it = NativeJpegTrainIterator(files, [0, 1, 2, 3], batch=4, image_size=32,
                                 mean=np.asarray(MEAN, np.float32),
                                 std=np.asarray(STD, np.float32),
                                 image_dtype="uint8", num_threads=1, seed=0)
    try:
        batch = next(it)  # batch == dataset, so the corrupt item is in it
        assert it.decode_errors() == 1
    finally:
        it.close()
    expected = np.broadcast_to(
        np.round(np.asarray(MEAN)).astype(np.uint8), (32, 32, 3))
    filled = [i for i in range(4)
              if np.array_equal(batch["image"][i], expected)]
    assert len(filled) == 1, "exactly the corrupt item is mean-filled"
    # and the device finish reads it as ~the host wires' zero-fill
    finish = make_device_finish(MEAN, STD)
    finished = np.asarray(finish(jnp.asarray(batch["image"][filled[0]][None])))
    assert np.abs(finished).max() <= 0.5 / min(STD) + 1e-6


@requires_native
def test_kill_switch_off_is_byte_identical_to_r7_path():
    """DVGGF_WIRE_U8 off: u8 loader creation refuses (the Python layer
    falls back ABOVE the ABI) and the host-normalize wires produce
    byte-identical output whether the u8 wire is armed or not — the
    r7-parity half of the kill-switch contract."""
    data = _jpeg_bytes()
    outs = {}
    for enabled in (True, False):
        if set_wire_u8(enabled) is None:
            pytest.skip("native library unavailable")
        for dtype in ("float32", "bfloat16"):
            out = decode_single_image(data, 32, MEAN, STD, image_dtype=dtype,
                                      eval_mode=True)
            key = (dtype,)
            if key in outs:
                np.testing.assert_array_equal(
                    outs[key].view(np.uint8), out.view(np.uint8),
                    err_msg=f"{dtype} host wire drifted with the u8 "
                            "kill-switch — the wire must be purely additive")
            outs[key] = out
    # and with the wire off, the u8 kind is refused, not silently degraded
    set_wire_u8(False)
    if wire_u8_supported():
        with pytest.raises(RuntimeError, match="refused"):
            decode_single_image(data, 32, MEAN, STD, image_dtype="uint8")


@requires_wire_u8
def test_train_iterator_ships_uint8(tmp_path):
    """The u8-armed train iterator yields raw uint8 HWC batches (no
    normalize, no pack) and refuses a host space_to_depth request."""
    from PIL import Image
    rng = np.random.default_rng(0)
    files = []
    for i in range(4):
        p = tmp_path / f"img_{i}.jpg"
        Image.fromarray(rng.integers(0, 256, size=(48, 52, 3))
                        .astype(np.uint8)).save(p, "JPEG", quality=90)
        files.append(str(p))
    it = NativeJpegTrainIterator(files, [0, 1, 2, 3], batch=4, image_size=32,
                                 mean=np.asarray(MEAN, np.float32),
                                 std=np.asarray(STD, np.float32),
                                 image_dtype="uint8", num_threads=1, seed=0)
    try:
        batch = next(it)
        assert batch["image"].dtype == np.uint8
        assert batch["image"].shape == (4, 32, 32, 3)
        assert it.image_dtype == "uint8"
    finally:
        it.close()
    with pytest.raises(ValueError, match="space-to-depth|space_to_depth"):
        NativeJpegTrainIterator(files, [0, 1, 2, 3], batch=4, image_size=32,
                                mean=np.asarray(MEAN, np.float32),
                                std=np.asarray(STD, np.float32),
                                image_dtype="uint8", num_threads=1, seed=0,
                                space_to_depth=True)


@requires_native
def test_ingest_layer_falls_back_when_wire_refused(tmp_path, caplog):
    """data.wire='u8' with the wire kill-switched: the imagenet builder
    must construct the HOST-normalize iterator (pre-r8 behavior) and log
    the fallback — never fail, never silently ship a different format."""
    import logging

    from distributed_vgg_f_tpu.data.imagenet import _wire_u8_active
    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path), wire="u8")
    set_wire_u8(False)
    with caplog.at_level(logging.WARNING,
                         logger="distributed_vgg_f_tpu.data.imagenet"):
        assert _wire_u8_active(cfg, is_train=True) is False
    assert any("falling back" in r.message for r in caplog.records)
    if wire_u8_supported():
        set_wire_u8(True)
        assert _wire_u8_active(cfg, is_train=True) is True
    # eval streams always ride the host wire, no warning involved
    assert _wire_u8_active(cfg, is_train=False) is False


def test_non_native_backend_warns_wire_unshipped(caplog):
    """data.wire='u8' on a backend that cannot ship it (tf.data, grain)
    logs the fallback — the 'never a silent format change' half of the
    contract for the paths that never reach the native loader."""
    import logging

    from distributed_vgg_f_tpu.data.imagenet import _warn_wire_u8_unshipped
    cfg = DataConfig(name="imagenet", wire="u8")
    with caplog.at_level(logging.WARNING,
                         logger="distributed_vgg_f_tpu.data.imagenet"):
        _warn_wire_u8_unshipped(cfg, True, "tf.data")
    assert any("only the native train loader" in r.message
               for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="distributed_vgg_f_tpu.data.imagenet"):
        # eval streams and host wires are by-design silent
        _warn_wire_u8_unshipped(cfg, False, "tf.data")
        _warn_wire_u8_unshipped(DataConfig(name="imagenet"), True, "grain")
    assert not caplog.records


# ------------------------------------------------------------ device finish
def test_finish_passthrough_on_float_batches():
    """Host-normalized batches (every pre-r8 wire) pass through UNTOUCHED —
    the structural half of the single-normalization contract."""
    finish = make_device_finish(MEAN, STD)
    for dtype in (jnp.float32, jnp.bfloat16):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 8, 3)),
                        dtype)
        np.testing.assert_array_equal(np.asarray(finish(x)), np.asarray(x))


def test_finish_normalizes_uint8_exactly_once():
    """A sentinel uint8 batch is normalized once; re-feeding the finish its
    own output is a no-op (dtype dispatch makes double-normalize
    structurally impossible)."""
    finish = make_device_finish(MEAN, STD)
    x = jnp.full((2, 8, 8, 3), 100, jnp.uint8)
    once = finish(x)
    assert once.dtype == jnp.float32
    expect = (100.0 - np.asarray(MEAN, np.float32)) \
        * (np.float32(1.0) / np.asarray(STD, np.float32))
    np.testing.assert_allclose(np.asarray(once)[0, 0, 0], expect, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(finish(once)), np.asarray(once))


def test_finish_matches_host_normalize_bitwise():
    """For identical u8 pixels the device finish and the host wire compute
    the SAME single-rounded f32 ops ((v - mean) * (1/std)) — bit-identical
    output, the basis of the loss-trajectory equivalence gate."""
    rng = np.random.default_rng(3)
    pixels = rng.integers(0, 256, size=(4, 16, 16, 3)).astype(np.uint8)
    finish = make_device_finish(MEAN, STD)
    device = np.asarray(jax.jit(finish)(jnp.asarray(pixels)))
    mean = np.asarray(MEAN, np.float32)
    inv = np.float32(1.0) / np.asarray(STD, np.float32)
    host = (pixels.astype(np.float32) - mean) * inv
    np.testing.assert_array_equal(device, host)


def test_finish_space_to_depth_matches_reference():
    """The device-side 4x4 packing emits tf.nn.space_to_depth's (dy, dx, c)
    channel order — the host packer's and the VGG-F stem's contract."""
    rng = np.random.default_rng(5)
    pixels = rng.integers(0, 256, size=(2, 8, 8, 3)).astype(np.uint8)
    finish = make_device_finish((0.0, 0.0, 0.0), (1.0, 1.0, 1.0),
                                space_to_depth=True)
    packed = np.asarray(finish(jnp.asarray(pixels)))
    assert packed.shape == (2, 2, 2, 48)
    x = pixels.astype(np.float32)
    for b in (0, 1):
        for by in (0, 1):
            for bx in (0, 1):
                for dy in range(4):
                    for dx in range(4):
                        for c in range(3):
                            assert packed[b, by, bx, (dy * 4 + dx) * 3 + c] \
                                == x[b, by * 4 + dy, bx * 4 + dx, c]
    # eval-shaped (non-%4 or packed-already) inputs pass through unpacked
    odd = jnp.asarray(rng.integers(0, 256, size=(1, 6, 6, 3)), jnp.uint8)
    assert finish(odd).shape == (1, 6, 6, 3)


def test_space_to_depth_batch_bfloat16_preserved():
    x = jnp.ones((1, 8, 8, 3), jnp.bfloat16)
    assert space_to_depth_batch(x).dtype == jnp.bfloat16


def test_vggf_refuses_raw_uint8():
    """Raw wire pixels must never silently reach the model: a uint8 batch
    convolved as 0..255 floats would train, badly, with no error."""
    from distributed_vgg_f_tpu.models.vggf import VGGF
    model = VGGF(num_classes=4, compute_dtype=jnp.float32)
    with pytest.raises(TypeError, match="device-finish"):
        model.init(jax.random.key(0),
                   jnp.zeros((1, 32, 32, 3), jnp.uint8))


# ----------------------------------------------- step-level single-normalize
class _MiniNet:
    """Tiny flax model standing in for VGG-F in step-level gates (one conv
    + head keeps the jit cheap inside the tier-1 budget)."""

    def __new__(cls):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, *, train=False, rngs=None):
                x = nn.Conv(8, (3, 3), strides=(2, 2), dtype=jnp.float32)(x)
                x = nn.relu(x)
                x = x.reshape((x.shape[0], -1))
                return nn.Dense(10, dtype=jnp.float32)(x)

        return Net()


def _mesh8(devices8):
    from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
    return build_mesh(MeshSpec(("data",), (8,)), devices=devices8)


def test_eval_step_u8_matches_host_wire(devices8):
    """The satellite's sentinel gate: the SAME pixels fed as (a) a uint8
    wire batch and (b) a host-normalized f32 batch produce identical eval
    counts through the eval step's device-finish prologue — and the float
    batch is provably not re-normalized (it matches the no-finish step)."""
    from distributed_vgg_f_tpu.parallel.mesh import shard_host_batch
    from distributed_vgg_f_tpu.train.step import build_eval_step
    mesh = _mesh8(devices8)
    model = _MiniNet()
    rng = np.random.default_rng(11)
    pixels = rng.integers(0, 256, size=(16, 16, 16, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, size=(16,)).astype(np.int32)
    mean = np.asarray(MEAN, np.float32)
    inv = np.float32(1.0) / np.asarray(STD, np.float32)
    host_norm = (pixels.astype(np.float32) - mean) * inv

    from distributed_vgg_f_tpu.train.state import TrainState
    import optax
    state = TrainState.create(model, optax.sgd(0.1), jax.random.key(0),
                              jnp.zeros((1, 16, 16, 3), jnp.float32))

    finish = make_device_finish(MEAN, STD)
    with_finish = build_eval_step(model, mesh, device_finish=finish)
    without = build_eval_step(model, mesh)

    def counts(step, images):
        batch = shard_host_batch({"image": images, "label": labels}, mesh)
        return {k: int(v) for k, v in
                jax.device_get(step(state, batch)).items()}

    via_u8 = counts(with_finish, pixels)
    via_host = counts(with_finish, host_norm)
    no_finish = counts(without, host_norm)
    assert via_u8 == via_host, (
        "u8 wire and host wire disagree through the eval prologue")
    assert via_host == no_finish, (
        "the finish re-normalized an already-normalized batch — the "
        "double-normalize hazard the dtype dispatch must prevent")


def test_train_loss_trajectory_equivalent_across_wires(devices8):
    """CPU loss-trajectory equivalence smoke (the acceptance gate): N steps
    on the SAME u8 pixel stream, once through the u8 wire + device finish,
    once host-normalized — equal loss trajectories (both wires perform the
    same single-rounded f32 normalize, see
    test_finish_matches_host_normalize_bitwise)."""
    import optax

    from distributed_vgg_f_tpu.parallel.mesh import shard_host_batch
    from distributed_vgg_f_tpu.train.state import TrainState
    from distributed_vgg_f_tpu.train.step import build_train_step
    mesh = _mesh8(devices8)
    model = _MiniNet()
    rng = np.random.default_rng(17)
    batches = [rng.integers(0, 256, size=(16, 16, 16, 3)).astype(np.uint8)
               for _ in range(3)]
    labels = [rng.integers(0, 10, size=(16,)).astype(np.int32)
              for _ in range(3)]
    mean = np.asarray(MEAN, np.float32)
    inv = np.float32(1.0) / np.asarray(STD, np.float32)

    def run(as_u8: bool):
        tx = optax.sgd(0.05)
        state = TrainState.create(model, tx, jax.random.key(0),
                                  jnp.zeros((1, 16, 16, 3), jnp.float32))
        step = build_train_step(
            model, tx, mesh, weight_decay=1e-4,
            device_finish=make_device_finish(MEAN, STD))
        base = jax.jit(lambda: jax.random.key(1))()
        losses = []
        for px, lb in zip(batches, labels):
            images = px if as_u8 else (px.astype(np.float32) - mean) * inv
            batch = shard_host_batch({"image": images, "label": lb}, mesh)
            state, metrics = step(state, batch, base)
            losses.append(float(jax.device_get(metrics["loss"])))
        return losses

    np.testing.assert_array_equal(run(True), run(False))


# ----------------------------------------------------- prefetch + telemetry
@pytest.fixture()
def _fresh_telemetry():
    telemetry.reset()
    telemetry.configure(enabled=True)
    yield
    telemetry.reset()
    telemetry.configure(enabled=True)


def test_prefetch_refuses_u8_ring_armed_source(devices8):
    """The r7 buffer-ownership contract holds for uint8-armed sources: a
    ring-recycling iterator is refused regardless of wire format."""
    from distributed_vgg_f_tpu.data.prefetch import DevicePrefetchIterator
    mesh = _mesh8(devices8)

    class _U8RingSource:
        reuses_output_buffers = True

        def __iter__(self):
            return self

        def __next__(self):
            return {"image": np.zeros((8, 4, 4, 3), np.uint8),
                    "label": np.zeros((8,), np.int32)}

    with pytest.raises(ValueError, match="reuse"):
        DevicePrefetchIterator(_U8RingSource(), mesh, buffer_size=2)


def test_prefetch_device_put_bytes_counter(devices8, _fresh_telemetry):
    """prefetch/device_put_bytes counts what the wire actually ships —
    1 B/px for a u8 batch, 4 for f32 — and bytes_in_flight drains to 0
    once every queued batch is consumed."""
    from distributed_vgg_f_tpu.data.prefetch import DevicePrefetchIterator
    mesh = _mesh8(devices8)

    def source(dtype, n=3):
        for _ in range(n):
            yield {"image": np.zeros((8, 4, 4, 3), dtype),
                   "label": np.zeros((8,), np.int32)}

    for dtype, px_bytes in ((np.uint8, 1), (np.float32, 4)):
        telemetry.reset()
        per_batch = 8 * 4 * 4 * 3 * px_bytes + 8 * 4  # images + i32 labels
        pre = DevicePrefetchIterator(source(dtype), mesh, buffer_size=2)
        try:
            for _ in range(3):
                next(pre)
            with pytest.raises(StopIteration):
                next(pre)
        finally:
            pre.close()
        snap = telemetry.get_registry().snapshot_split()
        counters = snap.get("counters", snap)
        assert counters["prefetch/device_put_bytes"] == 3 * per_batch, dtype
        gauges = snap.get("gauges", {})
        assert gauges.get("prefetch/bytes_in_flight", 0) == 0


# ------------------------------------------------------------------- schema
def test_schema_validates_wire_fields():
    from distributed_vgg_f_tpu.telemetry.schema import validate_bench_artifact
    good = {"metric": "m", "value": 1000.0, "layouts": [
        {"wire": "u8", "wire_bytes_per_image": 150528,
         "profile": {"jpeg_us_per_image": 700.0,
                     "resample_us_per_image": 110.0}}]}
    assert validate_bench_artifact(good) == []
    bad = {"metric": "m", "value": 1000.0, "layouts": [
        {"wire": "u9", "wire_bytes_per_image": -3,
         "profile": {"jpeg_us_per_image": -1.0}}]}
    errors = validate_bench_artifact(bad)
    assert any("'wire'" in e for e in errors)
    assert any("wire_bytes_per_image" in e for e in errors)
    assert any("jpeg_us_per_image" in e for e in errors)


def test_config_validates_wire():
    with pytest.raises(ValueError, match="data.wire"):
        DataConfig(wire="uint8")
    with pytest.raises(ValueError, match="image_dtype"):
        DataConfig(image_dtype="uint8")
    for wire in ("auto", "host_f32", "host_bf16", "u8"):
        DataConfig(wire=wire)


def test_wire_bytes_per_pixel():
    from distributed_vgg_f_tpu.data.dtypes import wire_bytes_per_pixel
    assert wire_bytes_per_pixel("u8", "float32") == 3
    assert wire_bytes_per_pixel("host_bf16", "float32") == 6
    assert wire_bytes_per_pixel("host_f32", "bfloat16") == 12
    assert wire_bytes_per_pixel("auto", "bfloat16") == 6
