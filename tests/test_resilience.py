"""Chaos suite: every resilience guard proven end-to-end on CPU via the
fault-injection harness (resilience/faults.py) — NaN-batch skip/abort,
loader stall → DataStallError, dead prefetch worker, truncated-checkpoint
fallback restore, transient-save retry, and injected preemption composing
with the PreemptConsensus collective. The guards exist for faults CI never
throws on its own; this file throws them on purpose."""

import dataclasses
import io
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.data.prefetch import DevicePrefetchIterator
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.resilience import (
    CheckpointIntegrityError,
    DataStallError,
    FaultPlan,
    NonFiniteStepError,
    truncate_checkpoint,
)
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _cfg(steps=4, ckpt_dir="", **train_kw):
    return ExperimentConfig(
        name="resilience_test",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        train=TrainConfig(steps=steps, log_every=1, seed=0,
                          checkpoint_every_steps=2,
                          checkpoint_dir=str(ckpt_dir), **train_kw),
    )


def _quiet():
    return MetricLogger(stream=io.StringIO())


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(tree))]


# --------------------------------------------------------------- fault specs
def test_fault_plan_parsing():
    assert FaultPlan.parse("") is None
    assert FaultPlan.parse("   ") is None
    p = FaultPlan.parse("nan@3,stall@5:20,preempt@8")
    assert (p.nan_start, p.nan_end) == (3, 3)
    assert (p.stall_step, p.stall_seconds) == (5, 20.0)
    assert p.preempt_step == 8
    assert p.has_data_faults
    p2 = FaultPlan.parse("nan@4+")
    assert (p2.nan_start, p2.nan_end) == (4, None)
    assert p2._nan_at(4) and p2._nan_at(400) and not p2._nan_at(3)
    p3 = FaultPlan.parse("nan@2-5,crash@9")
    assert (p3.nan_start, p3.nan_end, p3.crash_step) == (2, 5, 9)
    assert FaultPlan.parse("preempt@2").preempt_now(3)  # >= semantics
    for bad in ("nan", "nan@0", "stall@3", "bogus@1", "nan@5-2",
                "crash@2:5", "preempt@2+",
                "nan@3:5",            # stall-style tail on nan
                "crash@2,crash@7",    # duplicate kind: last-wins would
                "nan@2,nan@9"):       # silently drop an injector
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_fault_injection_config_validated_at_config_time():
    with pytest.raises(ValueError, match="fault token"):
        _cfg(fault_injection="bogus@1")


# --------------------------------------------------- non-finite step guard
def test_nan_batch_skipped_params_bit_identical(devices8):
    """Acceptance: an injected NaN batch is SKIPPED — params, opt state and
    BN state bit-identical across the bad step, step counter still
    advances, metrics report bad_step=1 — and a following clean batch
    trains normally."""
    tr = Trainer(_cfg(steps=2), logger=_quiet())
    state = tr.init_state()
    rng = tr.base_rng()
    src = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                           seed=0)
    good = next(src)
    nan_batch = dict(good)
    nan_batch["image"] = np.full_like(np.asarray(good["image"]), np.nan)

    before = _leaves(state.params)
    opt_before = _leaves(state.opt_state)
    state, metrics = tr.train_step(state, tr.shard(nan_batch), rng)
    assert float(jax.device_get(metrics["bad_step"])) == 1.0
    assert int(jax.device_get(state.step)) == 1  # counter still advances
    for a, b in zip(before, _leaves(state.params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(opt_before, _leaves(state.opt_state)):
        np.testing.assert_array_equal(a, b)

    state, metrics = tr.train_step(state, tr.shard(good), rng)
    assert float(jax.device_get(metrics["bad_step"])) == 0.0
    assert any(not np.array_equal(a, b)
               for a, b in zip(before, _leaves(state.params)))


def test_single_nan_batch_run_completes_with_skip_logged(devices8):
    """End-to-end: fault_injection="nan@2" mid-run — fit completes all
    steps, exactly one skip is counted, and the final params match a run
    whose step 2 was never applied (the skipped step changed nothing)."""
    log = io.StringIO()
    tr = Trainer(_cfg(steps=4, fault_injection="nan@2"),
                 logger=MetricLogger(stream=log))
    state = tr.fit(tr.init_state())
    assert int(jax.device_get(state.step)) == 4
    assert "[nonfinite_step_skipped]" in log.getvalue()
    assert "nonfinite_skips=1" in log.getvalue()


def test_consecutive_nonfinite_steps_abort_with_diagnostic(devices8):
    """Acceptance: K consecutive bad steps abort with a NonFiniteStepError
    whose message carries the step, the threshold knob, and triage hints —
    well before the configured horizon burns."""
    tr = Trainer(_cfg(steps=50, fault_injection="nan@1+",
                      max_nonfinite_steps=3), logger=_quiet())
    with pytest.raises(NonFiniteStepError) as exc:
        tr.fit(tr.init_state())
    msg = str(exc.value)
    assert "3 consecutive" in msg
    assert "max_nonfinite_steps" in msg
    assert "aborting" in msg


def test_guard_disabled_keeps_legacy_semantics(devices8):
    """skip_nonfinite=False: no bad_step metric, no skip select, no abort —
    the legacy jax_debug_nans-or-nothing behavior stays reachable. The NaN
    loss flows through unguarded (and at least one parameter tree leaf is
    poisoned by the unskipped update)."""
    tr = Trainer(_cfg(steps=2, skip_nonfinite=False), logger=_quiet())
    state = tr.init_state()
    rng = tr.base_rng()
    src = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                           seed=0)
    batch = dict(next(src))
    batch["image"] = np.full_like(np.asarray(batch["image"]), np.nan)
    state, metrics = tr.train_step(state, tr.shard(batch), rng)
    assert "bad_step" not in metrics
    assert not np.isfinite(float(jax.device_get(metrics["loss"])))
    assert any(not np.isfinite(l).all() for l in _leaves(state.params))


# ------------------------------------------------------------ data watchdog
@pytest.fixture()
def mesh8(devices8):
    return build_mesh(MeshSpec(("data",), (8,)), devices=devices8)


def test_loader_stall_raises_data_stall_error(devices8):
    """Acceptance: an injected loader stall surfaces as a typed
    DataStallError within the configured timeout+backoff window instead of
    hanging the step loop indefinitely."""
    tr = Trainer(_cfg(steps=6, fault_injection="stall@2:300",
                      data_timeout_s=0.3, data_timeout_retries=1),
                 logger=_quiet())
    t0 = time.monotonic()
    with pytest.raises(DataStallError, match="stalled"):
        tr.fit(tr.init_state())
    # 0.3s + 0.6s backoff plus slack for the first step's (possibly cold)
    # compile — but nowhere near the 300s stall a hang would ride out
    assert time.monotonic() - t0 < 120.0


def test_stall_shorter_than_timeout_is_tolerated(devices8):
    """A pause the watchdog budget covers (timeout doubles per retry) must
    NOT kill the run — the retry ladder exists exactly so transient slowness
    survives."""
    tr = Trainer(_cfg(steps=3, fault_injection="stall@2:0.4",
                      data_timeout_s=0.5, data_timeout_retries=4),
                 logger=_quiet())
    state = tr.fit(tr.init_state())
    assert int(jax.device_get(state.step)) == 3


def test_watchdog_inactive_without_prefetch_is_logged(devices8):
    """data_timeout_s with prefetch_to_device=0 cannot engage (the sync
    fallback has no thread to time-bound) — a configured-but-inert watchdog
    must be loud in the log, never silent (code-review)."""
    log = io.StringIO()
    tr = Trainer(_cfg(steps=2, data_timeout_s=5.0, prefetch_to_device=0),
                 logger=MetricLogger(stream=log))
    state = tr.fit(tr.init_state())
    assert int(jax.device_get(state.step)) == 2
    assert "[data_watchdog_inactive]" in log.getvalue()


def test_crash_injection_propagates_typed_error(devices8):
    from distributed_vgg_f_tpu.resilience import InjectedFault
    tr = Trainer(_cfg(steps=6, fault_injection="crash@2"), logger=_quiet())
    with pytest.raises(InjectedFault, match="injected loader crash"):
        tr.fit(tr.init_state())


def test_dead_prefetch_worker_detected(mesh8, monkeypatch):
    """A worker thread that dies without delivering a batch OR an error
    (C-level death, not a Python exception) must surface as DataStallError
    — with no timeout configured — instead of blocking on a queue nothing
    will ever fill."""
    monkeypatch.setattr(DevicePrefetchIterator, "_worker",
                        lambda self: None)  # dies silently, delivers nothing
    src = SyntheticDataset(batch_size=16, image_size=8, num_classes=10,
                           seed=0)
    pre = DevicePrefetchIterator(src, mesh8)
    try:
        with pytest.raises(DataStallError, match="died"):
            next(pre)
    finally:
        pre.close()


def test_watchdog_timeout_only_after_all_retries(mesh8):
    """The backoff ladder is bounded: total wait ≈ t·(2^(r+1)−1); a source
    that stays silent exhausts it and the error names the budget knob."""

    def silent():
        time.sleep(600)
        yield {}

    pre = DevicePrefetchIterator(silent(), mesh8, batch_timeout_s=0.2,
                                 timeout_retries=2)
    try:
        t0 = time.monotonic()
        with pytest.raises(DataStallError, match="data_timeout_s"):
            next(pre)
        waited = time.monotonic() - t0
        assert 1.0 <= waited < 15.0  # ~0.2+0.4+0.8 plus poll slack
    finally:
        pre.close()


# ------------------------------------------------------ checkpoint integrity
def test_truncated_latest_checkpoint_falls_back_to_intact(devices8,
                                                          tmp_path):
    """Acceptance: a truncated latest checkpoint restores transparently
    from the newest INTACT one — detected by the checksum manifest, logged,
    with the integrity fallback recorded on the manager."""
    cfg = _cfg(steps=4, ckpt_dir=tmp_path / "ckpt")
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()  # checkpoints at steps 2 and 4 (+manifests via wait())
    assert {2, 4} <= set(tr.checkpoints.all_steps())
    assert tr.checkpoints.verify_step(4)

    damaged = truncate_checkpoint(str(tmp_path / "ckpt"))  # newest = step 4
    assert "/4/" in damaged

    log = io.StringIO()
    tr2 = Trainer(cfg, logger=MetricLogger(stream=log))
    restored = tr2.restore_or_init()
    assert int(jax.device_get(restored.step)) == 2
    assert not tr2.checkpoints.verify_step(4)
    fallback = tr2.checkpoints.last_integrity_fallback
    assert fallback is not None and fallback["chosen"] == 2
    assert [s for s, _ in fallback["skipped"]] == [4]
    assert "checkpoint_integrity_fallback" in log.getvalue()


def test_every_checkpoint_corrupt_refuses_restore(devices8, tmp_path):
    """With NOTHING intact the trainer must refuse to silently reinitialize
    over a damaged run — CheckpointIntegrityError, not a fresh init."""
    cfg = _cfg(steps=4, ckpt_dir=tmp_path / "ckpt")
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()
    for step in tr.checkpoints.all_steps():  # Orbax also saved step 1
        truncate_checkpoint(str(tmp_path / "ckpt"), step=step)
    tr2 = Trainer(cfg, logger=_quiet())
    with pytest.raises(CheckpointIntegrityError, match="none passed"):
        tr2.restore_or_init()


def test_explicit_corrupt_step_raises_not_substitutes(devices8, tmp_path):
    """An EXPLICITLY requested step that fails verification raises — the
    caller asked for that exact state; silently handing back another step
    would be time travel."""
    cfg = _cfg(steps=4, ckpt_dir=tmp_path / "ckpt")
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()
    truncate_checkpoint(str(tmp_path / "ckpt"), step=4)
    with pytest.raises(CheckpointIntegrityError, match="step 4"):
        tr.checkpoints.restore(tr.init_state(), step=4)


def test_legacy_checkpoint_without_manifest_still_restores(devices8,
                                                           tmp_path):
    """Pre-manifest checkpoints (and the crash window before a manifest
    flush) verify as unknown and stay restorable — integrity checking must
    not brick existing checkpoint dirs."""
    import os
    import shutil
    cfg = _cfg(steps=4, ckpt_dir=tmp_path / "ckpt")
    tr = Trainer(cfg, logger=_quiet())
    state = tr.fit()
    shutil.rmtree(os.path.join(str(tmp_path / "ckpt"), "integrity"))
    tr2 = Trainer(cfg, logger=_quiet())
    assert tr2.checkpoints.verify_step(4)  # unknown → restorable
    restored = tr2.restore_or_init()
    assert int(jax.device_get(restored.step)) == 4
    for a, b in zip(_leaves(state.params), _leaves(restored.params)):
        np.testing.assert_array_equal(a, b)


def test_save_retries_transient_io_error(devices8, tmp_path):
    """A transient OSError during the save dispatch is retried with backoff
    and the save succeeds; a permanent failure still propagates once the
    budget is spent."""
    from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager

    tr = Trainer(_cfg(steps=1), logger=_quiet())
    state = tr.init_state()

    mgr = CheckpointManager(str(tmp_path / "flaky"), save_retries=2)
    orig_save, fails = mgr._mngr.save, {"n": 2}

    def flaky_save(*a, **k):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient I/O blip")
        return orig_save(*a, **k)

    mgr._mngr.save = flaky_save
    assert mgr.save(state, force=True)
    mgr.wait()
    assert mgr.latest_step() == 0

    mgr2 = CheckpointManager(str(tmp_path / "flaky2"), save_retries=1)
    mgr2._mngr.save = lambda *a, **k: (_ for _ in ()).throw(
        OSError("disk is gone"))
    with pytest.raises(OSError, match="disk is gone"):
        mgr2.save(state, force=True)


def test_orphaned_manifests_pruned_resave_not_bricked(devices8, tmp_path):
    """Orbax's retention GC deletes step dirs without passing through
    delete(), orphaning their manifests; a stale manifest for a GC'd step
    NUMBER must not falsely flag a later re-save of that number as corrupt
    (branched runs re-reach old step numbers). Flushes prune orphans, and
    a re-save under a planted stale manifest verifies clean."""
    import shutil
    from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager
    from distributed_vgg_f_tpu.resilience.integrity import (
        list_manifest_steps, manifest_path)

    tr = Trainer(_cfg(steps=1), logger=_quiet())
    state = tr.init_state()
    root = str(tmp_path / "gc")
    mgr = CheckpointManager(root, max_to_keep=2, save_interval_steps=1)
    for s in range(4):
        assert mgr.save(state.replace(step=jnp.asarray(s, jnp.int32)),
                        force=True)
    mgr.wait()
    kept = set(mgr.all_steps())
    assert kept == {2, 3}
    # GC'd steps' manifests were pruned at the flushes
    assert set(list_manifest_steps(root)) <= kept

    # plant a stale manifest for GC'd step 0 (as if the process died between
    # the GC and the prune), then re-save step 0 via a fresh manager — the
    # save-entry flush must prune the orphan so the new step verifies clean
    shutil.copyfile(manifest_path(root, 3), manifest_path(root, 0))
    mgr2 = CheckpointManager(root, max_to_keep=2, save_interval_steps=1)
    assert mgr2.save(state.replace(step=jnp.asarray(0, jnp.int32)),
                     force=True)
    mgr2.wait()
    assert mgr2.verify_step(0)
    assert mgr2.restore(tr.init_state(), step=0)


# --------------------------------------------------------- preemption faults
def test_injected_preemption_checkpoints_and_stops(devices8, tmp_path):
    """fault_injection="preempt@2" drives the full SIGTERM path without a
    signal: stop after step 2, forced checkpoint, clean return — and a
    restart resumes from the preemption step."""
    log = io.StringIO()
    cfg = _cfg(steps=10, ckpt_dir=tmp_path / "ckpt",
               fault_injection="preempt@2")
    tr = Trainer(cfg, logger=MetricLogger(stream=log))
    state = tr.fit()
    assert int(jax.device_get(state.step)) == 2
    assert tr.checkpoints.latest_step() == 2
    assert "[preempt]" in log.getvalue()

    clean = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, fault_injection="", steps=4))
    resumed = Trainer(clean, logger=_quiet()).fit()
    assert int(jax.device_get(resumed.step)) == 4


def test_injected_preemption_composes_with_consensus(devices8):
    """The injector raises the same local flag a real SIGTERM would, so it
    composes with the PreemptConsensus collective: every poll index observes
    the same verdict, reaching consensus within LAG+1 polls of the injected
    step — the multi-host stop path, exercised on the fake 8-device mesh."""
    from distributed_vgg_f_tpu.parallel.preempt import PreemptConsensus

    mesh = build_mesh(MeshSpec(("data",), (8,)))
    consensus = PreemptConsensus(mesh)
    plan = FaultPlan.parse("preempt@3")
    stopped_at = None
    for step in range(1, 10):
        if consensus.poll(plan.preempt_now(step)):
            stopped_at = step
            break
    assert stopped_at is not None
    assert 3 <= stopped_at <= 3 + PreemptConsensus.LAG + 1

# --------------------------------------- corrupt entropy streams (r9 decode)
#
# The restart-marker excerpt decoder cuts JPEG entropy streams apart on
# RSTn boundaries — so streams that LIE about their own structure are a
# first-class fault class, not an edge case. The contract mirrors the r9
# corrupt-image rules: every malformed stream must either decode through
# the sequential path byte-identically to restart-off, or fail cleanly into
# the caller's corrupt-image fill — never crash, never produce different
# pixels with the feature on vs off.

def _native_or_skip():
    from distributed_vgg_f_tpu.data import native_jpeg as nj
    if nj.load_native_jpeg() is None:
        pytest.skip("native jpeg loader unavailable")
    if not nj.restart_supported():
        pytest.skip("restart decode compiled out (-DDVGGF_NO_RESTART)")
    return nj


def _marked_jpeg(nj, h=160, w=144, seed=0, interval=0):
    from PIL import Image
    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 256, size=(h, w, 3))
                    .astype(np.uint8)).save(buf, "JPEG", quality=90)
    data = nj.reencode_restart(buf.getvalue(), interval)
    assert data
    return data


def _decode_both_entropy_paths(nj, data, **kw):
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    before = nj.restart_kind()
    try:
        nj.set_restart(False)
        ref = nj.decode_single_image(data, 64, mean, std, **kw)
        nj.set_restart(True)
        out = nj.decode_single_image(data, 64, mean, std, **kw)
    finally:
        nj.set_restart(before == "restart")
    return ref, out


@pytest.mark.parametrize("cut", [0.35, 0.6, 0.92])
def test_truncated_restart_stream_degrades_like_sequential(cut):
    """Truncated mid-segment: the scan sees no EOI, refuses the excerpt
    path, and the outcome — partial pixels or a clean decode failure — is
    IDENTICAL to restart-off."""
    nj = _native_or_skip()
    data = _marked_jpeg(nj)
    trunc = data[:int(len(data) * cut)]
    s0 = nj.restart_stats()
    ref, out = _decode_both_entropy_paths(nj, trunc, rng_seed=2)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)
    s1 = nj.restart_stats()
    assert s1["images"] == s0["images"]          # excerpt path never engaged
    assert s1["scan_failures"] > s0["scan_failures"]


def test_bogus_rst_sequence_number_falls_back(tmp_path):
    """An out-of-sequence RSTn (stream claims RST5 where RST0 belongs):
    scan refuses, sequential path decodes (libjpeg resyncs with a warning),
    restart-on == restart-off byte-for-byte."""
    nj = _native_or_skip()
    data = bytearray(_marked_jpeg(nj))
    idx = bytes(data).find(b"\xff\xd0")
    assert idx > 0
    data[idx + 1] = 0xD5
    data = bytes(data)
    s0 = nj.restart_stats()
    ref, out = _decode_both_entropy_paths(nj, data, rng_seed=1)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)
    s1 = nj.restart_stats()
    assert s1["scan_failures"] > s0["scan_failures"]


def test_missing_rst_marker_count_mismatch_falls_back():
    """Deleting one RSTn (segment count no longer matches the declared
    geometry): scan refuses; both paths agree on the outcome."""
    nj = _native_or_skip()
    data = _marked_jpeg(nj, seed=3)
    idx = data.find(b"\xff\xd1")
    assert idx > 0
    broken = data[:idx] + data[idx + 2:]
    s0 = nj.restart_stats()
    ref, out = _decode_both_entropy_paths(nj, broken, rng_seed=4)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)
    assert nj.restart_stats()["scan_failures"] > s0["scan_failures"]


def test_garbage_segment_payload_excerpt_falls_back_to_sequential():
    """Structurally valid marker layout but corrupted entropy bytes inside
    a segment: whichever path decodes it (libjpeg error-resyncs on RST
    boundaries), restart-on must agree with restart-off exactly — the
    excerpt either reproduces the sequential pixels or retreats."""
    nj = _native_or_skip()
    data = bytearray(_marked_jpeg(nj, seed=5))
    i0 = bytes(data).find(b"\xff\xd0")
    i1 = bytes(data).find(b"\xff\xd1")
    assert 0 < i0 < i1
    mid = (i0 + 2 + i1) // 2
    for k in range(mid, min(mid + 8, i1)):
        # never synthesize a marker: leave 0xFF bytes (their removal would
        # orphan a stuffed 0x00) and bytes FOLLOWING a 0xFF (overwriting a
        # stuffing 0x00 would mint a new FFxx marker) untouched
        if data[k] != 0xFF and data[k - 1] != 0xFF:
            data[k] = 0x55
    data = bytes(data)
    ref, out = _decode_both_entropy_paths(nj, data, rng_seed=6)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)


def test_batch_loader_corrupt_marked_file_mean_fills(tmp_path):
    """End-to-end through the threaded loader on the u8 wire: a corrupt
    marker-bearing file mean-fills exactly like r9's corrupt-image
    contract — with the restart path enabled."""
    nj = _native_or_skip()
    from PIL import Image
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator
    if not nj.wire_u8_enabled():
        pytest.skip("u8 wire unavailable")
    rng = np.random.default_rng(0)
    files, labels = [], []
    for i in range(4):
        p = str(tmp_path / f"c{i}.jpg")
        with open(p, "wb") as f:
            f.write(_marked_jpeg(nj, seed=i))
        files.append(p)
        labels.append(i)
    with open(files[2], "wb") as f:
        f.write(b"\xff\xd8\xff\xdb garbage not a jpeg")
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    before = nj.restart_kind()
    try:
        nj.set_restart(True)
        it = NativeJpegTrainIterator(files, labels, 4, 32, seed=0,
                                     mean=mean, std=std,
                                     image_dtype="uint8", num_threads=2)
        batch = next(it)
        n_err = it.decode_errors()
        it.close()
    finally:
        nj.set_restart(before == "restart")
    assert n_err >= 1
    fill = np.clip(np.round(mean), 0, 255).astype(np.uint8)
    labs = [int(x) for x in batch["label"]]
    img = np.asarray(batch["image"][labs.index(2)])
    assert np.array_equal(img, np.broadcast_to(fill, img.shape))


# --------------------------------------- disaggregated-ingest chaos (r16)
def _service_fleet(data_cfg, n, *, seed, num_classes):
    """n in-process decode workers replaying the EXACT stream the
    trainer's local builder would produce (data/ingest_service.py)."""
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.ingest_service import (
        IngestWorker, SequentialReplayProducer)
    svc_off = dataclasses.replace(
        data_cfg, service=dataclasses.replace(data_cfg.service,
                                              enabled=False))

    def factory():
        return build_dataset(svc_off, "train", seed=seed,
                             num_classes=num_classes)

    return [IngestWorker(SequentialReplayProducer(factory), worker_index=i,
                         num_workers=n,
                         receipt={"seed": seed, "shard_index": 0,
                                  "num_shards": 1})
            for i in range(n)]


def _service_cfg(base, workers, **svc_kw):
    return dataclasses.replace(base, data=dataclasses.replace(
        base.data, service=dataclasses.replace(
            base.data.service, enabled=True,
            workers=tuple(w.endpoint for w in workers), **svc_kw)))


def test_worker_kill_mid_epoch_reassigns_and_run_completes(devices8):
    """worker@N through a REAL training run: the injector asks a live
    decode worker to shut down via the production op, the client discovers
    the death and reassigns its shard to the survivor, and the run
    finishes every step — a worker death is a logged failover, not a
    crash."""
    from distributed_vgg_f_tpu import telemetry
    cfg = _cfg(steps=6, fault_injection="worker@2")
    workers = _service_fleet(cfg.data, 2, seed=cfg.train.seed,
                             num_classes=10)
    cfg = _service_cfg(cfg, workers)
    reg = telemetry.get_registry()
    kills0 = reg.counter_value("fault/worker_kill", 0)
    fails0 = reg.counter_value("ingest_service/failovers", 0)
    try:
        tr = Trainer(cfg, logger=_quiet())
        state = tr.fit()
        assert int(jax.device_get(state.step)) == 6
        assert reg.counter_value("fault/worker_kill", 0) == kills0 + 1
        assert reg.counter_value("ingest_service/failovers", 0) > fails0
    finally:
        for w in workers:
            w.close()


def test_all_workers_dead_without_fallback_is_data_stall(devices8,
                                                         tmp_path):
    """Every decode worker gone and no local fallback: the run aborts with
    the TYPED stall, and the flight recorder's black box classifies it
    `data_stall` — never `unhandled_exception` (the triage contract: a
    starved trainer is a data problem with a name)."""
    cfg = _cfg(steps=8, fault_injection="worker@2")
    cfg = dataclasses.replace(cfg, telemetry=dataclasses.replace(
        cfg.telemetry, flight_dir=str(tmp_path / "flight")))
    workers = _service_fleet(cfg.data, 1, seed=cfg.train.seed,
                             num_classes=10)
    cfg = _service_cfg(cfg, workers, fallback_local=False)
    try:
        tr = Trainer(cfg, logger=_quiet())
        with pytest.raises(DataStallError, match="decode workers"):
            tr.fit()
    finally:
        for w in workers:
            w.close()
    import glob as _glob
    import json
    boxes = _glob.glob(str(tmp_path / "flight" / "flight_p*.json"))
    assert boxes, "no flight black box dumped"
    with open(boxes[0]) as f:
        record = json.load(f)
    assert record["reason"] == "data_stall"


def test_all_workers_dead_with_fallback_degrades_to_local(devices8,
                                                          caplog):
    """The same total-fleet loss WITH the fallback: the run degrades to
    local ingest at the exact stream position and completes — service
    loss costs throughput, never the run."""
    import logging as _logging
    cfg = _cfg(steps=6, fault_injection="worker@2")
    workers = _service_fleet(cfg.data, 1, seed=cfg.train.seed,
                             num_classes=10)
    cfg = _service_cfg(cfg, workers)
    try:
        tr = Trainer(cfg, logger=_quiet())
        with caplog.at_level(_logging.WARNING,
                             "distributed_vgg_f_tpu.data.service_client"):
            state = tr.fit()
        assert int(jax.device_get(state.step)) == 6
        assert any("falling back to LOCAL ingest" in r.message
                   for r in caplog.records)
    finally:
        for w in workers:
            w.close()


# ----------------------- mid-epoch SIGKILL + position-exact resume (r18)
#
# The chaos half of data/iterator_state.py: a REAL un-catchable death
# (the production `sigkill@N` injector) mid-epoch, restart against the
# same checkpoint directory, and the resumed run must be
# loss-trajectory-EQUAL to an uninterrupted one with ZERO replayed
# batches — across the {local, snapshot-cache-warm, service} × u8-wire
# grid. The in-process stop/resume equalities (tests/test_iterator_state)
# cover the local cold cell in the default loop; the subprocess SIGKILL
# grid rides the slow marker like the other kill-restart drills.

def test_sigkill_fault_token_parses():
    p = FaultPlan.parse("sigkill@7")
    assert p.sigkill_step == 7 and p.has_data_faults
    for bad in ("sigkill@0", "sigkill@3+", "sigkill@3:5",
                "sigkill@2,sigkill@5"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


@pytest.fixture(scope="module")
def resume_jpeg_dir(tmp_path_factory):
    native = pytest.importorskip("distributed_vgg_f_tpu.data.native_jpeg")
    if native.load_native_jpeg() is None:
        pytest.skip("native jpeg loader unavailable")
    from PIL import Image
    root = tmp_path_factory.mktemp("resume_imagenet")
    rs = np.random.RandomState(3)
    for cls in ("n01", "n02", "n03", "n04"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i in range(10):   # 40 items, batch 8 -> 5 batches/epoch
            Image.fromarray((rs.rand(72, 80, 3) * 255).astype(np.uint8)) \
                .save(str(d / f"{i}.jpg"), "JPEG", quality=90)
    return str(root)


def _resume_cfg(data_dir, ckpt_dir, steps, *, snapshot_dir=""):
    from distributed_vgg_f_tpu.config import SnapshotCacheConfig
    return ExperimentConfig(
        name="resume_chaos_inproc",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(
            name="imagenet", data_dir=data_dir, image_size=32,
            global_batch_size=8, num_train_examples=40, wire="u8",
            snapshot_cache=SnapshotCacheConfig(
                enabled=bool(snapshot_dir), dir=snapshot_dir)),
        train=TrainConfig(steps=steps, seed=0, log_every=1,
                          checkpoint_dir=ckpt_dir,
                          checkpoint_every_steps=3,
                          track_best_eval=False),
    )


def _fit_collect(cfg):
    records = []
    logger = _quiet()
    orig = logger.log

    def log(event, metrics):
        records.append({"event": event, **dict(metrics)})
        return orig(event, metrics)

    logger.log = log
    state = Trainer(cfg, logger=logger).fit()
    import hashlib
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    losses = {r["step"]: r["loss"] for r in records
              if r["event"] == "train" and "loss" in r}
    return records, losses, h.hexdigest()


def test_mid_epoch_stop_resume_snapshot_warm_trajectory_equal(
        resume_jpeg_dir, tmp_path, devices8):
    """Default-loop grid cell (snapshot-cache-warm × u8): interrupt at
    step 7 (epoch 1, the store warm since step 5), resume through the
    blob dispatch, and the 8..12 trajectory + final params are EQUAL to
    an uninterrupted run with its own (identically-built) store."""
    ck_i, ck_u = str(tmp_path / "i"), str(tmp_path / "u")
    s_i, s_u = str(tmp_path / "snap_i"), str(tmp_path / "snap_u")

    _fit_collect(_resume_cfg(resume_jpeg_dir, ck_i, 7, snapshot_dir=s_i))
    recs, losses_r, fp_r = _fit_collect(
        _resume_cfg(resume_jpeg_dir, ck_i, 12, snapshot_dir=s_i))
    restore = [r for r in recs if r["event"] == "iterator_state_restore"]
    assert restore and restore[0]["cursor"] == 7
    assert restore[0]["replayed_batches"] == 0

    _, losses_u, fp_u = _fit_collect(
        _resume_cfg(resume_jpeg_dir, ck_u, 12, snapshot_dir=s_u))
    for step in range(8, 13):
        assert losses_r[step] == losses_u[step], step
    assert fp_r == fp_u, \
        "warm-cache resumed run diverged from uninterrupted"


RESUME_CHILD = os.path.join(os.path.dirname(__file__), "resume_child.py")


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["local", "warm", "service"])
def test_mid_epoch_sigkill_resume_trajectory_equal(resume_jpeg_dir,
                                                   tmp_path, mode):
    """The full drill, per grid cell: the production sigkill@8 injector
    kills the child mid-epoch-1 (last checkpoint: step 6, mid-epoch), the
    restarted child resumes through the blob dispatch with zero replayed
    batches, and its trajectory + final params equal an uninterrupted
    run's."""
    import signal
    import subprocess
    import sys as _sys
    steps = 30
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    ck_i, ck_u = str(tmp_path / "i"), str(tmp_path / "u")
    res_i, res_u = str(tmp_path / "i.json"), str(tmp_path / "u.json")
    snap_i = str(tmp_path / "snap_i") if mode == "warm" else ""
    snap_u = str(tmp_path / "snap_u") if mode == "warm" else ""

    def run(ckpt, result, fault, snap):
        return subprocess.run(
            [_sys.executable, RESUME_CHILD, ckpt, result, str(steps),
             resume_jpeg_dir, mode, fault, snap],
            env=env, capture_output=True, timeout=900)

    # run 1: dies mid-epoch by SIGKILL (un-catchable — rc is -9). The
    # kill lands 20+ steps past the early cadence saves so at least one
    # MID-RUN checkpoint is durable despite the async writer (durability
    # of the very last save is deliberately racy — that is the crash
    # window the integrity-fallback restore exists for).
    out1 = run(ck_i, res_i, "sigkill@28", snap_i)
    assert out1.returncode == -signal.SIGKILL, \
        out1.stdout.decode(errors="replace")[-2000:]
    assert not os.path.exists(res_i)

    # run 2: same dirs, no fault — must resume via the blob and finish
    out2 = run(ck_i, res_i, "", snap_i)
    assert out2.returncode == 0, \
        out2.stdout.decode(errors="replace")[-3000:] \
        + out2.stderr.decode(errors="replace")[-2000:]
    with open(res_i) as f:
        resumed = json.load(f)
    assert resumed["start_step"] >= 6  # a durable mid-run checkpoint
    assert resumed["iterator_state_restored"] is True
    assert resumed["replayed_batches"] == 0
    assert resumed["final_step"] == steps

    # run 3: uninterrupted control, fresh dirs
    out3 = run(ck_u, res_u, "", snap_u)
    assert out3.returncode == 0, \
        out3.stdout.decode(errors="replace")[-3000:]
    with open(res_u) as f:
        control = json.load(f)
    assert resumed["fingerprint"] == control["fingerprint"], \
        f"{mode}: killed+resumed run diverged from uninterrupted"
    for step in range(resumed["start_step"] + 1, steps + 1):
        assert resumed["losses"][str(step)] \
            == control["losses"][str(step)], step
