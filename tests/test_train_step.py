"""End-to-end train-step tests on the virtual 8-device mesh (SURVEY.md §4):
mesh construction, pmean gradient sync, loss decrease, DP-vs-single-device
gradient equivalence."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _tiny_cfg(batch=16, dropout=0.5, num_data=0):
    return ExperimentConfig(
        name="tiny",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=dropout,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=batch,
                          weight_decay=1e-4, decay_epochs=(1000.0,)),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=batch,
                        num_train_examples=batch * 4),
        mesh=MeshConfig(num_data=num_data),
        train=TrainConfig(steps=5, log_every=100, seed=0),
    )


def _quiet():
    import io
    return MetricLogger(stream=io.StringIO())


def test_mesh_uses_all_8_devices(devices8):
    mesh = build_mesh(MeshSpec(("data",), (0,)))
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("data",)


@pytest.mark.slow
def test_loss_decreases_on_fixed_batch(devices8):
    cfg = _tiny_cfg(batch=16, dropout=0.0)
    cfg = dataclasses.replace(cfg, optim=dataclasses.replace(cfg.optim,
                                                             base_lr=0.1))
    tr = Trainer(cfg, logger=_quiet())
    state = tr.init_state()
    rng = tr.base_rng()
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10, seed=0,
                          fixed=True)
    batch = tr.shard(next(ds))
    losses = []
    for _ in range(12):
        state, metrics = tr.train_step(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert losses[-1] < losses[0] * 0.9, losses


def test_dp_matches_single_device(devices8):
    """Gradients pmean'd over 8 shards of a batch == gradients on the full batch
    on 1 device — the defining property of synchronous DP (SURVEY.md §4)."""
    batch_np = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                                seed=3, fixed=True)._fixed_batch

    results = {}
    for label, num in (("dp8", 0), ("single", 1)):
        cfg = _tiny_cfg(batch=16, dropout=0.0, num_data=num)
        devices = None if num == 0 else jax.devices()[:1]
        mesh = build_mesh(MeshSpec(("data",), (num,)), devices=devices)
        tr = Trainer(cfg, mesh=mesh, logger=_quiet())
        state = tr.init_state()
        rng = tr.base_rng()
        batch = tr.shard(batch_np)
        for _ in range(3):
            state, metrics = tr.train_step(state, batch, rng)
        results[label] = (jax.device_get(state.params),
                          float(jax.device_get(metrics["loss"])))

    p8, loss8 = results["dp8"]
    p1, loss1 = results["single"]
    assert abs(loss8 - loss1) < 1e-4, (loss8, loss1)
    flat8 = jax.tree_util.tree_leaves(p8)
    flat1 = jax.tree_util.tree_leaves(p1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_bf16_reduce_tracks_fp32_reduce(devices8):
    """mesh.reduce_dtype='bfloat16' halves gradient wire bytes (the scaling
    model's fp32 worst case is VGG-16's 553 MB all-reduce); the update must
    track the fp32-reduce update to bf16 rounding — and ONLY the gradient
    sync may differ: momentum/params stay fp32, metrics are exact."""
    batch_np = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                                seed=5, fixed=True)._fixed_batch
    results = {}
    for dtype in ("float32", "bfloat16"):
        cfg = _tiny_cfg(batch=16, dropout=0.0)
        cfg = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, reduce_dtype=dtype))
        tr = Trainer(cfg, logger=_quiet())
        state = tr.init_state()
        rng = tr.base_rng()
        batch = tr.shard(batch_np)
        for _ in range(3):
            state, metrics = tr.train_step(state, batch, rng)
        results[dtype] = (jax.device_get(state.params),
                          float(jax.device_get(metrics["loss"])))
    p32, loss32 = results["float32"]
    pbf, lossbf = results["bfloat16"]
    # metrics come from the fp32 forward, independent of the wire dtype of
    # the same-step gradient sync; 3 steps of bf16-perturbed updates shift
    # the step-3 loss by at most rounding-noise scale
    assert abs(loss32 - lossbf) < 1e-2, (loss32, lossbf)
    total = diff = 0.0
    for a, b in zip(jax.tree_util.tree_leaves(p32),
                    jax.tree_util.tree_leaves(pbf)):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        total += float(np.sum(a * a))
        diff += float(np.sum((a - b) ** 2))
        # per-leaf: the update difference is O(lr · bf16_eps · |grad|) per
        # step — far below the weights themselves
        np.testing.assert_allclose(a, b, rtol=0, atol=5e-4)
    # params must ACTUALLY differ (the bf16 cast really happened) yet stay
    # tiny relative to the weights
    assert 0 < diff < 1e-6 * total, (diff, total)


@pytest.mark.slow
def test_bf16_reduce_zero1_composition(devices8):
    """bf16 wire under ZeRO-1: ONLY the gradient reduce-scatter narrows.
    Checked against the replicated bf16-reduce run on the same data: the
    two layouts' updates may differ only by reduction-order rounding of the
    same bf16-cast gradients — a bf16 param all-gather (the regression this
    test guards) would show up as a ~1e-2-relative param divergence and as
    non-fp32 leaves (code-review r4: 'loss decreases' guarded nothing)."""
    batch_np = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                                seed=6, fixed=True)._fixed_batch
    results = {}
    for label, zero1 in (("replicated", False), ("zero1", True)):
        cfg = _tiny_cfg(batch=16, dropout=0.0)
        cfg = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, shard_opt_state=zero1,
                                          reduce_dtype="bfloat16"))
        tr = Trainer(cfg, logger=_quiet())
        state = tr.init_state()
        batch = tr.shard(batch_np)
        for _ in range(3):
            state, metrics = tr.train_step(state, batch, tr.base_rng())
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
        results[label] = jax.device_get(state.params)
    for a, b in zip(jax.tree_util.tree_leaves(results["replicated"]),
                    jax.tree_util.tree_leaves(results["zero1"])):
        assert np.asarray(b).dtype == np.float32     # fp32 gather preserved
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=5e-5)


def test_reduce_dtype_validated():
    import pytest

    with pytest.raises(ValueError, match="reduce_dtype"):
        MeshConfig(reduce_dtype="float16")


def test_dropout_differs_across_replicas(devices8):
    """Per-replica RNG folding (SURVEY.md §7): identical inputs on every replica
    must produce *different* dropout masks per replica."""
    from jax.sharding import Mesh
    from distributed_vgg_f_tpu.parallel.compat import shard_map

    from distributed_vgg_f_tpu.parallel.collectives import fold_rng_per_replica

    mesh = build_mesh(MeshSpec(("data",), (0,)))

    def per_replica_mask(key):
        key = fold_rng_per_replica(key, "data")
        return jax.random.bernoulli(key, 0.5, (1, 16)).astype(jnp.float32)

    f = shard_map(per_replica_mask, mesh=mesh, in_specs=P(),
                  out_specs=P("data"), check_vma=False)
    masks = np.asarray(jax.jit(f)(jax.random.key(0)))
    assert masks.shape == (8, 16)
    # at least two replicas must differ
    assert len({m.tobytes() for m in masks}) > 1


def test_eval_step_counts(devices8):
    cfg = _tiny_cfg(batch=16, dropout=0.0)
    tr = Trainer(cfg, logger=_quiet())
    state = tr.init_state()
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10, seed=1,
                          fixed=True)
    counts = jax.device_get(tr.eval_step(state, tr.shard(next(ds))))
    assert int(counts["count"]) == 16
    assert 0 <= int(counts["top1"]) <= int(counts["top5"]) <= 16


def test_trainer_fit_runs(devices8):
    cfg = _tiny_cfg(batch=16, dropout=0.5)
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, steps=3,
                                                             log_every=1))
    tr = Trainer(cfg, logger=_quiet())
    state = tr.fit()
    assert int(jax.device_get(state.step)) == 3


@pytest.mark.slow
def test_grad_accum_matches_big_batch(devices8):
    """k micro-batches through the scan must produce EXACTLY the big-batch
    update for a BN-free model with dropout off: same data, same params →
    mean of micro-gradients == big-batch gradient (CE is a per-example mean;
    fp32 summation noise only)."""
    cfg = _tiny_cfg(batch=64, dropout=0.0)
    tr_big = Trainer(cfg, logger=_quiet())
    cfg_acc = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, grad_accum_steps=4))
    tr_acc = Trainer(cfg_acc, logger=_quiet())

    state_b = tr_big.init_state()
    state_a = tr_acc.init_state()
    ds = SyntheticDataset(batch_size=64, image_size=32, num_classes=10,
                          seed=0, fixed=True)
    batch = tr_big.shard(next(ds))
    rng = tr_big.base_rng()
    state_b, m_b = tr_big.train_step(state_b, batch, rng)
    state_a, m_a = tr_acc.train_step(state_a, tr_acc.shard(next(ds)), rng)

    np.testing.assert_allclose(float(jax.device_get(m_a["loss"])),
                               float(jax.device_get(m_b["loss"])), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(state_a.params)),
                    jax.tree.leaves(jax.device_get(state_b.params))):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-7)


@pytest.mark.slow
def test_grad_accum_zero1_composition(devices8):
    """Accumulation happens BEFORE the ZeRO-1 reduce-scatter, so the two
    features compose: accumulated ZeRO-1 == accumulated replicated DP."""
    cfg = _tiny_cfg(batch=16, dropout=0.0, num_data=8)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, grad_accum_steps=2))
    cfg_z = dataclasses.replace(
        cfg, mesh=MeshConfig(num_data=8, shard_opt_state=True))
    tr = Trainer(cfg, logger=_quiet())
    tr_z = Trainer(cfg_z, logger=_quiet())
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                          seed=1, fixed=True)
    batch = next(ds)
    s, _ = tr.train_step(tr.init_state(), tr.shard(batch), tr.base_rng())
    sz, _ = tr_z.train_step(tr_z.init_state(), tr_z.shard(batch),
                            tr_z.base_rng())
    for a, b in zip(jax.tree.leaves(jax.device_get(s.params)),
                    jax.tree.leaves(jax.device_get(sz.params))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_grad_accum_shard_matches_unsharded_accum(devices8):
    """ZeRO-2-flavored accumulation (train.grad_accum_shard): reduce-
    scattering each micro-gradient and accumulating only the 1/N shard
    must produce the same update as accumulate-then-scatter (scatter is a
    sum over replicas — the two orderings differ only in fp summation
    order) AND as plain accumulated replicated DP."""
    cfg = _tiny_cfg(batch=16, dropout=0.0, num_data=8)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, grad_accum_steps=2))
    cfg_z = dataclasses.replace(
        cfg, mesh=MeshConfig(num_data=8, shard_opt_state=True))
    cfg_z2 = dataclasses.replace(
        cfg_z, train=dataclasses.replace(cfg_z.train,
                                         grad_accum_shard=True))
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                          seed=1, fixed=True)
    batch = next(ds)
    states = []
    for c in (cfg, cfg_z, cfg_z2):
        tr = Trainer(c, logger=_quiet())
        s, m = tr.train_step(tr.init_state(), tr.shard(batch),
                             tr.base_rng())
        states.append((s, m))
    for (s_ref, m_ref), (s, m) in zip(states[:-1], states[1:]):
        for a, b in zip(jax.tree.leaves(jax.device_get(s_ref.params)),
                        jax.tree.leaves(jax.device_get(s.params))):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            float(m_ref["grad_norm"]), float(m["grad_norm"]), rtol=1e-5)


@pytest.mark.slow
def test_grad_accum_shard_bf16_wire(devices8):
    """The sharded accumulator composes with mesh.reduce_dtype=bfloat16:
    k wire roundings instead of one must still track the fp32-wire update
    to bf16-rounding tolerance."""
    cfg = _tiny_cfg(batch=16, dropout=0.0, num_data=8)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, grad_accum_steps=2,
                                       grad_accum_shard=True))
    cfg_f32 = dataclasses.replace(
        cfg, mesh=MeshConfig(num_data=8, shard_opt_state=True))
    cfg_bf16 = dataclasses.replace(
        cfg, mesh=MeshConfig(num_data=8, shard_opt_state=True,
                             reduce_dtype="bfloat16"))
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10,
                          seed=2, fixed=True)
    batch = next(ds)
    outs = []
    for c in (cfg_f32, cfg_bf16):
        tr = Trainer(c, logger=_quiet())
        s, _ = tr.train_step(tr.init_state(), tr.shard(batch),
                             tr.base_rng())
        outs.append(s)
    for a, b in zip(jax.tree.leaves(jax.device_get(outs[0].params)),
                    jax.tree.leaves(jax.device_get(outs[1].params))):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_grad_accum_shard_validation(devices8):
    """grad_accum_shard without ZeRO-1 or without accumulation is a config
    error (loud, not a silent fallback) — except the documented 1-device
    downgrade, which follows shard_opt_state's own."""
    import pytest
    base = _tiny_cfg(batch=16, dropout=0.0, num_data=8)
    no_zero = dataclasses.replace(
        base, train=dataclasses.replace(base.train, grad_accum_steps=2,
                                        grad_accum_shard=True))
    with pytest.raises(ValueError, match="shard_opt_state"):
        Trainer(no_zero, logger=_quiet())
    no_accum = dataclasses.replace(
        base, train=dataclasses.replace(base.train, grad_accum_shard=True),
        mesh=MeshConfig(num_data=8, shard_opt_state=True))
    with pytest.raises(ValueError, match="grad_accum_steps"):
        Trainer(no_accum, logger=_quiet())


def test_grad_accum_rejects_indivisible_batch(devices8):
    cfg = _tiny_cfg(batch=16)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, grad_accum_steps=3))
    tr = Trainer(cfg, logger=_quiet())
    ds = SyntheticDataset(batch_size=16, image_size=32, num_classes=10, seed=0)
    import pytest
    with pytest.raises(Exception, match="not divisible|divisible"):
        tr.train_step(tr.init_state(), tr.shard(next(ds)), tr.base_rng())


@pytest.mark.slow
def test_grad_accum_updates_bn_stats(devices8):
    """BN models: batch stats update sequentially per micro-batch through the
    scan carry (the standard accumulation semantics) and training proceeds."""
    import io
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = ExperimentConfig(
        name="accum_bn",
        model=ModelConfig(name="resnet50", num_classes=10,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=64, global_batch_size=16),
        train=TrainConfig(steps=1, seed=0, grad_accum_steps=2),
    )
    tr = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = tr.init_state()
    old_stats = jax.device_get(state.batch_stats)
    ds = SyntheticDataset(batch_size=16, image_size=64, num_classes=10, seed=0)
    state, metrics = tr.train_step(state, tr.shard(next(ds)), tr.base_rng())
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    new_stats = jax.device_get(state.batch_stats)
    assert any(not np.allclose(a, b) for a, b in
               zip(jax.tree_util.tree_leaves(old_stats),
                   jax.tree_util.tree_leaves(new_stats)))


def test_fit_rejects_labels_beyond_model_head(devices8):
    """First-batch guard for EVERY pipeline (code-review r3): labels >= the
    head width are a CE gather past the logits — loss=nan with finite grads
    and no error. The trainer must fail loudly instead."""
    import pytest

    cfg = _tiny_cfg(batch=16, dropout=0.0)
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, steps=2))
    tr = Trainer(cfg, logger=_quiet())

    def bad_batches():
        rng = np.random.default_rng(0)
        while True:
            yield {"image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
                   "label": np.full((16,), 937, np.int32)}   # >= num_classes=10

    with pytest.raises(ValueError, match="num_classes"):
        tr.fit(dataset=bad_batches())
