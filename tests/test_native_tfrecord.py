"""Native TFRecord path (native/tfrecord_index.cc + the ranged loader in
native/jpeg_loader.cc): index correctness against tf-written shards, framing
corruption detection, index caching, ranged train determinism, and the exact
finite native eval pass."""

import dataclasses
import os

import numpy as np
import pytest

pytest.importorskip("tensorflow")

from distributed_vgg_f_tpu.data.native_jpeg import (  # noqa: E402
    NativeJpegEvalIterator,
    NativeJpegTrainIterator,
    load_native_jpeg,
)
from distributed_vgg_f_tpu.data.native_tfrecord import (  # noqa: E402
    index_tfrecord,
    index_tfrecords,
    load_native_tfrecord,
)

if load_native_tfrecord() is None or load_native_jpeg() is None:
    pytest.skip("native libraries unavailable", allow_module_level=True)

MEAN = np.array([123.68, 116.78, 103.94], np.float32)
STD = np.array([58.393, 57.12, 57.375], np.float32)


def _write_tfrecords(root, num_files=3, per_file=8, hw=(96, 128), seed=0,
                     prefix="train"):
    """Classic ImageNet-style shards: image/encoded JPEG + 1-based int64
    label. Returns (paths, per-record jpeg arrays, labels)."""
    import tensorflow as tf
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    paths, images, labels = [], [], []
    for i in range(num_files):
        p = os.path.join(root, f"{prefix}-{i:05d}-of-{num_files:05d}")
        with tf.io.TFRecordWriter(p) as w:
            for _ in range(per_file):
                img = rng.integers(0, 256, size=(*hw, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img, quality=90).numpy()
                label = int(rng.integers(1, 11))
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[label])),
                }))
                w.write(ex.SerializeToString())
                images.append(jpeg)
                labels.append(label)
        paths.append(p)
    return paths, images, labels


@pytest.fixture(scope="module")
def tfrecord_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tfrecords"))
    return root, *_write_tfrecords(root)


def test_index_matches_written_records(tfrecord_dir):
    _, paths, jpegs, labels = tfrecord_dir
    seen = 0
    for p in paths:
        offs, lens, labs = index_tfrecord(p)
        with open(p, "rb") as f:
            blob = f.read()
        for o, l, lab in zip(offs, lens, labs):
            # the indexed byte range IS the exact encoded JPEG we wrote
            assert blob[o:o + l] == jpegs[seen]
            assert lab == labels[seen]
            seen += 1
    assert seen == len(jpegs)


def test_index_verify_payload_crc_ok(tfrecord_dir):
    _, paths, _, _ = tfrecord_dir
    offs, _, _ = index_tfrecord(paths[0], verify_payload_crc=True)
    assert len(offs) > 0


def test_index_detects_framing_corruption(tmp_path, tfrecord_dir):
    _, paths, _, _ = tfrecord_dir
    with open(paths[0], "rb") as f:
        blob = bytearray(f.read())
    blob[3] ^= 0xFF  # flip a bit inside the first record's length field
    bad = tmp_path / "corrupt-00000-of-00001"
    bad.write_bytes(bytes(blob))
    with pytest.raises(ValueError, match="crc|truncated"):
        index_tfrecord(str(bad))


def test_index_cache_roundtrip(tfrecord_dir, tmp_path):
    _, paths, _, _ = tfrecord_dir
    cache = str(tmp_path / "cache")
    first = index_tfrecords(paths, cache_dir=cache)
    cached_files = os.listdir(cache)
    assert len(cached_files) == 1
    second = index_tfrecords(paths, cache_dir=cache)
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)


def test_ranged_train_iterator_deterministic(tfrecord_dir):
    _, paths, _, _ = tfrecord_dir
    path_idx, offs, lens, labs64 = index_tfrecords(paths)
    labels = (labs64 - 1).astype(np.int32)

    def make(threads):
        return NativeJpegTrainIterator(
            paths, labels, 6, 48, seed=3, mean=MEAN, std=STD,
            num_threads=threads, ranges=(path_idx, offs, lens))

    a, b = make(1), make(4)
    for _ in range(6):  # crosses the 24-item epoch boundary
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
    assert a.decode_errors() == 0
    a.close()
    b.close()


def test_ranged_seek_resume(tfrecord_dir):
    _, paths, _, _ = tfrecord_dir
    path_idx, offs, lens, labs64 = index_tfrecords(paths)
    labels = (labs64 - 1).astype(np.int32)
    kw = dict(seed=9, mean=MEAN, std=STD, ranges=(path_idx, offs, lens))
    ref = NativeJpegTrainIterator(paths, labels, 5, 40, **kw)
    batches = [next(ref) for _ in range(7)]
    res = NativeJpegTrainIterator(paths, labels, 5, 40, **kw)
    assert res.restore_state(4)
    for i in range(4, 7):
        got = next(res)
        np.testing.assert_array_equal(got["image"], batches[i]["image"])
        np.testing.assert_array_equal(got["label"], batches[i]["label"])
    ref.close()
    res.close()


def test_native_eval_exact_finite_pass(tfrecord_dir):
    _, paths, _, labels_written = tfrecord_dir
    path_idx, offs, lens, labs64 = index_tfrecords(paths)
    labels = (labs64 - 1).astype(np.int32)
    n = len(labels)  # 24
    batch = 7       # 24 = 3*7 + 3 -> final batch has 3 valid rows
    it = NativeJpegEvalIterator(paths, labels, batch, 48, mean=MEAN, std=STD,
                                ranges=(path_idx, offs, lens))
    assert it.is_finite and it.num_examples == n
    for _ in range(2):  # re-iterable: two identical passes
        got_labels, got_valid = [], 0
        batches = list(it)
        assert len(batches) == (n + batch - 1) // batch
        for bt in batches:
            assert bt["image"].shape == (batch, 48, 48, 3)
            got_valid += int(bt["valid"].sum())
            got_labels.extend(bt["label"][bt["valid"]].tolist())
            # padding rows are zeroed
            assert (np.asarray(bt["image"], np.float32)[~bt["valid"]]
                    == 0).all()
        assert got_valid == n
        # in-order identity pass: labels come back exactly as written
        assert got_labels == [l - 1 for l in labels_written]
    pad = it.padding_batch()
    assert not pad["valid"].any() and pad["image"].shape == (batch, 48, 48, 3)


def test_native_eval_interleaved_passes_independent(tfrecord_dir):
    """Each iter() owns a private native handle: two interleaved passes must
    yield identical independent streams, and abandoning one mid-pass must not
    disturb the other."""
    _, paths, _, _ = tfrecord_dir
    path_idx, offs, lens, labs64 = index_tfrecords(paths)
    labels = (labs64 - 1).astype(np.int32)
    ds = NativeJpegEvalIterator(paths, labels, 5, 32, mean=MEAN, std=STD,
                                ranges=(path_idx, offs, lens))
    it1, it2 = iter(ds), iter(ds)
    a1, a2 = next(it1), next(it2)
    np.testing.assert_array_equal(a1["image"], a2["image"])
    del it1  # abandon pass 1 mid-stream; its cleanup must not touch pass 2
    rest = [next(it2)["label"] for _ in range(2)]
    full = [b["label"] for b in ds]  # a fresh third pass, run to completion
    np.testing.assert_array_equal(rest[0], full[1])
    np.testing.assert_array_equal(rest[1], full[2])


def test_build_imagenet_uses_native_tfrecord(tfrecord_dir):
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset

    root, _, _, labels_written = tfrecord_dir
    cfg = DataConfig(name="imagenet", data_dir=root, image_size=32,
                     global_batch_size=6, shuffle_buffer=8)
    ds = build_dataset(cfg, "train", seed=0)
    assert isinstance(ds, NativeJpegTrainIterator)
    b = next(ds)
    assert b["image"].shape == (6, 32, 32, 3)
    assert set(b["label"].tolist()) <= set(l - 1 for l in labels_written)
    ds.close()

    # native off -> tf.data path still serves the same layout
    ds_tf = build_dataset(dataclasses.replace(cfg, native_jpeg=False),
                          "train", seed=0)
    assert not isinstance(ds_tf, NativeJpegTrainIterator)
    b = next(ds_tf)
    assert b["image"].shape == (6, 32, 32, 3)


def test_build_imagenet_native_eval_toggle(tmp_path):
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset

    root = str(tmp_path)
    _write_tfrecords(root, num_files=2, per_file=5, prefix="validation",
                     seed=4)
    cfg = DataConfig(name="imagenet", data_dir=root, image_size=32,
                     global_batch_size=4, native_jpeg_eval=True)
    ds = build_dataset(cfg, "validation", seed=0)
    assert isinstance(ds, NativeJpegEvalIterator)
    total = sum(int(b["valid"].sum()) for b in ds)
    assert total == 10
    # default: eval stays on the tf.data exact-eval path
    ds_tf = build_dataset(dataclasses.replace(cfg, native_jpeg_eval=False),
                          "validation", seed=0)
    assert not isinstance(ds_tf, NativeJpegEvalIterator)
