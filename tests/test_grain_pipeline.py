"""Grain (PyGrain) input backend (data/grain_imagenet.py, data.backend =
"grain"): native single-image decode, deterministic streams, snapshot-file
resume, exact finite eval, both layouts."""

import dataclasses
import os

import numpy as np
import pytest

pytest.importorskip("grain")
pytest.importorskip("tensorflow")

from distributed_vgg_f_tpu.config import DataConfig  # noqa: E402
from distributed_vgg_f_tpu.data import build_dataset  # noqa: E402
from distributed_vgg_f_tpu.data.grain_imagenet import (  # noqa: E402
    GrainTrainIterator,
)
from distributed_vgg_f_tpu.data.native_jpeg import load_native_jpeg  # noqa: E402

if load_native_jpeg() is None:
    pytest.skip("native jpeg decoder unavailable", allow_module_level=True)


def _write_tfrecords(root, n=18, hw=(72, 88), seed=0):
    import tensorflow as tf
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    labels = []
    for split, count in (("train", n), ("validation", 10)):
        path = os.path.join(root, f"{split}-00000-of-00001")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(count):
                img = rng.integers(0, 256, size=(*hw, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img, quality=90).numpy()
                label = int(rng.integers(1, 11))
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[label])),
                }))
                w.write(ex.SerializeToString())
                if split == "validation":
                    labels.append(label)
    return labels


@pytest.fixture(scope="module")
def grain_data_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("grain_imagenet"))
    val_labels = _write_tfrecords(root)
    return root, val_labels


def _cfg(root, **kw):
    kw.setdefault("backend", "grain")
    return DataConfig(name="imagenet", data_dir=root, image_size=32,
                      global_batch_size=4, **kw)


def test_grain_train_stream(grain_data_dir):
    root, _ = grain_data_dir
    ds = build_dataset(_cfg(root), "train", seed=0)
    assert isinstance(ds, GrainTrainIterator)
    for _ in range(6):  # crosses the 18-record epoch boundary
        b = next(ds)
        assert b["image"].shape == (4, 32, 32, 3)
        assert b["image"].dtype == np.float32
        assert set(b["label"].tolist()) <= set(range(10))
        assert float(np.abs(b["image"]).mean()) > 0.1  # actually decoded


def test_grain_deterministic_per_seed(grain_data_dir):
    root, _ = grain_data_dir
    a = build_dataset(_cfg(root), "train", seed=7)
    b = build_dataset(_cfg(root), "train", seed=7)
    c = build_dataset(_cfg(root), "train", seed=8)
    diff = False
    for _ in range(4):
        ba, bb, bc = next(a), next(b), next(c)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
        diff = diff or not np.array_equal(ba["image"], bc["image"])
    assert diff  # different seed, different stream


def test_grain_snapshot_resume(grain_data_dir, tmp_path):
    root, _ = grain_data_dir
    state_dir = str(tmp_path / "grain_state")
    make = lambda: build_dataset(_cfg(root), "train", seed=1,
                                 state_dir=state_dir, snapshot_every=2)
    ds = make()
    assert ds.supports_state
    batches = [next(ds) for _ in range(8)]
    assert os.path.exists(os.path.join(state_dir, f"grain_{4:012d}.state"))
    resumed = make()
    assert resumed.restore_state(4)
    for i in range(4, 8):
        b = next(resumed)
        np.testing.assert_array_equal(b["image"], batches[i]["image"])
        np.testing.assert_array_equal(b["label"], batches[i]["label"])
    assert make().restore_state(3) is False  # no snapshot at 3


def test_grain_eval_exact(grain_data_dir):
    root, val_labels = grain_data_dir
    ds = build_dataset(_cfg(root), "validation", seed=0)
    assert ds.is_finite
    got = []
    total = 0
    batches = list(ds)
    assert len(batches) == 3  # 10 examples in batches of 4: 4+4+2
    for b in batches:
        assert b["image"].shape == (4, 32, 32, 3)
        total += int(b["valid"].sum())
        got.extend(b["label"][b["valid"]].tolist())
    assert total == 10
    # sequential pass: labels come back exactly as written (0-based)
    assert got == [l - 1 for l in val_labels]


def test_grain_space_to_depth(grain_data_dir):
    root, _ = grain_data_dir
    raw = next(build_dataset(_cfg(root), "train", seed=3))
    packed = next(build_dataset(_cfg(root, space_to_depth=True), "train",
                                seed=3))
    assert packed["image"].shape == (4, 8, 8, 48)
    b, h, w, c = raw["image"].shape
    manual = raw["image"].reshape(b, h // 4, 4, w // 4, 4, c) \
        .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 4, w // 4, 48)
    np.testing.assert_array_equal(packed["image"], manual)


def test_grain_imagefolder_layout(tmp_path):
    import tensorflow as tf
    rng = np.random.default_rng(2)
    for cls in ("n01", "n02"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(4):
            img = rng.integers(0, 256, size=(48, 56, 3)).astype(np.uint8)
            with open(d / f"{cls}_{i}.JPEG", "wb") as f:
                f.write(tf.io.encode_jpeg(img).numpy())
    ds = build_dataset(_cfg(str(tmp_path)), "train", seed=0)
    assert isinstance(ds, GrainTrainIterator)
    b = next(ds)
    assert b["image"].shape == (4, 32, 32, 3)
    assert set(b["label"].tolist()) <= {0, 1}


def test_grain_decode_errors_surface(tmp_path):
    import tensorflow as tf
    path = tmp_path / "train-00000-of-00001"
    with tf.io.TFRecordWriter(str(path)) as w:
        for _ in range(4):
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(
                        value=[b"\xff\xd8\xffnot a jpeg"])),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[1])),
            }))
            w.write(ex.SerializeToString())
    ds = build_dataset(_cfg(str(tmp_path)), "train", seed=0)
    assert isinstance(ds, GrainTrainIterator)
    b = next(ds)
    # zero-filled, and the counter the trainer polls reflects it
    assert (np.asarray(b["image"], np.float32) == 0).all()
    assert "failed" not in b
    assert ds.decode_errors() == 4


@pytest.mark.slow
@pytest.mark.parametrize("workers", [1, 2])
def test_grain_worker_processes_match_in_process(grain_data_dir, workers):
    """Real spawned decode worker processes (1 and >1 — oversubscribed on
    this 1-vCPU host, but the multiprocess path is exercised; VERDICT r2 #6)
    must reproduce the in-process stream. workers=1: bit-identical batches.
    workers=2: grain batches per worker and interleaves round-robin, so
    batch PACKING differs — but over any aligned window of N×batch records
    the decoded (image, label) multiset is identical, because both the
    shuffled order and each record's decode rng are pure functions of
    (seed, global stream index), not of which process decodes."""
    root, _ = grain_data_dir
    a = build_dataset(_cfg(root), "train", seed=5)
    b = build_dataset(_cfg(root, grain_workers=workers), "train", seed=5)

    def window(ds, n=4):
        recs = []
        for _ in range(n):
            batch = next(ds)
            for img, lab in zip(np.asarray(batch["image"], np.float32),
                                np.asarray(batch["label"])):
                recs.append((int(lab), img.tobytes()))
        return sorted(recs)

    if workers == 1:
        for _ in range(3):
            ba, bb = next(a), next(b)
            np.testing.assert_array_equal(ba["image"], bb["image"])
            np.testing.assert_array_equal(ba["label"], bb["label"])
    else:
        assert window(a) == window(b)
    a.close()
    b.close()


def test_range_source_truncated_file_raises_io_error(tmp_path):
    """ADVICE r2: a file that shrank after indexing must surface as an IO
    error — not as truncated JPEG bytes silently zero-filled into a 'corrupt
    image'. Also covers the short-read pread loop."""
    from distributed_vgg_f_tpu.data.grain_imagenet import JpegRangeSource

    path = str(tmp_path / "blob.bin")
    with open(path, "wb") as f:
        f.write(b"A" * 100)
    src = JpegRangeSource([path], path_idx=[0, 0], offsets=[10, 80],
                          lengths=[20, 40], labels=[1, 2])
    # in-bounds range reads exactly
    assert src[0]["jpeg"] == b"A" * 20
    # range extends past EOF (file truncated since indexing) -> IOError
    with pytest.raises(IOError, match="short read"):
        src[1]
