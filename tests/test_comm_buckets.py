"""Overlapped bucketed gradient exchange + ZeRO-2 (ISSUE 11,
parallel/buckets.py): bucket-geometry invariants, the kill-switch
lowered-text identity (comm_bucket_mb unset ≡ the pre-r14 step), the
committed lowered-HLO overlap assertions, the CPU loss-trajectory EQUALITY
grid across {dp, zero1, zero2} x {bucketed on/off} x {grad_accum 1,2} x
two bucket sizes, the clip-after-cast x reduce_dtype pin (ISSUE 11
bugfix satellite), checkpoint layout migration, comm telemetry, and the
scaling-model memory claims."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
    get_config,
)
from distributed_vgg_f_tpu.parallel.buckets import (
    build_bucket_layout,
    hlo_overlap_report,
    layout_from_receipt,
)
from distributed_vgg_f_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    shard_host_batch,
)
from distributed_vgg_f_tpu.parallel.zero import (
    flat_param_count,
    padded_flat_size,
    train_state_specs,
)
from distributed_vgg_f_tpu.train.state import TrainState
from distributed_vgg_f_tpu.train.step import build_train_step


def _mesh8(devices8):
    return build_mesh(MeshSpec(("data",), (8,)), devices=devices8)


class _MiniNet:
    """Tiny flax model with a conv + two dense layers: enough leaves for a
    multi-bucket partition, cheap enough for the full equality grid."""

    def __new__(cls):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, *, train=False, rngs=None):
                x = nn.Conv(8, (3, 3), strides=(2, 2),
                            dtype=jnp.float32)(x)
                x = nn.relu(x)
                x = x.reshape((x.shape[0], -1))
                x = nn.Dense(32, dtype=jnp.float32)(x)
                x = nn.relu(x)
                return nn.Dense(10, dtype=jnp.float32)(x)

        return Net()


def _mini_params():
    import optax
    model = _MiniNet()
    state = TrainState.create(model, optax.sgd(0.1), jax.random.key(0),
                              jnp.zeros((1, 16, 16, 3), jnp.float32))
    return model, state.params


# ------------------------------------------------------------------- config
def test_mesh_config_validation():
    with pytest.raises(ValueError, match="comm_bucket_mb"):
        MeshConfig(comm_bucket_mb=-1.0)
    assert MeshConfig().sharding_label == "dp"
    assert MeshConfig(shard_opt_state=True).sharding_label == "zero1"
    assert MeshConfig(shard_opt_state=True,
                      shard_gradients=True).sharding_label == "zero2"
    # shard_gradients without the ZeRO-1 frame DOWNGRADES (the trainer's
    # single-device precedent) so the README's documented
    # `--set mesh.shard_opt_state=false` toggle stays valid on the
    # flagship, which ships ZeRO-2
    assert MeshConfig(shard_gradients=True).sharding_label == "dp"


def test_flagship_ships_zero2_bucketed():
    """The flagship preset carries the r14 exchange: ZeRO-2 gradient
    sharding over the ZeRO-1 frame plus 4 MB buckets — and the derived zoo
    presets inherit it."""
    flag = get_config("vggf_imagenet_dp")
    assert flag.mesh.shard_opt_state is True
    assert flag.mesh.shard_gradients is True
    assert flag.mesh.comm_bucket_mb == 4.0
    assert flag.mesh.sharding_label == "zero2"
    for name in ("vgg16_imagenet", "resnet50_imagenet", "vit_s16_imagenet"):
        assert get_config(name).mesh.sharding_label == "zero2"


def test_step_rejects_zero2_without_zero1():
    import optax
    model = _MiniNet()
    mesh = build_mesh(MeshSpec(("data",), (0,)))
    with pytest.raises(ValueError, match="shard_gradients"):
        build_train_step(model, optax.sgd(0.1), mesh, weight_decay=0.0,
                         shard_gradients=True)


# ----------------------------------------------------------- layout geometry
def test_bucket_layout_partition_invariants():
    _, params = _mini_params()
    leaves = jax.tree.leaves(params)
    layout = build_bucket_layout(params, 8, 1024)
    # every canonical leaf appears in exactly one bucket
    seen = [i for b in layout.buckets for i in b]
    assert sorted(seen) == list(range(len(leaves)))
    # reverse-backward emission: bucket 0 starts at the LAST leaf
    assert layout.buckets[0][0] == len(leaves) - 1
    flat = [i for b in layout.buckets for i in b]
    assert flat == list(reversed(range(len(leaves))))
    # per-bucket padding is a multiple of the shard count and geometry sums
    for n, p, s in zip(layout.bucket_sizes(), layout.padded_sizes(),
                       layout.shard_sizes()):
        assert p % 8 == 0 and p - n < 8 and s == p // 8
    assert layout.total_padded == sum(layout.padded_sizes())
    assert layout.shard_size * 8 == layout.total_padded
    # leaves are atomic: a leaf above the target gets its own bucket, so
    # bucket count never exceeds leaf count
    assert 2 <= layout.num_buckets <= len(leaves)
    # kill-switch: 0 target -> no layout
    assert build_bucket_layout(params, 8, 0) is None


def test_bucket_layout_global_roundtrip():
    """to_global/from_global are exact inverses — the checkpoint layout
    permutation loses nothing, and the local shard IS row r of the global
    (N, S) view (the property the per-bucket psum_scatter relies on)."""
    _, params = _mini_params()
    for target in (512, 4096):
        layout = build_bucket_layout(params, 8, target)
        vec = layout.to_global(params)
        assert vec.shape == (layout.total_padded,)
        back = layout.from_global(vec)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mat = np.asarray(vec).reshape(8, layout.shard_size)
        # row r == concat of per-bucket pieces r
        off = 0
        leaves = jax.tree.leaves(params)
        for b, s_b in enumerate(layout.shard_sizes()):
            parts = [np.ravel(np.asarray(leaves[i]))
                     for i in layout.buckets[b]]
            bvec = np.concatenate(parts)
            bvec = np.pad(bvec, (0, layout.padded_sizes()[b] - bvec.size))
            for r in range(8):
                np.testing.assert_array_equal(
                    mat[r, off:off + s_b], bvec[r * s_b:(r + 1) * s_b])
            off += s_b


def test_layout_receipt_roundtrip_and_mismatch():
    _, params = _mini_params()
    layout = build_bucket_layout(params, 8, 1024)
    rebuilt = layout_from_receipt(params, layout.describe())
    assert rebuilt.describe() == layout.describe()
    bad = dict(layout.describe(), total_padded=layout.total_padded + 8)
    with pytest.raises(ValueError, match="does not reproduce"):
        layout_from_receipt(params, bad)
    # same TOTAL, different partition (two layers trading widths): the
    # receipt's per-bucket sizes must catch what the total cannot
    elems = list(layout.describe()["bucket_elems"])
    swapped = dict(layout.describe(),
                   bucket_elems=[elems[1], elems[0]] + elems[2:])
    with pytest.raises(ValueError, match="does not reproduce"):
        layout_from_receipt(params, swapped)
    with pytest.raises(ValueError, match="kind"):
        layout_from_receipt(params, {"kind": "nope"})


# -------------------------------------------------- step builders for grids
def _build(mesh, model, *, zero=False, zero2=False, bucket_mb=0.0,
           accum=1, reduce_dtype="float32", clip=0.0, sample_hw=16):
    import optax
    tx = optax.sgd(0.05, momentum=0.9)
    sample = jnp.zeros((1, sample_hw, sample_hw, 3), jnp.float32)
    specs = None
    state = None
    if zero:
        layout = None
        shapes = jax.eval_shape(
            lambda r: TrainState.create(model, tx, r, sample,
                                        zero1_shards=8),
            jax.random.key(0))
        if bucket_mb > 0:
            layout = build_bucket_layout(shapes.params, 8,
                                         int(bucket_mb * 1024 * 1024))
            padded = layout.total_padded
        else:
            padded = padded_flat_size(flat_param_count(shapes.params), 8)

        def create(r):
            return TrainState.create(model, tx, r, sample, zero1_shards=8,
                                     bucket_layout=layout)

        specs = train_state_specs(jax.eval_shape(create, jax.random.key(0)),
                                  padded, "data")
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        state = jax.jit(create, out_shardings=shardings)(jax.random.key(0))
    else:
        state = TrainState.create(model, tx, jax.random.key(0), sample)
    step = build_train_step(model, tx, mesh, weight_decay=1e-4, zero1=zero,
                            state_specs=specs, grad_accum_steps=accum,
                            shard_gradients=zero2, comm_bucket_mb=bucket_mb,
                            reduce_dtype=reduce_dtype, grad_clip_norm=clip)
    return state, step


def _run(mesh, model, batches, base, n=3, **kw):
    state, step = _build(mesh, model, **kw)
    losses, norms = [], []
    for b in batches[:n]:
        state, m = step(state, b, base)
        losses.append(float(jax.device_get(m["loss"])))
        norms.append(float(jax.device_get(m["grad_norm"])))
    return losses, norms, state, step


def _batches(n=3, hw=16, classes=10, batch=16, mesh=None, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        b = {"image": rng.standard_normal(
                (batch, hw, hw, 3)).astype(np.float32),
             "label": rng.integers(0, classes, (batch,)).astype(np.int32)}
        out.append(shard_host_batch(b, mesh))
    return out


# ----------------------------------------------- loss-trajectory EQUALITY
def test_equality_grid_mininet(devices8):
    """The acceptance grid at MiniNet scale (the vggf/vit_s16 runs ride
    the slow marker below): {dp, zero1, zero2} x {bucketed on/off} x two
    bucket sizes produce BITWISE-equal CPU loss trajectories at
    grad_accum=1 — bucketing permutes flat layouts, never elementwise
    math — and the accum=2 compositions agree to fp-summation tolerance."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh)
    base = jax.jit(lambda: jax.random.key(1))()

    ref, ref_norms, _, _ = _run(mesh, model, batches, base)
    small, big = 0.0005, 0.004  # MB — two bucket geometries
    grid = {
        "dp_bucket_small": dict(bucket_mb=small),
        "dp_bucket_big": dict(bucket_mb=big),
        "zero1": dict(zero=True),
        "zero1_bucket_small": dict(zero=True, bucket_mb=small),
        "zero2_bucket_small": dict(zero=True, zero2=True, bucket_mb=small),
        "zero2_bucket_big": dict(zero=True, zero2=True, bucket_mb=big),
    }
    for name, kw in grid.items():
        losses, norms, _, step = _run(mesh, model, batches, base, **kw)
        assert losses == ref, f"{name} diverged: {losses} != {ref}"
        # the grad norm is computed from the sharded form under ZeRO
        # (psum of shard partials) and per-leaf sums under DP — fp
        # reduction ORDER differs across layouts, so the pin is a tight
        # tolerance, not bitwise (the bitwise contract covers the LOSS
        # trajectory, where no cross-element reduction reorders)
        np.testing.assert_allclose(norms, ref_norms, rtol=1e-5)
    # grad accumulation: sharded accumulator (zero2) == full-tree
    # accumulator == replicated accumulation, at fp tolerance (the scan
    # reorders gradient summation)
    acc_ref, _, _, _ = _run(mesh, model, batches, base, accum=2)
    for kw in (dict(zero=True, accum=2),
               dict(zero=True, zero2=True, accum=2),
               dict(zero=True, zero2=True, accum=2, bucket_mb=small),
               dict(zero=True, zero2=True, accum=2, bucket_mb=big)):
        losses, _, _, _ = _run(mesh, model, batches, base, **kw)
        np.testing.assert_allclose(losses, acc_ref, rtol=2e-5)


def test_zero2_accum_carry_is_sharded(devices8):
    """ZeRO-2's memory claim at the jaxpr level: with shard_gradients on,
    the scan carry is the (shard_size,) vector — O(params/N) — without
    needing the explicit grad_accum_shard flag."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    state, step = _build(mesh, model, zero=True, zero2=True, accum=2,
                         bucket_mb=0.0005)
    meta = None
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    state, m = step(state, batches[0], base)
    meta = step.comm_meta
    assert meta["sharding"] == "zero2"
    assert meta["grad_accum_steps"] == 2
    # k micro-scatters move k x the scatter-leg bytes (the explicit
    # memory-for-bandwidth trade documented in the step); the fp32 wire
    # makes scatter == gather per leg, so accum=2 doubles exactly
    assert meta["scatter_bytes"] == 2 * meta["gather_bytes"]
    assert meta["wire_bytes"] == meta["scatter_bytes"] \
        + meta["gather_bytes"]


# ------------------------------------------------------ kill-switch identity
def test_kill_switch_lowered_text_identity(devices8):
    """comm_bucket_mb unset lowers to EXACTLY the pre-r14 step — for both
    the DP and ZeRO paths (the ISSUE 11 kill-switch contract); the
    bucketed build must differ (it had better be doing something)."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    for zero in (False, True):
        state, legacy = _build(mesh, model, zero=zero)
        _, off = _build(mesh, model, zero=zero, bucket_mb=0.0)
        _, on = _build(mesh, model, zero=zero, bucket_mb=0.0005)
        text_legacy = legacy.lower(state, batches[0], base).as_text()
        text_off = off.lower(state, batches[0], base).as_text()
        text_on = on.lower(state, batches[0], base).as_text() if not zero \
            else None  # bucketed ZeRO needs the bucketed state layout
        assert text_off == text_legacy, \
            f"kill-switch not byte-identical (zero={zero})"
        if text_on is not None:
            assert text_on != text_legacy


# ------------------------------------------------- lowered-HLO assertions
def test_hlo_monolithic_zero_is_serial_tail(devices8):
    """The committed negative: the unbucketed ZeRO exchange is ONE flat
    reduce-scatter whose ancestors include the entire backward — no
    overlap license exists."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    state, step = _build(mesh, model, zero=True)
    rep = hlo_overlap_report(step.lower(state, batches[0], base).as_text())
    assert rep["collective_counts"].get("reduce_scatter", 0) == 1
    assert rep["serial_tail_collectives"] >= 1
    # every gradient collective (scatter AND param gather) depends on the
    # full backward: nothing can overlap
    assert rep["overlap_capable"] is False


def test_hlo_bucketed_zero_overlap_evidence(devices8):
    """ISSUE 11 acceptance: >= 2 collectives interleaved with backward
    compute when bucketing is on — one reduce-scatter PER BUCKET, and a
    committed dependency witness that some gradient collective and some
    backward matmul/conv have no path between them (the structural
    license for XLA's latency-hiding scheduler)."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    state, step = _build(mesh, model, zero=True, zero2=True,
                         bucket_mb=0.0005)
    rep = hlo_overlap_report(step.lower(state, batches[0], base).as_text())
    assert step.comm_meta["buckets"] >= 2
    assert rep["collective_counts"]["reduce_scatter"] \
        == step.comm_meta["buckets"]
    assert rep["grad_collectives"] >= 2
    assert rep["overlap_capable"] is True, \
        "no (collective, compute) pair is schedulable concurrently"
    assert rep["witness"] is not None


def test_hlo_bucketed_dp_groups_leaf_collectives(devices8):
    """Plain DP already emits one pmean per LEAF (overlap-capable but
    message-size-hostile at scale); bucketing must GROUP them — fewer
    gradient all-reduces than leaves, count == buckets, overlap
    preserved."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    state, mono = _build(mesh, model)
    _, bucketed = _build(mesh, model, bucket_mb=0.004)
    text_mono = mono.lower(state, batches[0], base).as_text()
    text_b = bucketed.lower(state, batches[0], base).as_text()
    n_leaves = len(jax.tree.leaves(state.params))
    rep_mono = hlo_overlap_report(text_mono)
    rep_b = hlo_overlap_report(text_b)
    assert rep_mono["collective_counts"]["all_reduce"] >= n_leaves
    assert rep_b["collective_counts"]["all_reduce"] \
        < rep_mono["collective_counts"]["all_reduce"]
    assert bucketed.comm_meta["buckets"] < n_leaves
    assert rep_b["overlap_capable"] is True


# ------------------------------------- clip-after-cast x reduce_dtype pin
def test_clip_after_cast_vs_fp32_within_wire_tolerance(devices8):
    """ISSUE 11 bugfix satellite: under ZeRO with mesh.reduce_dtype set,
    the scatter leg casts BEFORE the pad/clip interplay. Pin the
    semantics: (a) the padding region is inert through the cast (bf16(0)
    == 0 — the momentum tail stays exactly zero), (b) clip-after-cast
    (the implemented order: cast -> scatter -> fp32 norm -> clip) agrees
    with the fp32-wire clip within bf16 wire tolerance (~2^-8 relative),
    and (c) the DP and ZeRO paths implement the SAME ordering (they share
    collectives.cast_to_wire), so their clipped trajectories agree at the
    wire's own tolerance."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh)
    base = jax.jit(lambda: jax.random.key(1))()
    kw = dict(clip=0.05)
    f32_z, f32_zn, _, _ = _run(mesh, model, batches, base, zero=True, **kw)
    bf16_z, bf16_zn, state_z, _ = _run(mesh, model, batches, base,
                                       zero=True, reduce_dtype="bfloat16",
                                       **kw)
    bf16_d, bf16_dn, _, _ = _run(mesh, model, batches, base,
                                 reduce_dtype="bfloat16", **kw)
    bf16_zb, _, _, _ = _run(mesh, model, batches, base, zero=True,
                            zero2=True, bucket_mb=0.0005,
                            reduce_dtype="bfloat16", **kw)
    # (b) wire-dtype tolerance: bf16 keeps 8 mantissa bits -> ~0.4%
    # per-element rounding; 3 steps of momentum compound it, 2% covers it
    np.testing.assert_allclose(bf16_zn, f32_zn, rtol=2e-2)
    np.testing.assert_allclose(bf16_z, f32_z, rtol=2e-2)
    # (c) same ordering on both paths: dp-bf16 == zero-bf16 (+ bucketed)
    # to the wire's own tolerance (layouts permute the fp32 math only)
    np.testing.assert_allclose(bf16_z, bf16_d, rtol=1e-5)
    np.testing.assert_allclose(bf16_zn, bf16_dn, rtol=1e-4)
    np.testing.assert_allclose(bf16_zb, bf16_z, rtol=1e-5)
    # (a) the padded momentum tail is exactly zero after bf16+clip steps
    n_elem = flat_param_count(state_z.params)
    padded = padded_flat_size(n_elem, 8)
    for leaf in jax.tree.leaves(state_z.opt_state):
        if getattr(leaf, "ndim", 0) == 1 and leaf.shape[0] == padded \
                and padded > n_elem:
            tail = np.asarray(jax.device_get(leaf))[n_elem:]
            np.testing.assert_array_equal(tail, np.zeros_like(tail))


# ------------------------------------------------- opt-state layout moves
def test_convert_opt_state_bucketed_roundtrip(devices8):
    """canonical flat <-> bucketed flat through convert_opt_state is exact
    both ways (the checkpoint migration primitive retopology drives)."""
    import optax

    from distributed_vgg_f_tpu.parallel.zero import convert_opt_state
    model, params = _mini_params()
    tx = optax.sgd(0.05, momentum=0.9)
    n = flat_param_count(params)
    padded = padded_flat_size(n, 8)
    layout = build_bucket_layout(params, 8, 1024)
    # a canonical flat state with a recognizable momentum pattern
    rng = np.random.default_rng(3)
    canon_vec = jnp.asarray(
        np.concatenate([rng.standard_normal(n).astype(np.float32),
                        np.zeros(padded - n, np.float32)]))
    canon = jax.eval_shape(tx.init,
                           jax.ShapeDtypeStruct((padded,), jnp.float32))
    canon = jax.tree.map(
        lambda l: (canon_vec if l.ndim == 1 and l.shape[0] == padded
                   else jnp.zeros(l.shape, l.dtype)), canon)
    bucketed = convert_opt_state(canon, tx, params,
                                 layout.total_padded,
                                 target_bucket_layout=layout)
    back = convert_opt_state(bucketed, tx, params, padded,
                             src_bucket_layout=layout)
    for a, b in zip(jax.tree.leaves(canon), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # mismatched geometry must fail loudly
    with pytest.raises(ValueError, match="total_padded"):
        convert_opt_state(canon, tx, params, layout.total_padded + 8,
                          target_bucket_layout=layout)


# --------------------------------------------------------------- telemetry
def test_comm_block_schema():
    from distributed_vgg_f_tpu.telemetry import schema
    good = {"sharding": "zero2", "bucketed": True, "buckets": 14,
            "bucket_mb": 4.0, "reduce_dtype": "float32",
            "grad_accum_steps": 1, "wire_bytes": 123, "scatter_bytes": 61,
            "gather_bytes": 62, "allreduce_bytes": 0}
    errors = []
    schema.validate_comm_block(good, "t", errors)
    assert errors == []
    # r21: zero3 is a legal basis and may carry the gather count
    errors = []
    schema.validate_comm_block(dict(good, sharding="zero3", gathers=14),
                               "t", errors)
    assert errors == []
    for bad, match in (
            (dict(good, sharding="zero4"), "sharding"),
            (dict(good, sharding="zero3", gathers=-1), "gathers"),
            (dict(good, buckets=0), "buckets"),
            (dict(good, bucket_mb=-1), "bucket_mb"),
            ({k: v for k, v in good.items() if k != "wire_bytes"},
             "wire_bytes"),
            (dict(good, bucketed="yes"), "bucketed")):
        errors = []
        schema.validate_comm_block(bad, "t", errors)
        assert errors and match in errors[0]
    # wired into train records
    rec = {"event": "train", "step": 1, "comm": dict(good, sharding="bad")}
    assert any("sharding" in e
               for e in schema.validate_metrics_record(rec))


def test_comm_counters_and_window_block(devices8):
    """The step wrapper increments comm/exchanges + comm/wire_bytes and
    sets the exchange-shape gauges (the README counter-table rows the
    drift guard cross-checks), single-sourced from the traced geometry."""
    from distributed_vgg_f_tpu import telemetry
    telemetry.configure(enabled=True)
    try:
        mesh = _mesh8(devices8)
        model = _MiniNet()
        batches = _batches(mesh=mesh, n=2)
        base = jax.jit(lambda: jax.random.key(1))()
        state, step = _build(mesh, model, zero=True, zero2=True,
                             bucket_mb=0.0005)
        reg = telemetry.get_registry()
        reg.delta("comm_test")
        for b in batches:
            state, _ = step(state, b, base)
        delta = reg.delta("comm_test")
        assert delta.get("comm/exchanges") == 2
        assert delta.get("comm/wire_bytes") \
            == 2 * step.comm_meta["wire_bytes"]
        snap = reg.snapshot()
        assert snap.get("comm/buckets_per_step") \
            == step.comm_meta["buckets"]
        assert snap.get("comm/bucket_mb") == step.comm_meta["bucket_mb"]
        # the JSONL block the trainer logs validates against the schema
        from distributed_vgg_f_tpu.telemetry import schema
        errors = []
        schema.validate_comm_block(dict(step.comm_meta), "t", errors)
        assert errors == []
    finally:
        telemetry.reset()


# ------------------------------------------------------- regression sentinel
def test_sentinel_basis_grows_sharding_with_pre_r14_default():
    from distributed_vgg_f_tpu.telemetry.regress import Basis, row_basis
    b = Basis("u8", True, "noise", (320, 256), True)
    assert b.sharding == "dp"                       # pre-r14 default
    assert b.describe()["sharding"] == "dp"
    row = {"mode": "comm_overlap_bench", "wire": "u8",
           "sharding": "zero2_bucketed"}
    assert row_basis(row).sharding == "zero2_bucketed"
    # r21: the zero3 bases land on their own keys
    assert row_basis(dict(row, sharding="zero3_bucketed")).sharding \
        == "zero3_bucketed"
    assert row_basis(dict(row, sharding="zero3")).sharding == "zero3"
    # absent field keeps old receipts on their existing key
    assert row_basis({"wire": "u8"}).sharding == "dp"


# ------------------------------------------------------------ scaling model
def test_scaling_model_zero2_memory_and_wire():
    from distributed_vgg_f_tpu.utils.scaling_model import (
        approx_num_buckets,
        bucketed_exposed_comm_s,
        exchange_bytes_per_chip,
        gradient_state_bytes_per_chip,
    )
    P_, N = 60_000_000, 64
    # wire: zero2 moves exactly zero1's bytes; both beat nothing (the win
    # is memory), dp's all-reduce is the same total at fp32
    z1 = exchange_bytes_per_chip(4 * P_, N, sharding="zero1")
    z2 = exchange_bytes_per_chip(4 * P_, N, sharding="zero2")
    dp = exchange_bytes_per_chip(4 * P_, N, sharding="dp")
    assert z1 == z2 == dp
    # r21: zero3 moves the same bytes at the fp32 wire (the re-sync
    # gather becomes the just-in-time gather); its gather leg may narrow
    # with the wire dtype, expressed via param_bytes
    z3 = exchange_bytes_per_chip(4 * P_, N, sharding="zero3")
    assert z3 == z2
    z3_bf16 = exchange_bytes_per_chip(2 * P_, N, sharding="zero3",
                                      param_bytes=2 * P_)
    assert z3_bf16 == z3 / 2
    with pytest.raises(ValueError):
        exchange_bytes_per_chip(4 * P_, N, sharding="zero4")
    # memory: the ZeRO-2 claim — accumulator and opt state O(params/N)
    g_dp = gradient_state_bytes_per_chip(P_, N, sharding="dp",
                                         grad_accum_steps=2)
    g_z1 = gradient_state_bytes_per_chip(P_, N, sharding="zero1",
                                         grad_accum_steps=2)
    g_z2 = gradient_state_bytes_per_chip(P_, N, sharding="zero2",
                                         grad_accum_steps=2,
                                         bucket_bytes=4 << 20)
    assert g_dp["opt_state_bytes"] == 4 * P_
    assert g_z1["opt_state_bytes"] == g_z2["opt_state_bytes"] \
        == 4 * P_ / N
    assert g_dp["grad_accumulator_bytes"] \
        == g_z1["grad_accumulator_bytes"] == 4 * P_
    assert g_z2["grad_accumulator_bytes"] == 4 * P_ / N
    # r21: zero3 keeps zero2's gradient state exactly; its own win is
    # param state — O(params) everywhere else, O(params/N) under zero3
    from distributed_vgg_f_tpu.utils.scaling_model import param_bytes_per_chip
    g_z3 = gradient_state_bytes_per_chip(P_, N, sharding="zero3",
                                         grad_accum_steps=2,
                                         bucket_bytes=4 << 20)
    assert g_z3 == g_z2
    assert param_bytes_per_chip(P_, N, sharding="dp") \
        == param_bytes_per_chip(P_, N, sharding="zero2") == 4 * P_
    assert param_bytes_per_chip(P_, N, sharding="zero3") == 4 * P_ / N
    assert param_bytes_per_chip(P_, N, sharding="zero3", ema=True) \
        == 8 * P_ / N
    with pytest.raises(ValueError):
        param_bytes_per_chip(P_, N, sharding="zero4")
    # the VGG-16 acceptance row of the README table: 528 MB -> 4.1 MB
    vgg16_p = 138_357_544
    assert round(param_bytes_per_chip(vgg16_p, 128, sharding="zero3")
                 / (1 << 20), 1) == 4.1
    # the bucketed exchange buffer is O(bucket), the monolithic O(params)
    assert g_z2["exchange_buffer_bytes"] == 4 << 20
    mono = gradient_state_bytes_per_chip(P_, N, sharding="zero2")
    assert mono["exchange_buffer_bytes"] == 4 * P_
    assert mono["grad_accumulator_bytes"] == 0
    # bucketed DP builds per-bucket concat sends too; monolithic DP's
    # per-leaf pmean consumes leaves in place
    assert gradient_state_bytes_per_chip(
        P_, N, sharding="dp",
        bucket_bytes=4 << 20)["exchange_buffer_bytes"] == 4 << 20
    assert gradient_state_bytes_per_chip(
        P_, N, sharding="dp")["exchange_buffer_bytes"] == 0
    # accum=1: no carry
    # overlap: bucketing bounds the exposed tail by the last bucket; more
    # buckets -> smaller floor but linearly growing latency term
    e1 = bucketed_exposed_comm_s(0.010, 1, overlappable_s=0.0)
    e8 = bucketed_exposed_comm_s(0.010, 8, overlappable_s=0.008)
    assert e8 < e1
    assert bucketed_exposed_comm_s(0.010, 8, overlappable_s=0.008) \
        < bucketed_exposed_comm_s(0.010, 8, overlappable_s=0.0)
    with pytest.raises(ValueError):
        bucketed_exposed_comm_s(1.0, 0, overlappable_s=0.0)
    assert approx_num_buckets(P_, 0) == 1
    assert approx_num_buckets(P_, 4.0, num_leaves=10) == 10
    assert approx_num_buckets(10, 4.0) == 1


# ------------------------------------------------------- trainer-level slow
def _trainer_cfg(model="vggf", steps=3, **mesh_kw):
    return ExperimentConfig(
        name="comm_grid",
        model=ModelConfig(name=model, num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          momentum=0.9, weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        mesh=MeshConfig(num_data=8, **mesh_kw),
        train=TrainConfig(steps=steps, seed=0),
    )


def _trainer_run(cfg, n_steps=3):
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = trainer.init_state()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=cfg.data.global_batch_size,
                          image_size=32, num_classes=10, seed=0)
    losses = []
    for _ in range(n_steps):
        state, m = trainer.train_step(state, trainer.shard(next(ds)), rng)
        losses.append(float(jax.device_get(m["loss"])))
    return trainer, state, losses


@pytest.mark.slow
@pytest.mark.parametrize("model", ["vggf", "vit_s16"])
def test_equality_grid_real_models(model):
    """ISSUE 11 test-coverage satellite at real-model scale: vggf (the
    FC-heavy stress case) and vit_s16 (many small leaves) produce EQUAL
    CPU loss trajectories across the sharding x bucketing grid."""
    ref = _trainer_run(_trainer_cfg(model))[2]
    for mesh_kw in (
            dict(comm_bucket_mb=0.25),
            dict(shard_opt_state=True),
            dict(shard_opt_state=True, comm_bucket_mb=0.25),
            dict(shard_opt_state=True, shard_gradients=True,
                 comm_bucket_mb=0.25),
            dict(shard_opt_state=True, shard_gradients=True,
                 comm_bucket_mb=1.0)):
        losses = _trainer_run(_trainer_cfg(model, **mesh_kw))[2]
        assert losses == ref, f"{model} {mesh_kw}: {losses} != {ref}"


@pytest.mark.slow
def test_zero2_bucketed_checkpoint_migration(tmp_path):
    """ISSUE 11 layout-migration parity gate: a checkpoint written by the
    bucketed ZeRO-2 run restores into (a) the same layout (roundtrip), and
    (b) an UNBUCKETED zero1 run — where the momentum must land in the
    canonical frame with exactly the same per-parameter values; and (c) a
    pre-r14-style zero1 checkpoint restores into the bucketed zero2 run.
    All through the geometry receipt in the checkpoint's `extra`."""
    import dataclasses

    import jax.flatten_util

    def with_ckpt(cfg, d):
        return dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, checkpoint_dir=str(d),
                                           checkpoint_every_steps=1))

    cfg_b = with_ckpt(_trainer_cfg(shard_opt_state=True,
                                   shard_gradients=True,
                                   comm_bucket_mb=0.25),
                      tmp_path / "bucketed")
    tr_b, state_b, _ = _trainer_run(cfg_b, n_steps=2)
    tr_b.checkpoints.save(state_b, force=True,
                          extra=tr_b._opt_layout_extra())
    tr_b.checkpoints.wait()
    # (a) same-layout roundtrip
    restored = tr_b.restore_or_init()
    for a, b in zip(jax.tree.leaves(jax.device_get(state_b.opt_state)),
                    jax.tree.leaves(jax.device_get(restored.opt_state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # (b) bucketed -> canonical zero1
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    cfg_c = with_ckpt(_trainer_cfg(shard_opt_state=True),
                      tmp_path / "bucketed")
    tr_c = Trainer(cfg_c, logger=MetricLogger(stream=io.StringIO()))
    rest_c = tr_c.restore_or_init()
    mom_b = [l for l in jax.tree.leaves(jax.device_get(state_b.opt_state))
             if getattr(l, "ndim", 0) == 1 and l.size == tr_b._padded][0]
    mom_c = [l for l in jax.tree.leaves(jax.device_get(rest_c.opt_state))
             if getattr(l, "ndim", 0) == 1 and l.size == tr_c._padded][0]
    canon_from_b = jax.flatten_util.ravel_pytree(
        tr_b._bucket_layout.from_global(jnp.asarray(mom_b)))[0]
    np.testing.assert_array_equal(np.asarray(canon_from_b),
                                  np.asarray(mom_c)[:canon_from_b.size])
    # (c) canonical zero1 checkpoint -> bucketed zero2 run
    cfg_z1 = with_ckpt(_trainer_cfg(shard_opt_state=True),
                       tmp_path / "canon")
    tr_z1, state_z1, _ = _trainer_run(cfg_z1, n_steps=2)
    tr_z1.checkpoints.save(state_z1, force=True)
    tr_z1.checkpoints.wait()
    cfg_b2 = with_ckpt(_trainer_cfg(shard_opt_state=True,
                                    shard_gradients=True,
                                    comm_bucket_mb=0.25),
                       tmp_path / "canon")
    tr_b2 = Trainer(cfg_b2, logger=MetricLogger(stream=io.StringIO()))
    rest_b2 = tr_b2.restore_or_init()
    mom_z1 = [l for l in
              jax.tree.leaves(jax.device_get(state_z1.opt_state))
              if getattr(l, "ndim", 0) == 1 and l.size == tr_z1._padded][0]
    mom_b2 = [l for l in
              jax.tree.leaves(jax.device_get(rest_b2.opt_state))
              if getattr(l, "ndim", 0) == 1 and l.size == tr_b2._padded][0]
    canon_from_b2 = jax.flatten_util.ravel_pytree(
        tr_b2._bucket_layout.from_global(jnp.asarray(mom_b2)))[0]
    np.testing.assert_array_equal(
        np.asarray(canon_from_b2),
        np.asarray(mom_z1)[:canon_from_b2.size])


@pytest.mark.slow
def test_trainer_jsonl_carries_schema_valid_comm_block(tmp_path):
    """The per-window `comm` JSONL block rides every train record and
    schema-validates (the ISSUE 11 telemetry satellite, end to end)."""
    import dataclasses
    import json as _json

    from distributed_vgg_f_tpu.telemetry import schema
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    cfg = _trainer_cfg(shard_opt_state=True, shard_gradients=True,
                       comm_bucket_mb=0.25, steps=2)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, log_every=1))
    log_path = tmp_path / "train.jsonl"
    with MetricLogger(jsonl_path=str(log_path)) as logger:
        trainer = Trainer(cfg, logger=logger)
        trainer.fit()
    assert schema.validate_metrics_jsonl(str(log_path)) == []
    comm_blocks = []
    with open(log_path) as f:
        for line in f:
            rec = _json.loads(line)
            if rec.get("event") == "train" and "comm" in rec:
                comm_blocks.append(rec["comm"])
    assert comm_blocks, "no train record carried the comm block"
    assert comm_blocks[0]["sharding"] == "zero2"
    assert comm_blocks[0]["bucketed"] is True
    assert comm_blocks[0]["buckets"] >= 2
