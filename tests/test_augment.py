"""Fused on-device augmentation (r13): kill-switch structural absence,
eval-never-augments sentinel, mixup restart determinism, flip-ownership
single-sourcing (double-flip impossible across the cache-warm x augment-on
x restart-resume grid), the per-model u8 ≡ host loss-trajectory parity
gates, and the flagship preset pins (augment + ZeRO-1)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    AugmentConfig,
    DataConfig,
    get_config,
    supports_space_to_depth,
)
from distributed_vgg_f_tpu.data.augment import make_device_augment
from distributed_vgg_f_tpu.data.device_ingest import (
    make_device_finish,
    space_to_depth_batch,
)
from distributed_vgg_f_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    shard_host_batch,
)

MEAN = (123.68, 116.78, 103.94)
STD = (58.393, 57.12, 57.375)

FLAGS_ON = AugmentConfig(enabled=True, hflip=True, mixup_alpha=0.2)


def _mesh8(devices8):
    return build_mesh(MeshSpec(("data",), (8,)), devices=devices8)


class _MiniNet:
    """Tiny flax model standing in for the zoo in step-level gates."""

    def __new__(cls):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, *, train=False, rngs=None):
                x = nn.Conv(8, (3, 3), strides=(2, 2), dtype=jnp.float32)(x)
                x = nn.relu(x)
                x = x.reshape((x.shape[0], -1))
                return nn.Dense(10, dtype=jnp.float32)(x)

        return Net()


# ------------------------------------------------------------------- config
def test_augment_config_validation():
    with pytest.raises(ValueError, match="crop_jitter"):
        AugmentConfig(crop_jitter=-1)
    with pytest.raises(ValueError, match="mixup_alpha"):
        AugmentConfig(mixup_alpha=-0.1)
    with pytest.raises(ValueError, match="rand_ops"):
        AugmentConfig(rand_ops=-2)
    with pytest.raises(ValueError, match="rand_magnitude"):
        AugmentConfig(rand_magnitude=1.5)
    # ownership predicate: only enabled+hflip moves the flip to the device
    assert not AugmentConfig().owns_hflip
    assert not AugmentConfig(enabled=True, hflip=False).owns_hflip
    assert AugmentConfig(enabled=True).owns_hflip


def test_host_space_to_depth_splits_on_augment():
    """With augmentation enabled the host never packs — the step packs
    AFTER the device-side geometric augments (the ordering contract)."""
    base = DataConfig(name="imagenet", space_to_depth=True)
    assert base.host_space_to_depth is True
    aug = DataConfig(name="imagenet", space_to_depth=True,
                     augment=AugmentConfig(enabled=True))
    assert aug.host_space_to_depth is False
    # augment off is byte-identical to pre-r13: packing decision unchanged
    off = DataConfig(name="imagenet", space_to_depth=True,
                     augment=AugmentConfig(enabled=False, hflip=False))
    assert off.host_space_to_depth is True


def test_flagship_ships_augment_and_zero1():
    """Preset pins: the flagship ships flips+mixup on the u8 wire AND
    ZeRO-1 optimizer-state sharding (ROADMAP item 4 first slice); the zoo
    presets are first-class consumers of the same contract via their
    ingest descriptors — no hand-override back to the raw layout."""
    flag = get_config("vggf_imagenet_dp")
    assert flag.data.augment.enabled and flag.data.augment.hflip
    assert flag.data.augment.mixup_alpha > 0
    assert flag.data.wire == "u8" and flag.data.space_to_depth
    assert flag.mesh.shard_opt_state is True
    for name, model in (("vgg16_imagenet", "vgg16"),
                        ("resnet50_imagenet", "resnet50"),
                        ("vit_s16_imagenet", "vit_s16")):
        cfg = get_config(name)
        assert cfg.data.wire == "u8", f"{name} forfeits the u8 wire"
        assert cfg.data.space_to_depth is False
        assert cfg.data.augment.enabled, f"{name} forfeits device augment"
        assert cfg.mesh.shard_opt_state is True


def test_ingest_descriptors_single_source():
    """The descriptor table is the single source: space-to-depth
    eligibility, the schema validator's zoo list, and the DataConfig
    normalize-constant defaults must all agree with it."""
    from distributed_vgg_f_tpu.models.ingest import (
        IMAGENET_MEAN_RGB,
        IMAGENET_STDDEV_RGB,
        INGEST_DESCRIPTORS,
        ingest_descriptor,
    )
    from distributed_vgg_f_tpu.telemetry.schema import _ZOO_MODELS
    assert set(_ZOO_MODELS) == set(INGEST_DESCRIPTORS)
    assert tuple(DataConfig().mean_rgb) == IMAGENET_MEAN_RGB
    assert tuple(DataConfig().stddev_rgb) == IMAGENET_STDDEV_RGB
    assert ingest_descriptor("vggf").space_to_depth
    for name in ("vgg16", "resnet50", "vit_s16"):
        d = ingest_descriptor(name)
        assert not d.space_to_depth and d.wire == "u8"
        assert not d.accepts_uint8
    # unknown models get the conservative unpacked default
    assert not ingest_descriptor("notamodel").space_to_depth
    # supports_space_to_depth reads the descriptor, not a name literal
    assert supports_space_to_depth("vggf", 224)
    assert not supports_space_to_depth("vgg16", 224)
    assert not supports_space_to_depth("vggf", 225)


def test_zoo_models_refuse_raw_uint8():
    """Every zoo stem refuses raw wire pixels — silent 0..255 training is
    impossible for the whole zoo, not just VGG-F."""
    from distributed_vgg_f_tpu.models.resnet import ResNet50
    from distributed_vgg_f_tpu.models.vgg16 import VGG16
    from distributed_vgg_f_tpu.models.vit import ViT
    for model, size in ((VGG16(num_classes=4, compute_dtype=jnp.float32), 32),
                        (ResNet50(num_classes=4,
                                  compute_dtype=jnp.float32,
                                  bn_axis_name=None), 32),
                        (ViT.s16(num_classes=4,
                                 compute_dtype=jnp.float32), 32)):
        with pytest.raises(TypeError, match="device-finish"):
            jax.eval_shape(
                lambda m=model, s=size: m.init(
                    jax.random.key(0), jnp.zeros((1, s, s, 3), jnp.uint8)))


# ------------------------------------------------------ the stage's algebra
def test_disabled_stage_is_none():
    assert make_device_augment(AugmentConfig(), MEAN, STD) is None
    assert make_device_augment(None, MEAN, STD) is None


def test_augment_stage_shapes_and_guards():
    aug = make_device_augment(
        AugmentConfig(enabled=True, hflip=True, crop_jitter=2,
                      mixup_alpha=0.2, cutmix_alpha=0.2, rand_ops=2),
        MEAN, STD, space_to_depth=True)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 8, 8, 3)), jnp.float32)
    labels = jnp.arange(4, dtype=jnp.int32)
    out, mix_labels, lam = jax.jit(aug)(jax.random.key(0), x, labels)
    assert out.shape == (4, 2, 2, 48)  # packed AFTER augmenting
    assert out.dtype == jnp.float32
    assert mix_labels.shape == (4,)
    assert float(lam) == pytest.approx(float(lam))  # finite scalar
    # packed input refused: augmentation must run pre-pack
    with pytest.raises(ValueError, match="unpacked"):
        aug(jax.random.key(0), space_to_depth_batch(x), labels)
    # raw wire pixels refused: the finish runs first
    with pytest.raises(TypeError, match="finish"):
        aug(jax.random.key(0), jnp.zeros((4, 8, 8, 3), jnp.uint8), labels)


def test_hflip_only_stage_flips_about_half():
    aug = make_device_augment(AugmentConfig(enabled=True, hflip=True),
                              MEAN, STD)
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(64, 6, 6, 3)), jnp.float32)
    labels = jnp.zeros((64,), jnp.int32)
    out, mix_labels, lam = aug(jax.random.key(3), x, labels)
    assert mix_labels is None and lam is None
    x_np, out_np = np.asarray(x), np.asarray(out)
    same = sum(bool(np.array_equal(out_np[i], x_np[i])) for i in range(64))
    mirrored = sum(bool(np.array_equal(out_np[i], x_np[i, :, ::-1, :]))
                   for i in range(64))
    assert same + mirrored == 64, "flip must be the ONLY transform"
    assert 8 < mirrored < 56, "p=0.5 per-image draw"
    # reproducible from the key: same key, same flips
    out2, _, _ = aug(jax.random.key(3), x, labels)
    np.testing.assert_array_equal(out_np, np.asarray(out2))


def test_rand_ops_stay_in_pixel_range():
    """Photometric ops clip on the 0..255 pixel scale: de-normalizing the
    output must land inside [0, 255] whatever the draw."""
    aug = make_device_augment(
        AugmentConfig(enabled=True, hflip=False, rand_ops=3,
                      rand_magnitude=1.0), MEAN, STD)
    pixels = np.random.default_rng(2).integers(
        0, 256, size=(8, 8, 8, 3)).astype(np.uint8)
    finish = make_device_finish(MEAN, STD)
    x = finish(jnp.asarray(pixels))
    out, _, _ = aug(jax.random.key(9), x, jnp.zeros((8,), jnp.int32))
    p = np.asarray(out) * np.asarray(STD, np.float32) \
        + np.asarray(MEAN, np.float32)
    assert p.min() >= -1e-3 and p.max() <= 255.001


# ------------------------------------------- step integration + kill-switch
def _build_step(mesh, model, device_augment, **kw):
    import optax

    from distributed_vgg_f_tpu.train.step import build_train_step
    tx = optax.sgd(0.05, momentum=0.9)
    step = build_train_step(model, tx, mesh, weight_decay=1e-4,
                            device_finish=make_device_finish(MEAN, STD),
                            device_augment=device_augment, **kw)
    return tx, step


def _mini_state(model, tx):
    from distributed_vgg_f_tpu.train.state import TrainState
    return TrainState.create(model, tx, jax.random.key(0),
                             jnp.zeros((1, 16, 16, 3), jnp.float32))


def test_augment_off_step_is_structurally_absent(devices8):
    """data.augment.enabled=false ≡ structurally absent: the lowered train
    step from a disabled config is TEXT-IDENTICAL to one built without the
    stage at all — the kill-switch cannot even change instruction order."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    tx, step_off = _build_step(
        mesh, model, make_device_augment(AugmentConfig(), MEAN, STD))
    _, step_none = _build_step(mesh, model, None)
    state = _mini_state(model, tx)
    batch = shard_host_batch(
        {"image": np.zeros((16, 16, 16, 3), np.uint8),
         "label": np.zeros((16,), np.int32)}, mesh)
    rng = jax.jit(lambda: jax.random.key(1))()
    low_off = step_off.lower(state, batch, rng).as_text()
    low_none = step_none.lower(state, batch, rng).as_text()
    assert low_off == low_none


def test_eval_never_augments(devices8):
    """Sentinel: build_eval_step has no augmentation surface — the lowered
    eval computation is bit-identical between augment-on and augment-off
    trainers, and eval logits/counts are unchanged by the augment config."""
    from distributed_vgg_f_tpu.train.step import build_eval_step
    mesh = _mesh8(devices8)
    model = _MiniNet()
    import optax
    state = _mini_state(model, optax.sgd(0.1))
    finish = make_device_finish(MEAN, STD)
    eval_step = build_eval_step(model, mesh, device_finish=finish)
    batch = shard_host_batch(
        {"image": np.random.default_rng(5).integers(
            0, 256, size=(16, 16, 16, 3)).astype(np.uint8),
         "label": np.random.default_rng(6).integers(
             0, 10, size=(16,)).astype(np.int32)}, mesh)
    # the eval builder takes no augment argument at all — the structural
    # half of the sentinel
    import inspect
    assert "augment" not in inspect.signature(build_eval_step).parameters
    counts = {k: int(v) for k, v in
              jax.device_get(eval_step(state, batch)).items()}
    # trainer-level: augment-on and augment-off trainers lower the SAME
    # eval computation (proven on the lowered text, which includes every
    # op), and produce identical counts
    low = eval_step.lower(state, batch).as_text()
    eval_step2 = build_eval_step(model, mesh, device_finish=finish)
    assert eval_step2.lower(state, batch).as_text() == low
    counts2 = {k: int(v) for k, v in
               jax.device_get(eval_step2(state, batch)).items()}
    assert counts == counts2


def test_mixup_pairing_deterministic_across_restart(devices8):
    """Same (seed, step) → same permutation/lam: a run rebuilt from
    scratch (fresh step fn + fresh jit — the process-restart equivalent)
    that replays to step k continues with EXACTLY the uninterrupted run's
    losses. The augment key is fold_in(step_rng, AUGMENT_RNG_FOLD), so
    determinism rides the state's step counter, not python state."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    aug = make_device_augment(
        AugmentConfig(enabled=True, hflip=True, mixup_alpha=0.4,
                      cutmix_alpha=0.3, crop_jitter=1), MEAN, STD)
    rng0 = np.random.default_rng(7)
    batches = [
        shard_host_batch(
            {"image": rng0.integers(0, 256, (16, 16, 16, 3)).astype(np.uint8),
             "label": rng0.integers(0, 10, (16,)).astype(np.int32)}, mesh)
        for _ in range(4)]
    base = jax.jit(lambda: jax.random.key(1))()

    def run(n_steps, state=None, step=None, tx=None):
        if step is None:
            tx, step = _build_step(mesh, model, aug)
        if state is None:
            state = _mini_state(model, tx)
        losses = []
        start = int(jax.device_get(state.step))
        for b in batches[start:start + n_steps]:
            state, m = step(state, b, base)
            losses.append(float(jax.device_get(m["loss"])))
        return state, losses, tx, step

    _, cont, _, _ = run(4)  # the uninterrupted run
    # "restart": a brand-new step fn (fresh trace — the process-restart
    # equivalent) replays the first 2 steps...
    tx2, step2 = _build_step(mesh, model, aug)
    state2, first2, _, _ = run(2, tx=tx2, step=step2)
    np.testing.assert_array_equal(cont[:2], first2)
    # ...and yet ANOTHER fresh build continues from the replayed state:
    # the augment draws (mixup pairing included) depend only on
    # (seed, state.step, replica)
    tx3, step3 = _build_step(mesh, model, aug)
    _, tail, _, _ = run(2, state=state2, tx=tx3, step=step3)
    np.testing.assert_array_equal(cont, first2 + tail)


def test_augment_composes_with_zero1_and_accum(devices8):
    """The flagship composition (ZeRO-1 + fused augment) matches plain
    replicated DP step-for-step, and grad accumulation slices the mixup
    label pairing correctly (BN-free model: summed micro-grads equal the
    big-batch gradient exactly)."""
    from jax.sharding import PartitionSpec as P
    mesh = _mesh8(devices8)
    model = _MiniNet()
    aug = make_device_augment(FLAGS_ON, MEAN, STD)
    rng0 = np.random.default_rng(11)
    batches = [
        shard_host_batch(
            {"image": rng0.integers(0, 256, (16, 16, 16, 3)).astype(np.uint8),
             "label": rng0.integers(0, 10, (16,)).astype(np.int32)}, mesh)
        for _ in range(3)]
    base = jax.jit(lambda: jax.random.key(1))()

    def run(zero1=False, accum=1):
        import optax

        from distributed_vgg_f_tpu.parallel.zero import (
            flat_param_count, padded_flat_size, train_state_specs)
        from distributed_vgg_f_tpu.train.state import TrainState
        from distributed_vgg_f_tpu.train.step import build_train_step
        tx = optax.sgd(0.05, momentum=0.9)
        specs = None
        if zero1:
            shapes = jax.eval_shape(
                lambda r: TrainState.create(
                    model, tx, r, jnp.zeros((1, 16, 16, 3), jnp.float32),
                    zero1_shards=8),
                jax.random.key(0))
            padded = padded_flat_size(flat_param_count(shapes.params), 8)
            specs = train_state_specs(shapes, padded, "data")
        step = build_train_step(
            model, tx, mesh, weight_decay=1e-4, zero1=zero1,
            state_specs=specs, grad_accum_steps=accum,
            device_finish=make_device_finish(MEAN, STD),
            device_augment=aug)
        if zero1:
            from jax.sharding import NamedSharding
            shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            state = jax.jit(
                lambda r: TrainState.create(
                    model, tx, r, jnp.zeros((1, 16, 16, 3), jnp.float32),
                    zero1_shards=8),
                out_shardings=shardings)(jax.random.key(0))
        else:
            state = _mini_state(model, tx)
        losses = []
        for b in batches:
            state, m = step(state, b, base)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    ref = run()
    z1 = run(zero1=True)
    np.testing.assert_allclose(ref, z1, rtol=2e-6)
    acc = run(accum=2)
    np.testing.assert_allclose(ref, acc, rtol=2e-6)


# -------------------------------------------------- per-model parity gates
@pytest.mark.parametrize("model_name", ["vggf", "vgg16", "resnet50",
                                        "vit_s16"])
def test_zoo_wire_parity_with_augment(model_name, devices8):
    """The acceptance gate, per zoo model: the SAME u8 pixel stream fed
    (a) over the u8 wire + device finish and (b) host-normalized (and
    host-packed where the descriptor says so) produces EQUAL CPU loss
    trajectories — with the fused augmentation ON, since augmentation runs
    post-finish on bit-identical values. Models run at toy size; the wire
    contract is size-independent."""
    import optax

    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models import build_model
    from distributed_vgg_f_tpu.models.ingest import ingest_descriptor
    from distributed_vgg_f_tpu.train.state import TrainState
    from distributed_vgg_f_tpu.train.step import build_train_step
    mesh = _mesh8(devices8)
    size = 32
    desc = ingest_descriptor(model_name)
    model = build_model(ModelConfig(
        name=model_name, num_classes=10, dropout_rate=0.0,
        compute_dtype="float32"))
    s2d = desc.space_to_depth and size % 4 == 0
    aug = make_device_augment(FLAGS_ON, MEAN, STD, space_to_depth=s2d)
    rng0 = np.random.default_rng(13)
    pixels = [rng0.integers(0, 256, (8, size, size, 3)).astype(np.uint8)
              for _ in range(2)]
    labels = [rng0.integers(0, 10, (8,)).astype(np.int32) for _ in range(2)]
    mean = np.asarray(MEAN, np.float32)
    inv = np.float32(1.0) / np.asarray(STD, np.float32)

    def run(as_u8):
        tx = optax.sgd(0.05, momentum=0.9)
        state = TrainState.create(
            model, tx, jax.random.key(0),
            jnp.zeros((1, size, size, 3), jnp.float32))
        step = build_train_step(
            model, tx, mesh, weight_decay=1e-4,
            device_finish=make_device_finish(MEAN, STD),
            device_augment=aug)
        base = jax.jit(lambda: jax.random.key(1))()
        losses = []
        for px, lb in zip(pixels, labels):
            # host wire ships the normalized floats; with augmentation on
            # the host never packs (host_space_to_depth) — both wires
            # arrive unpacked and the stage packs post-augment
            images = px if as_u8 else (px.astype(np.float32) - mean) * inv
            b = shard_host_batch({"image": images, "label": lb}, mesh)
            state, m = step(state, b, base)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    np.testing.assert_array_equal(run(True), run(False))


# ------------------------------------------------------- trainer + JSONL
def test_trainer_fit_emits_augment_receipts(tmp_path):
    """A tiny augmented fit: the per-window JSONL carries the
    schema-validated `augment` block, the start record the augment flag,
    and the registry the augment/steps counter + enabled gauge."""
    import json

    from distributed_vgg_f_tpu import telemetry
    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
        TrainConfig)
    from distributed_vgg_f_tpu.telemetry.schema import (
        validate_metrics_record)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    telemetry.reset()
    cfg = ExperimentConfig(
        name="augment_fit_smoke",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64,
                        space_to_depth=True,
                        augment=AugmentConfig(enabled=True, hflip=True,
                                              mixup_alpha=0.2)),
        mesh=MeshConfig(num_data=8),
        train=TrainConfig(steps=4, log_every=2, seed=0),
    )
    jsonl = str(tmp_path / "metrics.jsonl")
    trainer = Trainer(cfg, logger=MetricLogger(jsonl_path=jsonl,
                                               stream=io.StringIO()))
    assert trainer.device_augment is not None
    trainer.fit(trainer.init_state())
    records = [json.loads(ln) for ln in open(jsonl)
               if ln.strip()]
    for r in records:
        assert validate_metrics_record(r) == [], r
    start = next(r for r in records if r["event"] == "start")
    assert start["augment"] is True
    trains = [r for r in records if r["event"] == "train"]
    assert trains and all("augment" in r for r in trains)
    assert trains[0]["augment"]["host_flips_disabled"] is True
    snap = telemetry.get_registry().snapshot_split()
    assert snap["counters"].get("augment/steps") == 4
    assert snap["gauges"].get("augment/enabled") == 1
    telemetry.reset()
    telemetry.configure(enabled=True)


def test_trainer_augment_off_is_byte_identical_trajectory():
    """Kill-switch trajectory pin: enabled=false trains the EXACT pre-r13
    stream — losses byte-identical to a config that never mentions
    augmentation."""
    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
        TrainConfig)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    def losses(augment_cfg):
        cfg = ExperimentConfig(
            name="augment_off_pin",
            model=ModelConfig(name="vggf", num_classes=10,
                              compute_dtype="float32", dropout_rate=0.0),
            optim=OptimConfig(base_lr=0.01, reference_batch_size=16),
            data=DataConfig(name="synthetic", image_size=32,
                            global_batch_size=16, num_train_examples=64,
                            augment=augment_cfg),
            mesh=MeshConfig(num_data=8),
            train=TrainConfig(steps=3, seed=0),
        )
        trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
        assert trainer.device_augment is None
        state = trainer.init_state()
        ds = trainer.make_dataset("train")
        out = []
        rng = trainer.base_rng()
        for _ in range(3):
            state, m = trainer.train_step(state, trainer.shard(next(ds)),
                                          rng)
            out.append(float(jax.device_get(m["loss"])))
        return out

    np.testing.assert_array_equal(
        losses(AugmentConfig()),
        losses(AugmentConfig(enabled=False, hflip=False, mixup_alpha=0.9)))


# ---------------------------------------------- flip ownership (native grid)
_native = None


def _native_available():
    global _native
    if _native is None:
        from distributed_vgg_f_tpu.data.native_jpeg import load_native_jpeg
        _native = load_native_jpeg() is not None
    return _native


requires_native = pytest.mark.skipif(
    not _native_available() if True else False,
    reason="native jpeg loader unavailable")


def _imagefolder(tmp_path, n_classes=2, per_class=6, hw=(40, 44)):
    from PIL import Image
    rng = np.random.default_rng(0)
    files, labels = [], []
    for c in range(n_classes):
        d = tmp_path / f"train/class_{c}"
        d.mkdir(parents=True)
        for i in range(per_class):
            p = d / f"img_{i}.jpg"
            Image.fromarray(rng.integers(0, 256, size=(*hw, 3))
                            .astype(np.uint8)).save(p, "JPEG", quality=90)
            files.append(str(p))
            labels.append(c)
    return files, labels


@requires_native
def test_double_flip_structurally_impossible(tmp_path):
    """The satellite grid: cache-warm x augment-on x restart-resume. With
    device-side augmentation owning flips, every host surface — the native
    decoder, the snapshot cache's warm redraw, and resumed streams — must
    serve the IDENTICAL unflipped pixels: byte-equality against the
    hflip=False reference stream in every cell, so no cell exists where a
    host flip could compose with the device flip."""
    from distributed_vgg_f_tpu.config import (
        DataConfig, SnapshotCacheConfig)
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import (
        NativeJpegTrainIterator)
    from distributed_vgg_f_tpu.data.snapshot_cache import (
        SnapshotCachingTrainIterator, shuffle_indices)
    files, labels = _imagefolder(tmp_path)
    n = len(files)
    batch, size, seed = 4, 32, 5
    mean = np.asarray(MEAN, np.float32)
    std = np.asarray(STD, np.float32)
    # the imagefolder builder deterministically permutes the file list
    # with the seed before creating the loader — the reference stream must
    # see the same item order
    order = np.random.default_rng(seed).permutation(n)
    files = [files[i] for i in order]
    labels = [labels[i] for i in order]

    def reference(num_batches, start=0):
        it = NativeJpegTrainIterator(files, labels, batch=batch,
                                     image_size=size, seed=seed, mean=mean,
                                     std=std, num_threads=1, hflip=False)
        try:
            if start:
                assert it.restore_state(start)
            return [next(it) for _ in range(num_batches)]
        finally:
            it.close()

    ref = reference(6)  # two epochs, flips-off ground truth

    def data_cfg(cache_dir=None):
        return DataConfig(
            name="imagenet", data_dir=str(tmp_path), image_size=size,
            global_batch_size=batch, native_threads=1, backend="native",
            augment=AugmentConfig(enabled=True, hflip=True,
                                  mixup_alpha=0.2),
            snapshot_cache=(SnapshotCacheConfig(enabled=True,
                                                dir=str(cache_dir))
                            if cache_dir else SnapshotCacheConfig()))

    # cell 1: augment-on loader — host flips disabled at the source
    ds = build_dataset(data_cfg(), "train", seed=seed)
    assert isinstance(ds, NativeJpegTrainIterator)
    assert ds.hflip is False
    try:
        for b, r in zip([next(ds) for _ in range(6)], ref):
            np.testing.assert_array_equal(b["image"], r["image"])
    finally:
        ds.close()
    # ...while a host-owned-flips loader (augment off) DOES flip: every
    # item is the reference crop or its mirror, and some are mirrored
    ds_flip = NativeJpegTrainIterator(files, labels, batch=batch,
                                      image_size=size, seed=seed, mean=mean,
                                      std=std, num_threads=1)
    try:
        mirrored = 0
        for b, r in zip([next(ds_flip) for _ in range(3)], ref[:3]):
            for i in range(batch):
                got, want = b["image"][i], r["image"][i]
                if np.array_equal(got, want):
                    continue
                np.testing.assert_array_equal(got, want[:, ::-1, :])
                mirrored += 1
        assert mirrored > 0
    finally:
        ds_flip.close()

    # cell 2: restart-resume (no cache) — resumed stream stays unflipped
    resumed = build_dataset(data_cfg(), "train", seed=seed)
    try:
        assert resumed.restore_state(3)
        for b, r in zip([next(resumed) for _ in range(3)], ref[3:6]):
            np.testing.assert_array_equal(b["image"], r["image"])
    finally:
        resumed.close()

    # cell 3: cache cold pass + warm epochs — warm serving never redraws
    # the flip (epoch-0 crops re-served bit-identically, reordered)
    cache_dir = tmp_path / "snap"
    ds = build_dataset(data_cfg(cache_dir), "train", seed=seed)
    assert isinstance(ds, SnapshotCachingTrainIterator)
    assert ds._hflip is False
    try:
        cold = [next(ds) for _ in range(3)]  # epoch 0: cold capture
        for b, r in zip(cold, ref[:3]):
            np.testing.assert_array_equal(b["image"], r["image"])
        by_idx = {}
        order0 = shuffle_indices(n, seed, 0)
        for bi, b in enumerate(cold):
            for j in range(batch):
                by_idx[int(order0[(bi * batch + j) % n])] = b["image"][j]
        warm = [next(ds) for _ in range(6)]  # epochs 1-2: warm serving
        for e in (1, 2):
            order = shuffle_indices(n, seed, e)
            for bi in range(3):
                b = warm[(e - 1) * 3 + bi]
                for j in range(batch):
                    idx = int(order[bi * batch + j])
                    np.testing.assert_array_equal(
                        b["image"][j], by_idx[idx],
                        err_msg=f"warm epoch {e} redrew a flip (item "
                                f"{idx}) despite device-owned flips")
    finally:
        ds.close()

    # cell 4: cache-warm x restart-resume — a NEW wrapped iterator over
    # the same (complete) store resumes mid-warm-stream, still unflipped
    ds2 = build_dataset(data_cfg(cache_dir), "train", seed=seed)
    try:
        assert ds2.restore_state(4)
        got = [next(ds2) for _ in range(2)]
        np.testing.assert_array_equal(got[0]["image"], warm[1]["image"])
        np.testing.assert_array_equal(got[1]["image"], warm[2]["image"])
    finally:
        ds2.close()


@requires_native
def test_native_hflip_switch_contracts(tmp_path):
    """ABI v9 surface: the per-loader switch refuses after the stream
    started; decode_single reproduces the flips-disabled crop; the crop
    geometry is identical at both settings (drawn-but-ignored RNG)."""
    import io as _io

    from PIL import Image

    from distributed_vgg_f_tpu.data.native_jpeg import (
        NativeJpegTrainIterator, decode_single_image, load_native_jpeg)
    rng = np.random.default_rng(3)
    buf = _io.BytesIO()
    Image.fromarray(rng.integers(0, 256, size=(48, 52, 3))
                    .astype(np.uint8)).save(buf, "JPEG", quality=90)
    data = buf.getvalue()
    zero, one = np.zeros(3, np.float32), np.ones(3, np.float32)
    flipped_seeds = 0
    for s in range(8):
        on = decode_single_image(data, 16, zero, one, rng_seed=s)
        off = decode_single_image(data, 16, zero, one, rng_seed=s,
                                  hflip=False)
        if np.array_equal(on, off):
            continue
        np.testing.assert_array_equal(on, off[:, ::-1, :])
        flipped_seeds += 1
    assert 0 < flipped_seeds < 8
    # set_hflip after the first draw is too late — refused, not raced
    files, labels = _imagefolder(tmp_path, n_classes=1, per_class=4)
    it = NativeJpegTrainIterator(files, labels, batch=2, image_size=16,
                                 seed=0, mean=zero, std=one, num_threads=1)
    try:
        next(it)
        lib = load_native_jpeg()
        assert int(lib.dvgg_jpeg_loader_set_hflip(it._handle, 0)) == -1
        assert int(lib.dvgg_jpeg_loader_hflip(it._handle)) == 1
    finally:
        it.close()
