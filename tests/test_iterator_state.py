"""Position-exact resumable ingest (r18, data/iterator_state.py): the
checkpointable iterator-state blob, the zero-replay restore transplant, the
live wire rebuild, and the trainer-side autotuner wire knob it unbinds.

Pins, in rough order of load-bearing-ness:

- cursor semantics are the SHARED next-item-to-emit contract: `epoch_of`
  is the one epoch-boundary off-by-one, and the service plane's
  `shard_owner` + the client's blob restore + the checkpoint blob all
  agree on it (ISSUE 15 satellite: the cross-implementation test);
- a blob captured mid-epoch restores a fresh native stack to the EXACT
  cursor — zero replayed batches, the in-flight read-ahead set re-issued
  (byte-identity against the uninterrupted stream);
- `rebuild_live` switches the wire host_f32→u8 mid-epoch and the stream
  continues byte-identical to a from-batch-0 u8 stream at the same
  cursors — the parity gate behind binding the trainer's wire knob;
- a LIVE trainer fit with the autotuner on actuates host_f32→u8 mid-epoch
  (wire_u8 actuation in the JSONL autotune block, a rebuild receipt in
  the iterator_state block) — the r11 "trainer leaves it unbound" receipt
  is retired;
- kill-and-resume ≡ uninterrupted: CPU loss-trajectory EQUALITY with the
  blob dispatch (and the pre-r18 receipt-absent checkpoint dispatches to
  the unchanged replay path — `data.iterator_state.enabled=false` is
  byte-identical to the r17 feed path).
"""

import io
import json
import os

import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig, ExperimentConfig, IteratorStateConfig, MeshConfig,
    ModelConfig, OptimConfig, TelemetryConfig, TrainConfig)
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.data.iterator_state import (
    ResumableIngest, epoch_of, restore_from_blob)
from distributed_vgg_f_tpu.telemetry import schema


# ------------------------------------------------------------ fixtures

N_ITEMS = 40
BATCH = 8
BPE = N_ITEMS // BATCH  # 5 batches per epoch


@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory):
    native = pytest.importorskip("distributed_vgg_f_tpu.data.native_jpeg")
    if native.load_native_jpeg() is None:
        pytest.skip("native jpeg loader unavailable")
    from PIL import Image
    root = tmp_path_factory.mktemp("iterstate_imagenet")
    rs = np.random.RandomState(7)
    for cls in ("n01", "n02", "n03", "n04"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i in range(N_ITEMS // 4):
            Image.fromarray((rs.rand(72, 80, 3) * 255).astype(np.uint8)) \
                .save(str(d / f"{i}.jpg"), "JPEG", quality=90)
    return str(root)


def _data_cfg(data_dir, **over):
    return DataConfig(name="imagenet", data_dir=data_dir, image_size=32,
                      global_batch_size=BATCH, num_train_examples=N_ITEMS,
                      **over)


def _factory(seed=7):
    return lambda dc: build_dataset(dc, "train", seed=seed, num_classes=10)


def _ingest(data_cfg, seed=7):
    return ResumableIngest(_factory(seed), data_cfg, seed=seed,
                           batches_per_epoch=BPE)


def _stream(data_cfg, n, seed=7):
    ing = _ingest(data_cfg, seed)
    try:
        return [{k: np.array(v, copy=True) for k, v in next(ing).items()}
                for _ in range(n)]
    finally:
        ing.close()


# ------------------------------------------- cursor semantics (shared)

def test_epoch_of_next_item_to_emit():
    """THE off-by-one: the batch AT cursor k*N opens epoch k. A cursor is
    the next item to emit, never the last emitted."""
    assert epoch_of(0, 5) == 0
    assert epoch_of(4, 5) == 0
    assert epoch_of(5, 5) == 1      # boundary batch belongs to the NEW epoch
    assert epoch_of(6, 5) == 1
    assert epoch_of(10, 5) == 2


def test_shard_owner_routes_through_shared_epoch_helper():
    """Cross-implementation pin (satellite): the service plane's ownership
    split draws its per-epoch permutation at exactly `epoch_of(cursor)` —
    reconstructed here from the primitives, boundary cursors included."""
    from distributed_vgg_f_tpu.data.ingest_service import (
        _OWNER_TAG, shard_owner)
    from distributed_vgg_f_tpu.data.snapshot_cache import (
        mix, shuffle_indices)
    seed, workers, bpe = 11, 3, 5
    for cursor in (0, 4, 5, 6, 9, 10, 14, 15):
        perm = shuffle_indices(workers, mix(seed, _OWNER_TAG),
                               epoch_of(cursor, bpe))
        assert shard_owner(cursor, workers, seed, bpe) \
            == int(perm[cursor % workers]), cursor
    # boundary regression shape: cursor N and N-1 sit in DIFFERENT epochs
    assert epoch_of(bpe, bpe) != epoch_of(bpe - 1, bpe)


def test_service_client_blob_restore_agrees_with_blob_cursor():
    """`restore_state(step)` generalized to the blob: the client seeks to
    the blob's cursor (next-item-to-emit) and refuses identity
    mismatches — the epoch-boundary off-by-one cannot drift between the
    two implementations because both read the same blob field."""
    from distributed_vgg_f_tpu.data.service_client import (
        ServiceIngestClient)
    syn = DataConfig(name="synthetic", image_size=8, global_batch_size=4,
                     num_train_examples=20)

    def local_factory():
        return build_dataset(syn, "train", seed=3, num_classes=10)

    for cursor in (BPE - 1, BPE, BPE + 1):  # the boundary triplet
        blob = _blob_at(cursor, seed=3, bpe=BPE)
        client = ServiceIngestClient(
            ("127.0.0.1:1",), seed=3, batches_per_epoch=BPE,
            local_factory=local_factory, connect_timeout_s=0.2,
            request_timeout_s=0.2)
        try:
            assert client.restore_state_blob(blob) is True
            assert client.describe()["next_cursor"] == cursor
        finally:
            client.close()
    # identity mismatch: a blob from another stream must be refused
    client = ServiceIngestClient(
        ("127.0.0.1:1",), seed=3, batches_per_epoch=BPE,
        local_factory=local_factory, connect_timeout_s=0.2,
        request_timeout_s=0.2)
    try:
        assert client.restore_state_blob(
            _blob_at(4, seed=99, bpe=BPE)) is False
        assert client.restore_state_blob(
            _blob_at(4, seed=3, bpe=BPE + 1)) is False
    finally:
        client.close()


def _blob_at(cursor, *, seed, bpe, in_flight=0, wire="host_f32"):
    return {"kind": "ingest_iterator_state", "version": 1,
            "cursor": cursor, "epoch": epoch_of(cursor, bpe),
            "batches_per_epoch": bpe, "seed": seed,
            "shuffle": {"algo": "splitmix64", "seed": seed,
                        "epoch": epoch_of(cursor, bpe)},
            "source_cursor": cursor + in_flight,
            "in_flight": list(range(cursor, cursor + in_flight)),
            "wire": wire, "ingest": "local", "rebuilds": 0}


# --------------------------------------------------- schema validators

def test_blob_schema_validates_and_rejects_drift():
    errors = []
    schema.validate_iterator_state_blob(_blob_at(7, seed=0, bpe=5,
                                                 in_flight=3),
                                        "t", errors)
    assert errors == []
    # the off-by-one the validator exists for: epoch from LAST-emitted
    bad = _blob_at(5, seed=0, bpe=5)
    bad["epoch"] = 0  # 5 // 5 == 1 — last-emitted semantics are a bug
    errors = []
    schema.validate_iterator_state_blob(bad, "t", errors)
    assert any("next-item-to-emit" in e for e in errors)
    # in-flight must be exactly [cursor, source_cursor)
    bad = _blob_at(4, seed=0, bpe=5, in_flight=2)
    bad["in_flight"] = [4]
    errors = []
    schema.validate_iterator_state_blob(bad, "t", errors)
    assert any("in_flight" in e for e in errors)


def test_resume_row_schema_pins_zero_replay():
    row = {"mode": "resume_bench", "resume_mode": "exact",
           "replayed_batches": 0, "resume_seconds": 0.5,
           "kill_cursor": 7, "batches_per_epoch": 5,
           "first_batch_matches": True}
    errors = []
    schema.validate_resume_row(row, "t", errors)
    assert errors == []
    bad = dict(row, replayed_batches=2)
    errors = []
    schema.validate_resume_row(bad, "t", errors)
    assert any("zero replay" in e for e in errors)
    bad = dict(row, first_batch_matches=False)
    errors = []
    schema.validate_resume_row(bad, "t", errors)
    assert any("diverged" in e for e in errors)
    replay = dict(row, resume_mode="replay", replayed_batches=2)
    errors = []
    schema.validate_resume_row(replay, "t", errors)
    assert errors == []


# ------------------------------------------ blob capture/restore (native)

def test_native_blob_restore_zero_replay_byte_identical(jpeg_dir):
    """Mid-epoch kill-and-restore: a fresh stack restored from the blob
    emits batch `cursor` first (zero replay) and every later batch
    byte-identical to the uninterrupted stream; the in-flight read-ahead
    set is accounted and receipted as transplanted."""
    cfg = _data_cfg(jpeg_dir, wire="u8")
    ref = _stream(cfg, 10)

    ing = _ingest(cfg)
    for _ in range(9):   # source drew 9; the trainer "consumed" 7
        next(ing)
    blob = ing.capture_state(7)
    ing.close()
    assert blob["cursor"] == 7 and blob["epoch"] == 1  # mid-epoch
    assert blob["in_flight"] == [7, 8]
    errors = []
    schema.validate_iterator_state_blob(blob, "t", errors)
    assert errors == []
    # JSON round-trip: exactly what the checkpoint extra stores
    blob = json.loads(json.dumps(blob))

    resumed = _ingest(cfg)
    receipt = restore_from_blob(resumed, blob, step=7,
                                expect={"seed": 7, "batches_per_epoch": BPE,
                                        "ingest": "local"})
    assert receipt is not None
    assert receipt["replayed_batches"] == 0
    assert receipt["transplanted_items"] == 2
    for i in range(7, 10):
        got = next(resumed)
        np.testing.assert_array_equal(got["image"], ref[i]["image"])
        np.testing.assert_array_equal(got["label"], ref[i]["label"])
    resumed.close()


def test_blob_restore_refuses_mismatch_and_unknown_version(jpeg_dir):
    cfg = _data_cfg(jpeg_dir)
    ing = _ingest(cfg)
    for _ in range(3):
        next(ing)
    blob = ing.capture_state(3)
    ing.close()
    # cursor/step drift: falling back beats seeking a wrong position
    fresh = _ingest(cfg)
    assert restore_from_blob(fresh, blob, step=4, expect={}) is None
    # identity drift
    assert restore_from_blob(fresh, blob, step=3,
                             expect={"seed": 8}) is None
    # unknown version = receipt-absent semantics
    v2 = dict(blob, version=99)
    assert restore_from_blob(fresh, v2, step=3, expect={}) is None
    # intact blob still restores the same (pre-start) instance
    assert restore_from_blob(fresh, blob, step=3,
                             expect={"seed": 7}) is not None
    fresh.close()


# --------------------------------------------------- live wire rebuild

def test_wire_rebuild_byte_identical_continuation(jpeg_dir):
    """The parity gate behind the trainer wire knob: escalate
    host_f32→u8 mid-epoch and the continuation is byte-identical to a
    from-batch-0 u8 stream at the same cursors (labels AND pixels — the
    post-switch batches ARE the u8 stream's batches)."""
    from distributed_vgg_f_tpu.data import native_jpeg
    if not native_jpeg.wire_u8_enabled():
        pytest.skip("u8 wire unavailable")
    u8_ref = _stream(_data_cfg(jpeg_dir, wire="u8"), 9)
    f32_ref = _stream(_data_cfg(jpeg_dir, wire="host_f32"), 4)

    ing = _ingest(_data_cfg(jpeg_dir, wire="host_f32"))
    assert ing.wire_value() == 0 and ing.wire_rebuild_available()
    for i in range(4):
        got = next(ing)
        np.testing.assert_array_equal(got["image"], f32_ref[i]["image"])
    assert ing.apply_wire(1) == 1
    assert ing.wire == "u8" and ing.rebuilds == 1
    for i in range(4, 9):
        got = next(ing)
        assert got["image"].dtype == np.uint8
        np.testing.assert_array_equal(got["image"], u8_ref[i]["image"])
        np.testing.assert_array_equal(got["label"], u8_ref[i]["label"])
    # and back down: the knob is reversible (host wire re-parity)
    assert ing.apply_wire(0) == 0 and ing.rebuilds == 2
    ing.close()


def test_wire_knob_gating():
    """No rebuild surface, no knob: synthetic (no u8 wire) and the
    service client (handshook stream identity) must read unavailable —
    the controller then simply has no such knob, never a silent no-op."""
    syn = DataConfig(name="synthetic", image_size=8, global_batch_size=4,
                     num_train_examples=16)
    ing = ResumableIngest(_factory(0), syn, seed=0, batches_per_epoch=4)
    assert not ing.wire_rebuild_available()
    assert ing.wire_knob() is None
    assert ing.apply_wire(1) is None
    ing.close()


def test_autotuner_escalates_wire_through_resumable_ingest(jpeg_dir):
    """The r11 carve-out retired at the unit level: an IngestAutotuner
    holding ONLY the ResumableIngest-bound wire knob escalates
    host_f32→u8 on an infeed_bound streak, with the actuation record
    naming wire_u8."""
    from distributed_vgg_f_tpu.data import autotune as at
    from distributed_vgg_f_tpu.data import native_jpeg
    if not native_jpeg.wire_u8_enabled():
        pytest.skip("u8 wire unavailable")
    ing = _ingest(_data_cfg(jpeg_dir, wire="host_f32"))
    knob = ing.wire_knob()
    assert knob is not None and knob.name == "wire_u8"
    from distributed_vgg_f_tpu.config import AutotuneConfig
    tuner = at.IngestAutotuner(
        AutotuneConfig(enabled=True, k_windows=1, cooldown_windows=0,
                       settled_after_windows=1), [knob])
    rec = tuner.observe({"verdict": "infeed_bound"})
    assert rec["actuations"][0]["knob"] == "wire_u8"
    assert rec["actuations"][0]["to"] == 1
    assert ing.wire == "u8" and ing.rebuilds == 1
    ing.close()


# --------------------------------------------------- trainer integration

def _exp_cfg(data_dir, ckpt_dir, steps, **data_over):
    its = data_over.pop("iterator_state", IteratorStateConfig(enabled=True))
    return ExperimentConfig(
        name="iterstate_test",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=BATCH),
        data=_data_cfg(data_dir, iterator_state=its, **data_over),
        mesh=MeshConfig(num_data=8),
        train=TrainConfig(steps=steps, seed=0, log_every=1,
                          checkpoint_dir=ckpt_dir,
                          checkpoint_every_steps=3,
                          track_best_eval=False),
        telemetry=TelemetryConfig(enabled=True),
    )


def _run_fit(cfg):
    import jax

    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    buf = io.StringIO()
    logger = MetricLogger(stream=buf)
    # route the records through an in-memory list alongside the stream
    records = []
    orig = logger.log

    def log(event, metrics):
        records.append({"event": event, **dict(metrics)})
        return orig(event, metrics)

    logger.log = log
    trainer = Trainer(cfg, logger=logger)
    state = trainer.fit()
    import hashlib
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    losses = {r["step"]: r["loss"] for r in records
              if r["event"] == "train" and "loss" in r}
    return trainer, records, losses, h.hexdigest()


def test_trainer_blob_rides_every_checkpoint_and_zero_replay_resume(
        jpeg_dir, tmp_path, devices8):
    """Acceptance (local × cold × u8): kill-and-resume mid-epoch ≡
    uninterrupted — CPU loss-trajectory EQUALITY, zero replayed batches
    (the blob-dispatch receipt), and the blob present in every durable
    checkpoint's extra."""
    from distributed_vgg_f_tpu import telemetry
    ck_a = str(tmp_path / "interrupted")
    ck_b = str(tmp_path / "uninterrupted")

    # interrupted run: stop at step 4 (mid-epoch 0; BPE=5)
    trainer, recs, _, _ = _run_fit(_exp_cfg(jpeg_dir, ck_a, 4, wire="u8"))
    assert trainer._ingest is not None
    mgr = trainer.checkpoints
    step4 = mgr.latest_step()
    assert step4 == 4
    blob = mgr.iterator_state_at(step4)
    assert blob is not None and blob["cursor"] == 4
    assert blob["epoch"] == 0 and blob["batches_per_epoch"] == BPE
    errors = []
    schema.validate_iterator_state_blob(blob, "ckpt", errors)
    assert errors == []
    assert telemetry.get_registry().counter_value(
        "ingest_state/saves", 0) >= 1

    # resume to 8: the blob dispatch must fire, replaying nothing
    trainer2, recs2, losses2, fp2 = _run_fit(
        _exp_cfg(jpeg_dir, ck_a, 8, wire="u8"))
    restores = [r for r in recs2 if r["event"] == "iterator_state_restore"]
    assert restores and restores[0]["cursor"] == 4
    assert restores[0]["replayed_batches"] == 0
    assert any(r["event"] == "data_iterator_restore" and r["restored"]
               for r in recs2)
    # per-window iterator_state block present and schema-valid
    blocks = [r["iterator_state"] for r in recs2
              if r["event"] == "train" and "iterator_state" in r]
    assert blocks
    for b in blocks:
        errors = []
        schema.validate_iterator_state_block(b, "rec", errors)
        assert errors == []

    # uninterrupted control: same seed, fresh dir
    _, _, losses_u, fp_u = _run_fit(_exp_cfg(jpeg_dir, ck_b, 8, wire="u8"))
    for step in range(5, 9):
        assert losses2[step] == losses_u[step], step
    assert fp2 == fp_u, "resumed run diverged from uninterrupted"


def test_pre_r18_checkpoint_dispatches_to_replay_path(jpeg_dir, tmp_path,
                                                      devices8):
    """Acceptance: a receipt-absent checkpoint (written with the
    kill-switch off — byte-for-byte what r17 wrote) restores through the
    unchanged replay path: no iterator_state_restore event, and the run
    still completes with the r17 restore semantics."""
    ck = str(tmp_path / "pre_r18")
    off = IteratorStateConfig(enabled=False)
    trainer, _, _, _ = _run_fit(
        _exp_cfg(jpeg_dir, ck, 4, wire="u8", iterator_state=off))
    assert trainer._ingest is None           # kill-switch: wrapper absent
    assert trainer.checkpoints.iterator_state_at(4) is None

    # resume with the feature ON: receipt-absent -> replay dispatch
    trainer2, recs2, _, _ = _run_fit(_exp_cfg(jpeg_dir, ck, 6, wire="u8"))
    assert not any(r["event"] == "iterator_state_restore" for r in recs2)
    restore = [r for r in recs2 if r["event"] == "data_iterator_restore"]
    assert restore and restore[0]["restored"] is True  # native O(1) seek
    # and the new run's own checkpoints DO carry the receipt
    assert trainer2.checkpoints.iterator_state_at(6) is not None


def test_kill_switch_off_is_r17_feed_path(jpeg_dir, tmp_path, devices8):
    """data.iterator_state.enabled=false ≡ r17: the wrapper is
    structurally absent and the loss trajectory is byte-equal to the
    enabled run's (the wrapper is a pure pass-through)."""
    _, recs_on, losses_on, fp_on = _run_fit(
        _exp_cfg(jpeg_dir, str(tmp_path / "on"), 5, wire="u8"))
    off = IteratorStateConfig(enabled=False)
    trainer_off, recs_off, losses_off, fp_off = _run_fit(
        _exp_cfg(jpeg_dir, str(tmp_path / "off"), 5, wire="u8",
                 iterator_state=off))
    assert trainer_off._ingest is None
    assert not any("iterator_state" in r for r in recs_off
                   if r["event"] == "train")
    assert losses_on == losses_off
    assert fp_on == fp_off


def test_trainer_live_wire_escalation(jpeg_dir, tmp_path, devices8):
    """Acceptance: a LIVE CPU fit with the autotuner on actuates
    host_f32→u8 mid-epoch — the wire_u8 actuation lands in the JSONL
    autotune block, the iterator_state block flips its wire receipt, and
    the run finishes with finite losses. (Byte-identity of the
    continuation is pinned at the stream level above; here the knob is
    driven by REAL verdicts through the production controller.)"""
    import dataclasses as dc

    from distributed_vgg_f_tpu.config import AutotuneConfig
    cfg = _exp_cfg(jpeg_dir, str(tmp_path / "esc"), 8, wire="host_f32")
    # rails pin every cheaper knob so the first escalation reaches the
    # wire; a microscopic infeed threshold makes every window
    # infeed_bound (any nonzero feed wait qualifies)
    cfg = dc.replace(
        cfg,
        data=dc.replace(cfg.data, prefetch=1, autotune=AutotuneConfig(
            enabled=True, k_windows=1, cooldown_windows=0,
            settled_after_windows=1, min_threads=1, max_threads=1,
            min_prefetch=1, max_prefetch=1, min_prefetch_to_device=1,
            max_prefetch_to_device=1)),
        train=dc.replace(cfg.train, prefetch_to_device=1,
                         checkpoint_dir=""),
        telemetry=dc.replace(cfg.telemetry, infeed_threshold=1e-6))
    trainer, recs, losses, _ = _run_fit(cfg)
    acts = [a for r in recs if r["event"] == "train"
            for a in (r.get("autotune") or {}).get("actuations", [])]
    assert any(a["knob"] == "wire_u8" and a["to"] == 1 for a in acts), acts
    assert trainer._ingest is not None and trainer._ingest.wire == "u8"
    assert trainer._ingest.rebuilds >= 1
    blocks = [r["iterator_state"] for r in recs if r["event"] == "train"]
    assert blocks[-1]["wire"] == "u8" and blocks[-1]["rebuilds"] >= 1
    assert all(np.isfinite(v) for v in losses.values())


def test_counters_registered():
    from distributed_vgg_f_tpu import telemetry
    from distributed_vgg_f_tpu.data import iterator_state  # noqa: F401
    syn = DataConfig(name="synthetic", image_size=8, global_batch_size=4,
                     num_train_examples=16)
    ing = ResumableIngest(_factory(0), syn, seed=0, batches_per_epoch=4)
    ing.close()
    counters = telemetry.get_registry().snapshot_split()["counters"]
    for name in ("ingest_state/saves", "ingest_state/restores",
                 "ingest_state/transplanted_items",
                 "ingest_state/rebuilds"):
        assert name in counters, name
