"""Host-side space-to-depth input contract (data.space_to_depth): the VGG-F
stem accepts (S/4, S/4, 48) packed batches; every train pipeline can emit
them; packed and raw inputs produce identical model outputs."""

import dataclasses

import numpy as np
import pytest

from distributed_vgg_f_tpu.config import DataConfig, ModelConfig
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset


def _pack(x):
    b, h, w, c = x.shape
    return x.reshape(b, h // 4, 4, w // 4, 4, c) \
            .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 4, w // 4, 16 * c)


def test_model_packed_equals_raw():
    import jax

    from distributed_vgg_f_tpu.models import build_model

    model = build_model(ModelConfig(name="vggf", num_classes=11,
                                    compute_dtype="float32"))
    rng = np.random.default_rng(0)
    raw = rng.standard_normal((2, 64, 64, 3)).astype(np.float32)
    variables = model.init(jax.random.key(0), raw, train=False)
    out_raw = model.apply(variables, raw, train=False)
    out_packed = model.apply(variables, _pack(raw), train=False)
    # same weights, same math — the packed path only skips the on-device
    # relayout, so f32 outputs match exactly
    np.testing.assert_array_equal(np.asarray(out_raw),
                                  np.asarray(out_packed))


def test_synthetic_packed_matches_manual_pack():
    kw = dict(batch_size=4, image_size=32, num_classes=10, seed=3)
    raw = next(SyntheticDataset(**kw))
    packed = next(SyntheticDataset(space_to_depth=True, **kw))
    assert packed["image"].shape == (4, 8, 8, 48)
    np.testing.assert_array_equal(packed["image"], _pack(raw["image"]))
    np.testing.assert_array_equal(packed["label"], raw["label"])
    with pytest.raises(ValueError, match="image_size"):
        SyntheticDataset(batch_size=4, image_size=30, space_to_depth=True)


def test_tfdata_imagenet_packed_matches_manual_pack(tmp_path):
    tf = pytest.importorskip("tensorflow")
    from distributed_vgg_f_tpu.data import build_dataset

    rng = np.random.default_rng(0)
    path = tmp_path / "train-00000-of-00001"
    with tf.io.TFRecordWriter(str(path)) as w:
        for _ in range(8):
            img = rng.integers(0, 256, size=(80, 96, 3)).astype(np.uint8)
            jpeg = tf.io.encode_jpeg(img).numpy()
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[jpeg])),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[1])),
            }))
            w.write(ex.SerializeToString())

    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path), image_size=32,
                     global_batch_size=4, shuffle_buffer=8, native_jpeg=False)
    raw = next(build_dataset(cfg, "train", seed=5))
    packed = next(build_dataset(
        dataclasses.replace(cfg, space_to_depth=True), "train", seed=5))
    assert packed["image"].shape == (4, 8, 8, 48)
    np.testing.assert_allclose(packed["image"], _pack(raw["image"]),
                               rtol=0, atol=0)


def test_native_loader_packed_matches_manual_pack(tmp_path):
    tf = pytest.importorskip("tensorflow")
    from distributed_vgg_f_tpu.data.native_jpeg import (
        NativeJpegTrainIterator, load_native_jpeg)
    if load_native_jpeg() is None:
        pytest.skip("native loader unavailable")

    rng = np.random.default_rng(1)
    files, labels = [], []
    for i in range(6):
        p = str(tmp_path / f"img_{i}.jpg")
        img = rng.integers(0, 256, size=(72, 88, 3)).astype(np.uint8)
        with open(p, "wb") as f:
            f.write(tf.io.encode_jpeg(img, quality=90).numpy())
        files.append(p)
        labels.append(i)
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    kw = dict(seed=2, mean=mean, std=std)
    raw_it = NativeJpegTrainIterator(files, labels, 3, 32, **kw)
    packed_it = NativeJpegTrainIterator(files, labels, 3, 32,
                                        space_to_depth=True, **kw)
    for _ in range(3):
        raw, packed = next(raw_it), next(packed_it)
        assert packed["image"].shape == (3, 8, 8, 48)
        np.testing.assert_array_equal(packed["image"], _pack(raw["image"]))
        np.testing.assert_array_equal(packed["label"], raw["label"])
    raw_it.close()
    packed_it.close()


def test_trainer_rejects_non_vggf_space_to_depth():
    import io

    from distributed_vgg_f_tpu.config import (
        ExperimentConfig, MeshConfig, OptimConfig, TrainConfig)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = ExperimentConfig(
        name="bad_s2d",
        model=ModelConfig(name="resnet50", num_classes=10),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=8,
                        space_to_depth=True),
        mesh=MeshConfig(num_data=0),
        train=TrainConfig(steps=1))
    with pytest.raises(ValueError, match="vggf"):
        Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))


def test_trainer_rejects_space_to_depth_on_non_packing_dataset():
    """ADVICE r2: cifar10 (32 % 4 == 0, vggf) used to pass the guard while its
    builder silently ignored the flag — the requested layout contract must be
    rejected when the host pipeline doesn't implement packing."""
    import io

    from distributed_vgg_f_tpu.config import (
        ExperimentConfig, MeshConfig, OptimConfig, TrainConfig)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = ExperimentConfig(
        name="bad_s2d_cifar",
        model=ModelConfig(name="vggf", num_classes=10),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(name="cifar10", image_size=32, global_batch_size=8,
                        space_to_depth=True),
        mesh=MeshConfig(num_data=0),
        train=TrainConfig(steps=1))
    with pytest.raises(ValueError, match="packing"):
        Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
