"""Exact eval (SURVEY.md §3.4): pad-and-mask over exactly the held-out split.

Replaces the `.repeat()` re-scoring trade-off — every example scored exactly
once, padding rows masked out, uneven host shards kept in lockstep (the
two-process variant lives in tests/test_multihost.py).
"""

import numpy as np
import pytest

from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable


def _epoch_factory(n_examples, local_batch, image_shape=(8, 8, 3), seed=0):
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((n_examples,) + image_shape).astype(np.float32)
    labels = rng.integers(0, 10, size=(n_examples,)).astype(np.int32)

    def epoch():
        for i in range(0, n_examples, local_batch):
            yield {"image": images[i:i + local_batch],
                   "label": labels[i:i + local_batch]}

    return epoch, images, labels


def test_finite_eval_iterable_pads_final_batch():
    epoch, _, labels = _epoch_factory(10, 4)
    ds = FiniteEvalIterable(epoch, 4, (8, 8, 3), np.float32)
    batches = list(ds)
    assert len(batches) == 3
    for b in batches:
        assert b["image"].shape == (4, 8, 8, 3)
        assert b["valid"].shape == (4,)
    assert batches[0]["valid"].all() and batches[1]["valid"].all()
    assert batches[2]["valid"].tolist() == [True, True, False, False]
    # padded rows are zeros, real rows untouched
    assert (batches[2]["image"][2:] == 0).all()
    assert (batches[2]["label"][:2] == labels[8:10]).all()
    # re-iterable: a second pass yields the same stream
    again = list(ds)
    assert len(again) == 3
    np.testing.assert_array_equal(again[2]["valid"], batches[2]["valid"])


def test_padding_batch_all_invalid():
    epoch, _, _ = _epoch_factory(10, 4)
    ds = FiniteEvalIterable(epoch, 4, (8, 8, 3), np.float32)
    pad = ds.padding_batch()
    assert not pad["valid"].any()
    assert pad["image"].shape == (4, 8, 8, 3)
    assert pad["image"].dtype == np.float32
    assert pad["label"].dtype == np.int32


def test_topk_correct_masks_padding_rows():
    import jax.numpy as jnp

    from distributed_vgg_f_tpu.ops.metrics import topk_correct

    # Padded rows have label 0; give them logits that argmax to 0 so an
    # unmasked count would wrongly include them.
    logits = jnp.asarray([[0.1, 0.9], [0.9, 0.1], [1.0, 0.0], [1.0, 0.0]])
    labels = jnp.asarray([1, 1, 0, 0])
    valid = jnp.asarray([True, True, False, False])
    assert int(topk_correct(logits, labels, 1)) == 3
    assert int(topk_correct(logits, labels, 1, valid)) == 1


@pytest.fixture(scope="module")
def smoke_trainer():
    import io

    from distributed_vgg_f_tpu.config import apply_overrides, get_config
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = apply_overrides(get_config("vggf_cifar10_smoke"),
                          {"data.global_batch_size": 48, "train.steps": 1})
    return Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))


@pytest.fixture(scope="module")
def small_eval_ds(smoke_trainer):
    """A 500-example slice of the real eval split, re-wrapped finite: 500 is
    not divisible by the 48-row batch (10*48 + 20), so the final batch is
    partial — the case `.repeat()` used to fudge."""
    full = iter(smoke_trainer.make_dataset("eval"))
    images, labels = [], []
    while sum(len(x) for x in labels) < 500:
        b = next(full)
        images.append(b["image"][b["valid"]])
        labels.append(b["label"][b["valid"]])
    images = np.concatenate(images)[:500]
    labels = np.concatenate(labels)[:500]

    def epoch():
        for i in range(0, 500, 48):
            yield {"image": images[i:i + 48], "label": labels[i:i + 48]}

    return FiniteEvalIterable(epoch, 48, images.shape[1:], images.dtype)


def test_make_dataset_eval_is_finite(smoke_trainer):
    ds = smoke_trainer.make_dataset("eval")
    assert getattr(ds, "is_finite", False)
    # 10,000 examples / 48 → 209 batches, final one padded to 48 with 32 pad
    first = next(iter(ds))
    assert first["image"].shape[0] == 48
    assert "valid" in first


def test_trainer_eval_scores_exactly_the_split(smoke_trainer, small_eval_ds):
    trainer = smoke_trainer
    state = trainer.init_state()
    result = trainer.evaluate(state, small_eval_ds)
    assert result["eval_examples"] == 500
    assert 0.0 <= result["eval_top1"] <= result["eval_top5"] <= 1.0
    # Re-running on the same (re-iterable) dataset scores the split again —
    # the in-training periodic-eval path.
    result2 = trainer.evaluate(state, small_eval_ds)
    assert result2["eval_examples"] == 500
    assert result2["eval_top1"] == result["eval_top1"]


def test_trainer_eval_matches_host_side_reference(smoke_trainer, small_eval_ds):
    """psum-accumulated masked counts == a plain host-side argmax over the
    exact split (computed by running the same model per-batch on host)."""
    import jax

    trainer = smoke_trainer
    state = trainer.init_state()
    result = trainer.evaluate(state, small_eval_ds)

    correct = 0
    total = 0
    params = jax.device_get(state.params)
    for batch in small_eval_ds:
        logits = trainer.model.apply({"params": params},
                                     batch["image"].astype(np.float32),
                                     train=False)
        pred = np.argmax(np.asarray(logits, np.float32), axis=-1)
        mask = batch["valid"]
        correct += int((pred[mask] == batch["label"][mask]).sum())
        total += int(mask.sum())
    assert total == 500
    assert result["eval_top1"] == pytest.approx(correct / total, abs=1e-12)
