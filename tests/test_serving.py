"""Production inference service (serving/, r17): bucketed AOT engine,
dynamic-batcher admission (max-latency partial flush, max-batch burst
flush), overload shed (typed 503, bounded queue, no collapse), clean drain,
the admission controller, /servingz, the serving sentinel basis — and the
acceptance gates: batched-server predictions bitwise-equal to offline
run_predict on the same inputs, and the kill-switch (serving off leaves
offline predict untouched, structurally)."""

import io
import json
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    ServingConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.telemetry import exporter as exporter_mod
from distributed_vgg_f_tpu.telemetry import flight as flight_mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    flight_mod.get_flight().clear()
    yield
    exporter_mod.stop_exporter()
    telemetry.reset()
    flight_mod.get_flight().clear()
    telemetry.configure(enabled=True)


# ------------------------------------------------------------------ helpers

def _tiny_engine(model_name="vggf", num_classes=5, size=32, buckets=(),
                 max_batch=4):
    import jax

    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    from distributed_vgg_f_tpu.models.ingest import ingest_descriptor
    from distributed_vgg_f_tpu.models.registry import build_model
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    model = build_model(ModelConfig(name=model_name,
                                    num_classes=num_classes,
                                    compute_dtype="float32"))
    desc = ingest_descriptor(model_name)
    finish = make_device_finish(desc.mean_rgb, desc.stddev_rgb)
    x0 = jax.numpy.zeros((1, size, size, 3), jax.numpy.uint8)
    variables = model.init(jax.random.PRNGKey(0), finish(x0), train=False)
    return PredictEngine(
        model_name=model_name, model=model, params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        image_size=size, num_classes=num_classes, buckets=buckets,
        max_batch=max_batch)


def _images(n, size=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n, size, size, 3)).astype(np.uint8)


def _serving_cfg(**kw):
    kw.setdefault("enabled", True)
    return ServingConfig(**kw)


def _post(port, model, image, timeout=30, k=None):
    url = f"http://127.0.0.1:{port}/v1/predict/{model}"
    if k is not None:
        url += f"?k={k}"
    req = urllib.request.Request(url, data=image.tobytes(), method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class _SlowEngine:
    """Delegating wrapper that makes every flush take `delay_s` — the
    overload/drain tests need a server that is slower than its arrivals
    without depending on box speed."""

    def __init__(self, engine, delay_s):
        self._engine = engine
        self.delay_s = delay_s

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def run(self, images):
        time.sleep(self.delay_s)
        return self._engine.run(images)


# ----------------------------------------------------------- engine/buckets

def test_resolve_buckets_ladder_and_validation():
    from distributed_vgg_f_tpu.serving.engine import resolve_buckets
    assert resolve_buckets((), 8) == (1, 2, 4, 8)
    assert resolve_buckets((), 6) == (1, 2, 4, 6)
    assert resolve_buckets((2, 4), 4) == (2, 4)
    with pytest.raises(ValueError, match="cover max_batch"):
        resolve_buckets((1, 2), 4)
    with pytest.raises(ValueError, match="ascending"):
        resolve_buckets((4, 2), 4)


def test_serving_config_validation():
    with pytest.raises(ValueError, match="cover max_batch"):
        ServingConfig(buckets=(1, 2), max_batch=8)
    with pytest.raises(ValueError, match="queue_limit"):
        ServingConfig(queue_limit=0)
    with pytest.raises(ValueError, match="rails"):
        ServingConfig(window_min_ms=50.0, window_max_ms=10.0,
                      max_latency_ms=50.0)
    with pytest.raises(ValueError, match="outside the controller rails"):
        ServingConfig(max_latency_ms=500.0)
    # the kill-switch default: serving exists on every config, OFF
    assert ExperimentConfig().serving.enabled is False


def test_engine_pad_slice_and_buckets():
    import jax
    engine = _tiny_engine(max_batch=4)
    assert engine.buckets == (1, 2, 4)
    imgs = _images(3)
    probs, bucket = engine.run(imgs)
    assert bucket == 4 and probs.shape == (3, 5)
    assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    # pad rows sliced away; tolerance vs the exact-geometry jit (bitwise
    # is NOT promised across geometries — that is the whole reason the
    # offline array path shares the engine)
    exact = np.asarray(jax.jit(engine._forward)(imgs))
    assert np.allclose(probs, exact, atol=1e-5)
    # exact-size group runs its own bucket
    probs2, bucket2 = engine.run(_images(2))
    assert bucket2 == 2 and probs2.shape == (2, 5)
    with pytest.raises(ValueError, match="exceeds the top bucket"):
        engine.run(_images(5))
    with pytest.raises(ValueError, match="uint8"):
        engine.validate_payload(np.zeros((32, 32, 3), np.float32))


# ---------------------------------------------------------------- admission

def test_max_latency_flush_fires_with_partial_batch():
    from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher
    engine = _tiny_engine(max_batch=8)
    batcher = DynamicBatcher(engine, max_batch=8, window_ms=120,
                             queue_limit=16)
    try:
        t0 = time.monotonic()
        pendings = [batcher.submit(img) for img in _images(3)]
        for p in pendings:
            assert p.event.wait(30)
            assert p.probs is not None and p.error is None
        elapsed = time.monotonic() - t0
        # the flush waited for the window (nobody else arrived), then ran
        # a PARTIAL batch — 3 requests, one flush, bucket 4
        assert elapsed >= 0.1
        assert {p.bucket for p in pendings} == {4}
        assert telemetry.get_registry().counter_value("serving/batches") == 1
        assert telemetry.get_registry().counter_value(
            "serving/batch_images") == 3
        assert telemetry.get_registry().counter_value(
            "serving/padded_images") == 1
    finally:
        batcher.close()


def test_max_batch_flush_fires_under_burst_before_window():
    from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher
    engine = _tiny_engine(max_batch=4)
    engine.warmup()
    # window far larger than the assertion budget: only the full-batch
    # trigger can flush this fast
    batcher = DynamicBatcher(engine, max_batch=4, window_ms=10_000,
                             queue_limit=16)
    try:
        t0 = time.monotonic()
        pendings = [batcher.submit(img) for img in _images(4)]
        for p in pendings:
            assert p.event.wait(30) and p.error is None
        assert time.monotonic() - t0 < 5.0
        assert {p.bucket for p in pendings} == {4}
    finally:
        batcher.close()


def test_overload_sheds_typed_503_bounded_queue_no_collapse():
    from distributed_vgg_f_tpu.serving.server import PredictServer
    engine = _SlowEngine(_tiny_engine(max_batch=2), delay_s=0.15)
    cfg = _serving_cfg(max_batch=2, buckets=(1, 2), max_latency_ms=5.0,
                       queue_limit=3, controller=False, warmup=False,
                       shed_retry_after_ms=25)
    server = PredictServer(cfg)
    server.add_engine(engine)
    port = server.start()
    try:
        statuses, sheds = [], []
        lock = threading.Lock()

        def post(i):
            try:
                status, payload = _post(port, "vggf", _images(1)[0])
            except urllib.error.HTTPError as e:
                status, payload = e.code, json.loads(e.read())
                if status == 503:
                    assert e.headers.get("Retry-After") is not None
            with lock:
                statuses.append(status)
                if status == 503:
                    sheds.append(payload)

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(14)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # overload split both ways: some admitted AND some shed
        assert statuses.count(200) >= 3
        assert len(sheds) >= 3
        for payload in sheds:
            # the TYPED shed payload — machine-actionable, not a timeout
            assert payload["error"] == "overloaded"
            assert payload["kind"] == "shed"
            assert payload["queue_limit"] == 3
            assert payload["queue_depth"] <= payload["queue_limit"]
            assert payload["retry_after_ms"] == 25
        assert telemetry.get_registry().counter_value("serving/shed") \
            == len(sheds)
        # the queue never exceeded its bound — shed, not stretched
        payload = server.servingz_payload()
        assert payload["models"]["vggf"]["admission"]["queue_peak"] <= 3
        # NO COLLAPSE: after the burst the server still answers promptly
        status, body = _post(port, "vggf", _images(1)[0], timeout=30)
        assert status == 200 and len(body["top_k"]) == 5
    finally:
        server.close()


def test_drain_answers_inflight_then_refuses():
    from distributed_vgg_f_tpu.serving.batcher import (DynamicBatcher,
                                                       OverloadShed)
    engine = _SlowEngine(_tiny_engine(max_batch=2), delay_s=0.1)
    batcher = DynamicBatcher(engine, max_batch=2, window_ms=30,
                             queue_limit=16)
    pendings = [batcher.submit(img) for img in _images(5)]
    batcher.close()  # blocks until drained
    for p in pendings:
        # every in-flight request was ANSWERED, not dropped
        assert p.event.is_set() and p.probs is not None and p.error is None
    with pytest.raises(OverloadShed) as err:
        batcher.submit(_images(1)[0])
    assert err.value.kind == "draining"


def test_expired_queue_entries_reaped_not_run():
    """Requests older than the reap horizon are answered with
    TimeoutError and NEVER run — under sustained overload the engine must
    not burn compute on requests whose clients already got 504."""
    from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher
    engine = _SlowEngine(_tiny_engine(max_batch=1, buckets=(1,)),
                         delay_s=0.4)
    batcher = DynamicBatcher(engine, max_batch=1, window_ms=1,
                             queue_limit=16, reap_after_s=0.2)
    try:
        pendings = [batcher.submit(img) for img in _images(4)]
        for p in pendings:
            assert p.event.wait(30)
        # the head request ran; the ones stuck behind the slow flush
        # crossed the horizon and were expired, not executed
        assert pendings[0].error is None and pendings[0].probs is not None
        reaped = [p for p in pendings if isinstance(p.error, TimeoutError)]
        assert reaped, "no queue entry was reaped past the horizon"
        assert all(p.probs is None for p in reaped)
        assert batcher.describe()["reaped_total"] == len(reaped)
    finally:
        batcher.close()


# --------------------------------------------------------------- controller

def test_controller_widens_under_pressure_and_relaxes():
    from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher
    from distributed_vgg_f_tpu.serving.controller import AdmissionController
    engine = _tiny_engine(max_batch=2)
    cfg = _serving_cfg(max_batch=2, buckets=(1, 2), max_latency_ms=10.0,
                       queue_limit=8, window_min_ms=2.0, window_max_ms=40.0,
                       controller_k_windows=2,
                       controller_cooldown_windows=0,
                       controller_relax_after_windows=2)
    batcher = DynamicBatcher(engine, max_batch=2, window_ms=10,
                             queue_limit=8)
    try:
        ctrl = AdmissionController(cfg, batcher)
        pressure = {"shed": 2, "queue_peak": 8, "latencies_ms": []}
        steady = {"shed": 0, "queue_peak": 0, "latencies_ms": []}
        assert ctrl.classify(pressure) == "queue_pressure"
        assert ctrl.classify(steady) == "steady"
        # hysteresis: one pressure window does not actuate
        rec = ctrl.observe_window(pressure)
        assert batcher.window_ms == 10 and rec["blocked"] == "hysteresis"
        # second consecutive pressure window: widen (geometric step)
        ctrl.observe_window(pressure)
        assert batcher.window_ms == 20
        # keep pressing to the rail
        for _ in range(6):
            ctrl.observe_window(pressure)
        assert batcher.window_ms == 40  # clamped at window_max_ms
        # sustained steady: relax back toward the 10ms baseline, never past
        for _ in range(12):
            ctrl.observe_window(steady)
        assert batcher.window_ms == 10
        assert telemetry.get_registry().counter_value(
            "serving/controller_actuations") >= 3
        receipt = ctrl.describe()
        assert receipt["knobs"][0]["name"] == "batch_window_ms"
        assert receipt["history"]
        # a serving crash must dump a VALID black box — the controller's
        # actuations ride the flight ring and must pass its schema
        from distributed_vgg_f_tpu.telemetry import schema
        box = flight_mod.get_flight().build_black_box(
            reason="unhandled_exception")
        assert schema.validate_flight_record(box) == []
        assert any(a["knob"] == "batch_window_ms"
                   for a in box["autotune_actuations"])
    finally:
        batcher.close()


# ------------------------------------------------- observability plane

def test_servingz_healthz_flight_and_metrics():
    from distributed_vgg_f_tpu.serving.server import PredictServer
    from distributed_vgg_f_tpu.telemetry.exporter import TelemetryExporter
    engine = _tiny_engine(max_batch=2)
    cfg = _serving_cfg(max_batch=2, buckets=(1, 2), max_latency_ms=5.0,
                       queue_limit=8, controller_interval_s=0.05,
                       warmup=False)
    exp = TelemetryExporter()
    eport = exp.start()
    # make it the process exporter so the serving heartbeat reaches it
    exporter_mod._default = exp
    server = PredictServer(cfg)
    server.add_engine(engine)
    port = server.start()
    try:
        status, _ = _post(port, "vggf", _images(1)[0])
        assert status == 200
        # two housekeeping ticks AFTER the completion: the first drains
        # the latency ring into the quantile gauges
        w0 = server._windows
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and server._windows < w0 + 2:
            time.sleep(0.02)
        # /servingz through the exporter (provider registration)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{eport}/servingz", timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] is True
        admission = payload["models"]["vggf"]["admission"]
        assert admission["queue_limit"] == 8
        assert admission["bucket_occupancy"].get("1") == 1
        assert "controller" in payload["models"]["vggf"]
        # serving heartbeat keeps /healthz a real LB health check
        with urllib.request.urlopen(
                f"http://127.0.0.1:{eport}/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["last_step"] >= 1
        # per-window summaries ride the flight recorder's ring
        windows = flight_mod.get_flight().windows()
        assert windows and windows[-1]["stall"]["verdict"] in (
            "steady", "queue_pressure")
        # serving counters + latency gauges land on /metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{eport}/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert "dvggf_serving_admitted 1" in metrics
        assert "dvggf_serving_latency_p99_ms" in metrics
        # GET /v1/models: the routing table over the descriptor receipt
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/models", timeout=10) as r:
            table = json.loads(r.read())
        assert table["models"]["vggf"]["ingest"]["wire"] == "u8"
    finally:
        server.close()
        exp.stop()
    # close() unregisters the provider (compare-and-clear)
    assert exporter_mod.serving_payload()["enabled"] is False


def test_bad_payload_and_unknown_model_are_400():
    from distributed_vgg_f_tpu.serving.server import PredictServer
    engine = _tiny_engine(max_batch=2)
    server = PredictServer(_serving_cfg(max_batch=2, buckets=(1, 2),
                                        warmup=False))
    server.add_engine(engine)
    port = server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(port, "vggf", np.zeros((8, 8, 3), np.uint8))
        assert err.value.code == 400
        assert json.loads(err.value.read())["error"] == "bad_request"
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(port, "nope", _images(1)[0])
        assert err.value.code == 400
        assert "vggf" in json.loads(err.value.read())["models"]
    finally:
        server.close()


# ------------------------------------------------------ parity + kill-switch

def _trainer(tmp_path, model_name="vggf", num_classes=5, size=32):
    import distributed_vgg_f_tpu.train.trainer as trainer_mod

    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    cfg = ExperimentConfig(
        name="serving_parity",
        model=ModelConfig(name=model_name, num_classes=num_classes,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=8),
        data=DataConfig(name="synthetic", image_size=size,
                        global_batch_size=8, num_train_examples=8),
        mesh=MeshConfig(num_data=0),
        train=TrainConfig(steps=1, seed=0,
                          checkpoint_dir=str(tmp_path / "ckpt")),
    )
    tr = trainer_mod.Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    tr.checkpoints.save(tr.init_state(), force=True)
    tr.checkpoints.wait()
    return tr


def _npy_files(tmp_path, n, size, seed=7):
    files = []
    imgs = _images(n, size=size, seed=seed)
    for i, img in enumerate(imgs):
        p = tmp_path / f"img_{i}.npy"
        np.save(p, img)
        files.append(str(p))
    return files, imgs


def _serve_parity(tr, buckets, max_batch):
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    from distributed_vgg_f_tpu.serving.server import PredictServer
    server = PredictServer(_serving_cfg(max_batch=max_batch,
                                        buckets=buckets,
                                        max_latency_ms=50.0,
                                        queue_limit=16))
    server.add_engine(PredictEngine.from_trainer(tr, buckets=buckets,
                                                 max_batch=max_batch))
    server.start()
    return server


def _assert_same_records(offline, served):
    """Bitwise: class indices identical, probabilities EXACTLY equal (both
    sides emit full precision; JSON floats round-trip exactly)."""
    assert [r["class"] for r in offline] == [r["class"] for r in served]
    assert [r["prob"] for r in offline] == [r["prob"] for r in served]


def test_server_bitwise_equals_offline_predict_vggf(tmp_path):
    from distributed_vgg_f_tpu.train.predict import run_predict
    tr = _trainer(tmp_path)
    files, imgs = _npy_files(tmp_path, 3, 32)
    # offline: the array path routes through the SAME engine machinery at
    # bucket 1 (batch=1); the server flushes sequential requests at
    # bucket 1 too — equal inputs through equal geometry
    offline = run_predict(tr, files, top_k=3, batch=1,
                          stream=io.StringIO())
    server = _serve_parity(tr, buckets=(1,), max_batch=1)
    try:
        for rec, img in zip(offline, imgs):
            status, body = _post(server.port, "vggf", img, k=3)
            assert status == 200 and body["bucket"] == 1
            _assert_same_records(rec["top_k"], body["top_k"])
    finally:
        server.close()


def test_batched_flush_bitwise_equals_offline_batch(tmp_path):
    """The grouped path: a 4-deep burst flushes as ONE bucket-4 batch and
    must equal the offline array path's bucket-4 chunk bit-for-bit.
    Submission rides the batcher directly so FIFO order is deterministic
    (HTTP thread scheduling would permute rows; cross-position equality is
    not a promise the engine makes)."""
    from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    from distributed_vgg_f_tpu.train.predict import run_predict
    tr = _trainer(tmp_path)
    files, imgs = _npy_files(tmp_path, 4, 32)
    offline = run_predict(tr, files, top_k=5, batch=4,
                          stream=io.StringIO())
    engine = PredictEngine.from_trainer(tr, buckets=(4,), max_batch=4)
    batcher = DynamicBatcher(engine, max_batch=4, window_ms=10_000,
                             queue_limit=8)
    try:
        pendings = [batcher.submit(img) for img in imgs]
        for p in pendings:
            assert p.event.wait(60) and p.error is None
        assert {p.bucket for p in pendings} == {4}
        for rec, p in zip(offline, pendings):
            from distributed_vgg_f_tpu.train.predict import top_k_records
            _assert_same_records(
                rec["top_k"], top_k_records(p.probs, 5,
                                            full_precision=True))
    finally:
        batcher.close()


@pytest.mark.slow
def test_server_bitwise_equals_offline_predict_zoo(tmp_path):
    """The acceptance grid: every zoo preset's model, server vs offline,
    bitwise."""
    from distributed_vgg_f_tpu.models.ingest import zoo_model_names
    from distributed_vgg_f_tpu.train.predict import run_predict
    for model_name in zoo_model_names():
        sub = tmp_path / model_name
        sub.mkdir()
        tr = _trainer(sub, model_name=model_name)
        files, imgs = _npy_files(sub, 2, 32)
        offline = run_predict(tr, files, top_k=3, batch=1,
                              stream=io.StringIO())
        server = _serve_parity(tr, buckets=(1,), max_batch=1)
        try:
            for rec, img in zip(offline, imgs):
                status, body = _post(server.port, model_name, img, k=3)
                assert status == 200
                _assert_same_records(rec["top_k"], body["top_k"])
        finally:
            server.close()


def test_zoo_routing_one_server_many_models(tmp_path):
    """One server fronts several descriptor rows: responses route by URL
    and each model's receipt carries ITS descriptor."""
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    from distributed_vgg_f_tpu.serving.server import PredictServer
    server = PredictServer(_serving_cfg(max_batch=2, buckets=(1, 2),
                                        warmup=False))
    for name, classes in (("vggf", 5), ("vit_s16", 7)):
        server.add_engine(_tiny_engine(name, num_classes=classes))
    server.start()
    try:
        s1, b1 = _post(server.port, "vggf", _images(1)[0], k=5)
        s2, b2 = _post(server.port, "vit_s16", _images(1, seed=3)[0], k=7)
        assert s1 == s2 == 200
        assert b1["model"] == "vggf" and len(b1["top_k"]) == 5
        assert b2["model"] == "vit_s16" and len(b2["top_k"]) == 7
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/v1/models",
                timeout=10) as r:
            table = json.loads(r.read())["models"]
        assert table["vggf"]["ingest"]["space_to_depth"] is True
        assert table["vit_s16"]["ingest"]["space_to_depth"] is False
    finally:
        server.close()


def test_kill_switch_offline_predict_never_imports_serving(tmp_path):
    """serving off (the default) leaves run_predict structurally untouched:
    a JPEG predict run must not even import the serving package."""
    pytest.importorskip("tensorflow")
    import tensorflow as tf

    from distributed_vgg_f_tpu.train.predict import run_predict
    tr = _trainer(tmp_path)
    img = _images(1, size=48, seed=2)[0]
    jpg = tmp_path / "one.jpg"
    jpg.write_bytes(tf.io.encode_jpeg(img, quality=90).numpy())
    dropped = [m for m in list(sys.modules)
               if m.startswith("distributed_vgg_f_tpu.serving")]
    for m in dropped:
        sys.modules.pop(m)
    out = run_predict(tr, [str(jpg)], top_k=3, stream=io.StringIO())
    assert len(out) == 1
    assert not any(m.startswith("distributed_vgg_f_tpu.serving")
                   for m in sys.modules), \
        "offline JPEG predict imported the serving package — the " \
        "kill-switch is no longer structural"


def test_cli_serve_requires_explicit_enable(tmp_path):
    import train as train_cli
    with pytest.raises(SystemExit, match="serving is disabled"):
        train_cli.main([
            "--config", "vggf_cifar10_smoke", "--mode", "serve",
            "--set", f"train.checkpoint_dir={tmp_path / 'none'}",
        ])


# -------------------------------------------------------- sentinel/schema

def _serving_artifact(value=200.0, **row_overrides):
    from distributed_vgg_f_tpu.telemetry import schema
    row = {
        "layout": "openloop", "mode": "serving_bench",
        "serving_mode": "openloop_b8", "model": "vggf", "wire": "u8",
        "space_to_depth": False, "image_dtype": "float32",
        "wire_bytes_per_image": 128 * 128 * 3,
        "source": {"source_kind": "u8_payload", "source_hw": [128, 128]},
        "admitted_rps": value, "spread": 0.05, "queue_peak": 30,
        "serving": {"buckets": [1, 2, 4, 8], "max_batch": 8,
                    "window_ms": 20.0, "queue_limit": 32,
                    "controller": False},
        "stages": [
            {"offered_rps": 100.0, "duration_s": 6.0, "admitted_rps": 99.0,
             "shed_rate": 0.0, "p50_ms": 20.0, "p95_ms": 30.0,
             "p99_ms": 40.0},
            {"offered_rps": 400.0, "duration_s": 6.0,
             "admitted_rps": value, "shed_rate": 0.4, "p50_ms": 60.0,
             "p95_ms": 90.0, "p99_ms": 120.0},
        ],
    }
    row.update(row_overrides)
    return {"schema_version": schema.SCHEMA_VERSION,
            "metric": "serving_admitted_rps", "value": value,
            "layouts": [row]}


def test_serving_artifact_schema_accepts_and_rejects():
    from distributed_vgg_f_tpu.telemetry import schema
    assert schema.validate_bench_artifact(_serving_artifact()) == []
    bad = schema.validate_bench_artifact(
        _serving_artifact(serving_mode="dynamic"))
    assert any("serving_mode" in e for e in bad)
    art = _serving_artifact()
    art["layouts"][0]["stages"][0]["shed_rate"] = 1.5
    assert any("shed_rate" in e
               for e in schema.validate_bench_artifact(art))
    art = _serving_artifact()
    art["layouts"][0]["stages"][0].update(p50_ms=50.0, p99_ms=10.0)
    assert any("quantiles not ordered" in e
               for e in schema.validate_bench_artifact(art))
    art = _serving_artifact(queue_peak=99)
    assert any("queue_limit" in e
               for e in schema.validate_bench_artifact(art))


def test_serving_basis_key_and_defaults():
    from distributed_vgg_f_tpu.telemetry.regress import Basis, row_basis
    row = _serving_artifact()["layouts"][0]
    basis = row_basis(row)
    assert basis.serving == "openloop_b8" and basis.model == "vggf"
    # pre-r17 decode rows keep their committed key: serving defaults off
    assert Basis("u8", True, "noise", (320, 256), True).serving == "off"


def test_serving_receipts_are_sentinel_gated():
    """The committed open-loop receipts back SERVING_RPS_R14: the chain
    passes check_committed, the trajectory carries a serving section, and
    a new artifact on the serving basis gates against the pin (below the
    tolerance floor -> REGRESSION)."""
    import os

    from distributed_vgg_f_tpu.telemetry import regress
    from distributed_vgg_f_tpu.utils import scaling_model
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert regress.check_committed(repo) == []
    trajectory = regress.build_trajectory(repo)
    (serving_round,) = trajectory["serving"]
    assert serving_round["pin"] == "SERVING_RPS_R14"
    assert serving_round["value"] == scaling_model.SERVING_RPS_R14 > 0
    assert any(a["pin_provenance"] for a in serving_round["artifacts"])
    # at the pin: green
    ok = _serving_artifact(value=scaling_model.SERVING_RPS_R14)
    errors, report = regress.check_artifact(ok, repo)
    assert errors == [] and report["pin"] == "SERVING_RPS_R14"
    # far below the floor: REGRESSION
    bad = _serving_artifact(value=scaling_model.SERVING_RPS_R14 * 0.5)
    errors, report = regress.check_artifact(bad, repo)
    assert any("REGRESSION" in e for e in errors)
    # measured with the admission controller steering the window: refused
    # outright (the decode chain's mid-autotune discipline)
    moving = _serving_artifact(value=scaling_model.SERVING_RPS_R14)
    moving["layouts"][0]["serving"]["controller"] = True
    errors, report = regress.check_artifact(moving, repo)
    assert any("REFUSED" in e and "controller" in e for e in errors)
