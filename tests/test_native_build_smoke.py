"""Native-build smoke gate: the checked-in .so binaries must never drift
from their sources. The test recompiles all three libraries from
native/Makefile into a scratch dir (the repo copies stay untouched) and
verifies each fresh build dlopens with the ABI version its Python binding
expects — combined with the bindings' load-time ABI gate, a source edit
that doesn't build, or an ABI bump that misses a binding, fails HERE
instead of silently shipping a stale binary.

Also proves the compiled-out configurations stand alone — each one
independently: jpeg_loader.cc built with -DDVGGF_NO_SIMD must report
simd_supported()==0 and still decode (the scalar fallback is a real build,
not dead code), built with -DDVGGF_NO_SCALED must report
scaled_supported()==0 and still decode at full resolution (the r7
scaled+partial machinery is severable), and built with -DDVGGF_NO_WIRE_U8
must report wire_u8_supported()==0, REFUSE the u8 output kind (rc=2 /
null handle — the fallback is a format decision made above the ABI), and
still run the host-normalize wires byte-identically (the r8 u8 wire is
severable), and built with -DDVGGF_NO_RESTART must report
restart_supported()==0, decode marker-bearing streams byte-identically
through the sequential entropy path, and still export the lossless
re-encode transcoder (the r9 restart machinery is severable). The runtime
kill-switch env vars (DVGGF_DECODE_SIMD=0 / DVGGF_DECODE_SCALED=0 /
DVGGF_WIRE_U8=0 / DVGGF_DECODE_RESTART=0) are asserted in fresh
subprocesses, because every dispatch resolves once per process.
"""

import ctypes
import os
import shutil
import subprocess

import pytest

from distributed_vgg_f_tpu.data.native_build import (  # noqa: E402
    NATIVE_DIR,
    toolchain_missing,
)
from distributed_vgg_f_tpu.data.native_jpeg import JPEG_ABI_VERSION

_reason = toolchain_missing()
if _reason is None and shutil.which("make") is None:
    _reason = "make not on PATH"
if _reason is not None:  # pragma: no cover — toolchain exists in CI image
    pytest.skip(f"native toolchain unavailable: {_reason}",
                allow_module_level=True)

# (library, ABI symbol, version the binding pins)
LIBS = [
    ("libdvgg_data.so", "dvgg_abi_version", 1),
    ("libdvgg_jpeg.so", "dvgg_jpeg_loader_abi_version", JPEG_ABI_VERSION),
    ("libdvgg_tfrecord.so", "dvgg_tfrecord_index_abi_version", 1),
]


@pytest.fixture(scope="module")
def build_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("native_build")
    for name in os.listdir(NATIVE_DIR):
        if name.endswith(".cc") or name == "Makefile":
            shutil.copy2(os.path.join(NATIVE_DIR, name), d / name)
    return d


def test_make_rebuilds_all_libraries(build_dir):
    out = subprocess.run(["make", "-C", str(build_dir)],
                         capture_output=True, timeout=300)
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    for so_name, symbol, expected in LIBS:
        path = build_dir / so_name
        assert path.exists(), f"{so_name} not produced by make"
        lib = ctypes.CDLL(str(path))
        fn = getattr(lib, symbol)
        fn.restype = ctypes.c_int64
        fn.argtypes = []
        assert int(fn()) == expected, (
            f"fresh {so_name} reports ABI {int(fn())}, binding expects "
            f"{expected} — source and binding drifted")


def _build_jpeg_variant(build_dir, tmp_path, define: str | None,
                        so_name: str):
    so = tmp_path / so_name
    out = subprocess.run(
        ["g++", "-O3", "-fPIC", "-std=c++17", "-Wall", "-pthread", "-shared",
         *([define] if define else []), "-o", str(so),
         str(build_dir / "jpeg_loader.cc"), "-ljpeg", "-ldl"],
        capture_output=True, timeout=300)
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    return so


def _decode_eval_32(lib, data, np):
    """Decode `data` to a 32x32 eval crop through a raw ctypes handle."""
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.dvgg_jpeg_decode_single.restype = ctypes.c_int
    lib.dvgg_jpeg_decode_single.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, f32p, f32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, ctypes.c_uint64, ctypes.c_void_p]
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    out_img = np.empty((32, 32, 3), np.float32)
    rc = lib.dvgg_jpeg_decode_single(
        data, len(data), 32, mean.ctypes.data_as(f32p),
        std.ctypes.data_as(f32p), 0, 0, 1, 1, 0.08, 1.0, 0,
        out_img.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    return out_img


def _test_jpeg(np):
    import io
    from PIL import Image
    rng = np.random.default_rng(0)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 256, size=(48, 52, 3))
                    .astype(np.uint8)).save(buf, "JPEG", quality=90)
    return buf.getvalue()


def test_jpeg_loader_builds_and_decodes_without_simd(build_dir, tmp_path):
    """-DDVGGF_NO_SIMD: the scalar-only build (non-x86 hosts, or AVX2
    compiled out) must build green and decode correctly on its own."""
    np = pytest.importorskip("numpy")
    pytest.importorskip("PIL.Image")
    so = _build_jpeg_variant(build_dir, tmp_path, "-DDVGGF_NO_SIMD",
                             "libdvgg_jpeg_nosimd.so")
    lib = ctypes.CDLL(str(so))
    lib.dvgg_jpeg_simd_supported.restype = ctypes.c_int
    lib.dvgg_jpeg_simd_kind.restype = ctypes.c_int
    lib.dvgg_jpeg_scaled_supported.restype = ctypes.c_int
    assert lib.dvgg_jpeg_simd_supported() == 0
    assert lib.dvgg_jpeg_simd_kind() == 0  # scalar, with nothing to enable
    assert lib.dvgg_jpeg_scaled_supported() == 1  # independent of SIMD

    data = _test_jpeg(np)
    out_img = _decode_eval_32(lib, data, np)
    assert float(np.abs(out_img).sum()) > 0  # decoded real pixels

    # the no-SIMD build's scalar math must equal the in-repo scalar path:
    # one algorithm, however compiled
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    from distributed_vgg_f_tpu.data.native_jpeg import (
        decode_single_image, load_native_jpeg, set_simd, simd_kind)
    if load_native_jpeg() is not None:
        before = simd_kind()
        try:
            set_simd(False)
            ref = decode_single_image(data, 32, mean, std, eval_mode=True)
        finally:
            set_simd(before != "scalar")
        np.testing.assert_array_equal(ref, out_img)


def test_jpeg_loader_builds_and_decodes_without_scaled(build_dir, tmp_path):
    """-DDVGGF_NO_SCALED (independently of -DDVGGF_NO_SIMD): the
    full-resolution-only build must build green, report the scaled path
    absent (and un-enableable), and still decode — pixel-identical to the
    in-repo build with the scaled path switched off, since full decode is
    the byte-parity anchor."""
    np = pytest.importorskip("numpy")
    pytest.importorskip("PIL.Image")
    so = _build_jpeg_variant(build_dir, tmp_path, "-DDVGGF_NO_SCALED",
                             "libdvgg_jpeg_noscaled.so")
    lib = ctypes.CDLL(str(so))
    for sym in ("dvgg_jpeg_scaled_supported", "dvgg_jpeg_scaled_kind",
                "dvgg_jpeg_set_scaled", "dvgg_jpeg_partial_supported",
                "dvgg_jpeg_simd_supported"):
        getattr(lib, sym).restype = ctypes.c_int
    assert lib.dvgg_jpeg_scaled_supported() == 0
    assert lib.dvgg_jpeg_scaled_kind() == 0
    assert lib.dvgg_jpeg_set_scaled(1) == 0   # nothing to enable
    assert lib.dvgg_jpeg_partial_supported() == 0  # dlsym probe compiled out
    assert lib.dvgg_jpeg_simd_supported() in (0, 1)  # SIMD untouched

    data = _test_jpeg(np)
    out_img = _decode_eval_32(lib, data, np)
    assert float(np.abs(out_img).sum()) > 0

    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    from distributed_vgg_f_tpu.data.native_jpeg import (
        decode_single_image, load_native_jpeg, scaled_kind, set_scaled)
    if load_native_jpeg() is not None:
        before = scaled_kind()
        try:
            set_scaled(False)
            ref = decode_single_image(data, 32, mean, std, eval_mode=True)
        finally:
            set_scaled(before == "scaled")
        np.testing.assert_array_equal(ref, out_img)


def test_jpeg_loader_builds_and_decodes_without_wire_u8(build_dir, tmp_path):
    """-DDVGGF_NO_WIRE_U8 (independently of the other two defines): the
    host-normalize-only build must build green, report the u8 wire absent
    (and un-enableable), refuse the u8 output kind, and keep the f32 wire
    byte-identical to the in-repo build — the u8 machinery is purely
    additive."""
    np = pytest.importorskip("numpy")
    pytest.importorskip("PIL.Image")
    so = _build_jpeg_variant(build_dir, tmp_path, "-DDVGGF_NO_WIRE_U8",
                             "libdvgg_jpeg_nowireu8.so")
    lib = ctypes.CDLL(str(so))
    for sym in ("dvgg_jpeg_wire_u8_supported", "dvgg_jpeg_wire_u8_kind",
                "dvgg_jpeg_set_wire_u8", "dvgg_jpeg_simd_supported",
                "dvgg_jpeg_scaled_supported"):
        getattr(lib, sym).restype = ctypes.c_int
    lib.dvgg_jpeg_set_wire_u8.argtypes = [ctypes.c_int]
    assert lib.dvgg_jpeg_wire_u8_supported() == 0
    assert lib.dvgg_jpeg_wire_u8_kind() == 0
    assert lib.dvgg_jpeg_set_wire_u8(1) == 0   # nothing to enable
    assert lib.dvgg_jpeg_simd_supported() in (0, 1)   # others untouched
    assert lib.dvgg_jpeg_scaled_supported() == 1

    data = _test_jpeg(np)
    out_img = _decode_eval_32(lib, data, np)   # host f32 wire stands alone
    assert float(np.abs(out_img).sum()) > 0

    # the u8 output kind (out_kind=2) is REFUSED with rc=2, not absorbed
    f32p = ctypes.POINTER(ctypes.c_float)
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    u8_out = np.empty((32, 32, 3), np.uint8)
    rc = lib.dvgg_jpeg_decode_single(
        data, len(data), 32, mean.ctypes.data_as(f32p),
        std.ctypes.data_as(f32p), 2, 0, 1, 1, 0.08, 1.0, 0,
        u8_out.ctypes.data_as(ctypes.c_void_p))
    assert rc == 2

    # f32 byte-parity with the in-repo (wire-capable) build: compiling the
    # wire OUT must not perturb the host-normalize numerics
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    from distributed_vgg_f_tpu.data.native_jpeg import (
        decode_single_image, load_native_jpeg)
    if load_native_jpeg() is not None:
        ref = decode_single_image(data, 32, mean, std, eval_mode=True)
        np.testing.assert_array_equal(ref, out_img)


def test_jpeg_loader_builds_and_decodes_without_restart(build_dir, tmp_path):
    """-DDVGGF_NO_RESTART (independently of the other defines): the
    sequential-entropy-only build must build green, report the restart
    path absent (and un-enableable), keep zeroed restart stats, still
    decode — pixel-identical to the in-repo build with restart switched
    off — and still export the lossless re-encode transcoder (encode-side
    machinery, deliberately outside the compile-out)."""
    np = pytest.importorskip("numpy")
    pytest.importorskip("PIL.Image")
    so = _build_jpeg_variant(build_dir, tmp_path, "-DDVGGF_NO_RESTART",
                             "libdvgg_jpeg_norestart.so")
    lib = ctypes.CDLL(str(so))
    for sym in ("dvgg_jpeg_restart_supported", "dvgg_jpeg_restart_kind",
                "dvgg_jpeg_set_restart", "dvgg_jpeg_restart_fanout",
                "dvgg_jpeg_set_restart_fanout", "dvgg_jpeg_simd_supported",
                "dvgg_jpeg_scaled_supported"):
        getattr(lib, sym).restype = ctypes.c_int
    lib.dvgg_jpeg_set_restart.argtypes = [ctypes.c_int]
    assert lib.dvgg_jpeg_restart_supported() == 0
    assert lib.dvgg_jpeg_restart_kind() == 0
    assert lib.dvgg_jpeg_set_restart(1) == 0   # nothing to enable
    assert lib.dvgg_jpeg_scaled_supported() == 1   # others untouched
    stats = (ctypes.c_int64 * 16)()
    lib.dvgg_jpeg_restart_stats.argtypes = [
        ctypes.POINTER(ctypes.c_int64)]
    lib.dvgg_jpeg_restart_stats(stats)
    assert all(int(v) == 0 for v in stats)

    # decodes marker-bearing bytes byte-identically to the in-repo build
    # with the restart path switched off (sequential is the anchor)
    data = _test_jpeg(np)
    lib.dvgg_jpeg_reencode_restart.restype = ctypes.c_int64
    lib.dvgg_jpeg_reencode_restart.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p,
        ctypes.c_int64]
    cap = len(data) * 2 + 65536
    buf = ctypes.create_string_buffer(cap)
    rc = lib.dvgg_jpeg_reencode_restart(data, len(data), 0, buf, cap)
    assert rc > 0   # the transcoder works on the NO_RESTART build
    marked = buf.raw[:rc]
    out_img = _decode_eval_32(lib, marked, np)
    assert float(np.abs(out_img).sum()) > 0

    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    from distributed_vgg_f_tpu.data.native_jpeg import (
        decode_single_image, load_native_jpeg, restart_kind, set_restart)
    if load_native_jpeg() is not None:
        before = restart_kind()
        try:
            set_restart(False)
            ref = decode_single_image(marked, 32, mean, std, eval_mode=True)
        finally:
            set_restart(before == "restart")
        np.testing.assert_array_equal(ref, out_img)


def test_v7_abi_exports_present():
    """The v6 wire_u8 triple, the v7 restart surface, and the v8 resize
    surface must exist on the in-repo build — a binding regression (or a
    stale .so) fails here by name."""
    lib = load_native_jpeg_or_skip()
    for sym in ("dvgg_jpeg_wire_u8_supported", "dvgg_jpeg_wire_u8_kind",
                "dvgg_jpeg_set_wire_u8", "dvgg_jpeg_restart_supported",
                "dvgg_jpeg_restart_kind", "dvgg_jpeg_set_restart",
                "dvgg_jpeg_restart_fanout", "dvgg_jpeg_set_restart_fanout",
                "dvgg_jpeg_restart_stats", "dvgg_jpeg_restart_stats_reset",
                "dvgg_jpeg_reencode_restart",
                "dvgg_jpeg_resize_supported", "dvgg_jpeg_resize_kind",
                "dvgg_jpeg_set_resize", "dvgg_jpeg_loader_set_threads",
                "dvgg_jpeg_loader_num_threads",
                "dvgg_jpeg_loader_set_hflip", "dvgg_jpeg_loader_hflip"):
        assert hasattr(lib, sym), f"v6/v7/v8/v9 ABI export {sym} missing"


def test_jpeg_loader_builds_without_resize(build_dir, tmp_path):
    """-DDVGGF_NO_RESIZE (independently of the other defines): the
    fixed-pool build must build green, report resize absent (and
    un-enableable), and still decode — the r11 grow/shrink machinery is
    severable, and the loader keeps its creation-time worker count for
    life (the Python binding reads set_num_threads -> -1 as 'knob
    unavailable')."""
    np = pytest.importorskip("numpy")
    pytest.importorskip("PIL.Image")
    so = _build_jpeg_variant(build_dir, tmp_path, "-DDVGGF_NO_RESIZE",
                             "libdvgg_jpeg_noresize.so")
    lib = ctypes.CDLL(str(so))
    for sym in ("dvgg_jpeg_resize_supported", "dvgg_jpeg_resize_kind",
                "dvgg_jpeg_set_resize", "dvgg_jpeg_simd_supported",
                "dvgg_jpeg_scaled_supported"):
        getattr(lib, sym).restype = ctypes.c_int
    lib.dvgg_jpeg_set_resize.argtypes = [ctypes.c_int]
    assert lib.dvgg_jpeg_resize_supported() == 0
    assert lib.dvgg_jpeg_resize_kind() == 0
    assert lib.dvgg_jpeg_set_resize(1) == 0   # nothing to enable
    assert lib.dvgg_jpeg_scaled_supported() == 1   # others untouched
    # set_threads on ANY handle refuses on this build (null handle probes
    # the dispatch gate without constructing a loader)
    lib.dvgg_jpeg_loader_set_threads.restype = ctypes.c_int
    lib.dvgg_jpeg_loader_set_threads.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_int]
    assert lib.dvgg_jpeg_loader_set_threads(None, 4) == -1

    data = _test_jpeg(np)
    out_img = _decode_eval_32(lib, data, np)
    assert float(np.abs(out_img).sum()) > 0


def load_native_jpeg_or_skip():
    from distributed_vgg_f_tpu.data.native_jpeg import load_native_jpeg
    lib = load_native_jpeg()
    if lib is None:
        pytest.skip("native jpeg loader unavailable")
    return lib


@pytest.fixture(scope="module")
def default_jpeg_so(build_dir, tmp_path_factory):
    """One default-flags build shared by every kill-switch case — the two
    env-var cases probe the SAME artifact, so compiling it per case would
    just burn tier-1 budget."""
    return _build_jpeg_variant(build_dir, tmp_path_factory.mktemp("killsw"),
                               None, "libdvgg_jpeg_default.so")


@pytest.mark.parametrize("env_var,kind_symbol", [
    ("DVGGF_DECODE_SIMD", "dvgg_jpeg_simd_kind"),
    ("DVGGF_DECODE_SCALED", "dvgg_jpeg_scaled_kind"),
    ("DVGGF_WIRE_U8", "dvgg_jpeg_wire_u8_kind"),
    ("DVGGF_DECODE_RESTART", "dvgg_jpeg_restart_kind"),
    ("DVGGF_THREAD_RESIZE", "dvgg_jpeg_resize_kind"),
])
def test_kill_switch_env_vars_honored(default_jpeg_so, env_var, kind_symbol):
    """DVGGF_DECODE_SIMD=0 / DVGGF_DECODE_SCALED=0 must pin their dispatch
    at first use. Each probe runs in a FRESH interpreter because both kinds
    resolve once per process (sticky atomics)."""
    import sys
    so = default_jpeg_so
    probe = (f"import ctypes; lib = ctypes.CDLL({str(so)!r}); "
             f"print('kind=%d' % lib.{kind_symbol}())")
    for value, expect_zero in (("0", True), ("1", False)):
        out = subprocess.run([sys.executable, "-c", probe],
                             env={**os.environ, env_var: value},
                             capture_output=True, timeout=120, text=True)
        assert out.returncode == 0, out.stderr[-2000:]
        kind = int(out.stdout.strip().split("=")[1])
        if expect_zero:
            assert kind == 0, (env_var, value, out.stdout)
        else:
            # not forced off: the library's own capability decides (scalar
            # CPUs legitimately report 0 for SIMD)
            assert kind in (0, 1)


def test_partial_decode_probe_reports_reason():
    """The dlsym probe must resolve on this image's libjpeg-turbo; on a
    plain-libjpeg host the partial path reports unavailable and the scaled
    tests skip WITH that reason rather than silently passing on the
    fallback (the skip text names the missing symbol)."""
    from distributed_vgg_f_tpu.data.native_jpeg import (
        load_native_jpeg, partial_supported, scaled_supported)
    if load_native_jpeg() is None:
        pytest.skip("native jpeg loader unavailable")
    if not scaled_supported():
        pytest.skip("scaled decode compiled out (-DDVGGF_NO_SCALED)")
    if not partial_supported():
        pytest.skip("libjpeg lacks jpeg_crop_scanline/jpeg_skip_scanlines "
                    "(not libjpeg-turbo?) — partial decode rides the "
                    "full-decode fallback on this host")
    assert partial_supported() is True
