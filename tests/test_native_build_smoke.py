"""Native-build smoke gate: the checked-in .so binaries must never drift
from their sources. The test recompiles all three libraries from
native/Makefile into a scratch dir (the repo copies stay untouched) and
verifies each fresh build dlopens with the ABI version its Python binding
expects — combined with the bindings' load-time ABI gate, a source edit
that doesn't build, or an ABI bump that misses a binding, fails HERE
instead of silently shipping a stale binary.

Also proves the SIMD-compiled-out configuration stands alone: jpeg_loader.cc
built with -DDVGGF_NO_SIMD must report simd_supported()==0 and still decode
— the scalar fallback is a real build, not dead code.
"""

import ctypes
import os
import shutil
import subprocess

import pytest

from distributed_vgg_f_tpu.data.native_build import (  # noqa: E402
    NATIVE_DIR,
    toolchain_missing,
)
from distributed_vgg_f_tpu.data.native_jpeg import JPEG_ABI_VERSION

_reason = toolchain_missing()
if _reason is None and shutil.which("make") is None:
    _reason = "make not on PATH"
if _reason is not None:  # pragma: no cover — toolchain exists in CI image
    pytest.skip(f"native toolchain unavailable: {_reason}",
                allow_module_level=True)

# (library, ABI symbol, version the binding pins)
LIBS = [
    ("libdvgg_data.so", "dvgg_abi_version", 1),
    ("libdvgg_jpeg.so", "dvgg_jpeg_loader_abi_version", JPEG_ABI_VERSION),
    ("libdvgg_tfrecord.so", "dvgg_tfrecord_index_abi_version", 1),
]


@pytest.fixture(scope="module")
def build_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("native_build")
    for name in os.listdir(NATIVE_DIR):
        if name.endswith(".cc") or name == "Makefile":
            shutil.copy2(os.path.join(NATIVE_DIR, name), d / name)
    return d


def test_make_rebuilds_all_libraries(build_dir):
    out = subprocess.run(["make", "-C", str(build_dir)],
                         capture_output=True, timeout=300)
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    for so_name, symbol, expected in LIBS:
        path = build_dir / so_name
        assert path.exists(), f"{so_name} not produced by make"
        lib = ctypes.CDLL(str(path))
        fn = getattr(lib, symbol)
        fn.restype = ctypes.c_int64
        fn.argtypes = []
        assert int(fn()) == expected, (
            f"fresh {so_name} reports ABI {int(fn())}, binding expects "
            f"{expected} — source and binding drifted")


def test_jpeg_loader_builds_and_decodes_without_simd(build_dir, tmp_path):
    """-DDVGGF_NO_SIMD: the scalar-only build (non-x86 hosts, or AVX2
    compiled out) must build green and decode correctly on its own."""
    so = tmp_path / "libdvgg_jpeg_nosimd.so"
    out = subprocess.run(
        ["g++", "-O3", "-fPIC", "-std=c++17", "-Wall", "-pthread", "-shared",
         "-DDVGGF_NO_SIMD", "-o", str(so),
         str(build_dir / "jpeg_loader.cc"), "-ljpeg"],
        capture_output=True, timeout=300)
    assert out.returncode == 0, out.stderr.decode(errors="replace")[-2000:]
    lib = ctypes.CDLL(str(so))
    lib.dvgg_jpeg_simd_supported.restype = ctypes.c_int
    lib.dvgg_jpeg_simd_kind.restype = ctypes.c_int
    assert lib.dvgg_jpeg_simd_supported() == 0
    assert lib.dvgg_jpeg_simd_kind() == 0  # scalar, with nothing to enable

    np = pytest.importorskip("numpy")
    pil = pytest.importorskip("PIL.Image")
    import io
    rng = np.random.default_rng(0)
    buf = io.BytesIO()
    pil.fromarray(rng.integers(0, 256, size=(48, 52, 3)).astype(np.uint8)) \
        .save(buf, "JPEG", quality=90)
    data = buf.getvalue()
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.dvgg_jpeg_decode_single.restype = ctypes.c_int
    lib.dvgg_jpeg_decode_single.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, f32p, f32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_double, ctypes.c_uint64, ctypes.c_void_p]
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    std = np.array([58.393, 57.12, 57.375], np.float32)
    out_img = np.empty((32, 32, 3), np.float32)
    rc = lib.dvgg_jpeg_decode_single(
        data, len(data), 32, mean.ctypes.data_as(f32p),
        std.ctypes.data_as(f32p), 0, 0, 1, 0.08, 1.0, 0,
        out_img.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    assert float(np.abs(out_img).sum()) > 0  # decoded real pixels

    # the no-SIMD build's scalar math must equal the in-repo scalar path:
    # one algorithm, however compiled
    from distributed_vgg_f_tpu.data.native_jpeg import (
        decode_single_image, load_native_jpeg, set_simd, simd_kind)
    if load_native_jpeg() is not None:
        before = simd_kind()
        try:
            set_simd(False)
            ref = decode_single_image(data, 32, mean, std, eval_mode=True)
        finally:
            set_simd(before != "scalar")
        np.testing.assert_array_equal(ref, out_img)
