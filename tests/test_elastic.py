"""Live elastic resize (r19, parallel/elastic.py + trainer integration):
when the preemption consensus fires for k of N data shards, survivors form
a shrunken mesh, reshard params/opt-state in place, and take over the data
stream via the r18 cursor blob — loss trajectory pinned EQUAL to a
restart-from-checkpoint control on the same survivor count, zero replayed
batches. `mesh.elastic.enabled=false` (the default) is pinned structurally
identical to the r18 checkpoint-and-stop path, and every refused resize
degrades to that path under the named `elastic_degraded_restart` flight
class — never `unhandled_exception`."""

import dataclasses
import io
import json
import os

import jax
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ElasticConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.parallel import elastic
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.resilience.errors import (
    ElasticDegraded,
    GeometryReceiptError,
)
from distributed_vgg_f_tpu.resilience.faults import FaultPlan
from distributed_vgg_f_tpu.telemetry import schema
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger

# global_batch 12 divides every survivor count this grid produces
# (4, 3, 2) — keep_global's divisibility precondition by construction.
BATCH = 12
STEPS = 5
PREEMPT_AT = 2  # completed step after which the consensus fires


def _cfg(ckpt_dir, *, zero1=False, zero2=False, bucket_mb=0.0,
         elastic_on=True, policy="keep_global", faults="",
         steps=STEPS) -> ExperimentConfig:
    return ExperimentConfig(
        name="elastic_test",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=BATCH,
                          momentum=0.9, weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=BATCH, num_train_examples=4 * BATCH),
        mesh=MeshConfig(num_data=0, shard_opt_state=zero1,
                        shard_gradients=zero2, comm_bucket_mb=bucket_mb,
                        elastic=ElasticConfig(enabled=elastic_on,
                                              batch_policy=policy)),
        train=TrainConfig(steps=steps, seed=0, log_every=1,
                          checkpoint_dir=str(ckpt_dir),
                          checkpoint_every_steps=100,
                          eval_every_steps=10_000,
                          fault_injection=faults),
    )


def _mesh(n: int):
    return build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])


def _run_fit(cfg, mesh_size: int):
    """fit() to completion with a JSONL log; returns (records, state)."""
    stream = io.StringIO()
    logger = MetricLogger(stream=io.StringIO())
    logger._file = stream  # capture the machine-readable JSONL records
    trainer = Trainer(cfg, mesh=_mesh(mesh_size), logger=logger)
    state = trainer.fit()
    records = [json.loads(ln) for ln in stream.getvalue().splitlines()]
    return records, state, trainer


def _losses(records) -> dict:
    return {r["step"]: r["loss"] for r in records if r.get("event") == "train"}


def _events(records, name) -> list:
    return [r for r in records if r.get("event") == name]


# ---------------------------------------------------------------------------
# fault-token grammar: preempt@rankR[+R2...]:N
# ---------------------------------------------------------------------------

def test_rank_token_parses():
    plan = FaultPlan.parse("preempt@rank0+2:5")
    assert plan.preempt_step == 5
    assert plan.preempt_ranks == (0, 2)
    assert plan.preempt_now(5) and not plan.preempt_now(4)
    # untargeted preempt keeps an empty rank set (the r18 shape)
    assert FaultPlan.parse("preempt@7").preempt_ranks == ()


def test_rank_token_rejects_malformed():
    with pytest.raises(ValueError, match="duplicate rank"):
        FaultPlan.parse("preempt@rank1+1:3")
    with pytest.raises(ValueError, match="duplicate 'preempt'"):
        FaultPlan.parse("preempt@2,preempt@rank1:3")
    with pytest.raises(ValueError, match="preempt@rankR"):
        FaultPlan.parse("preempt@rank:3")


# ---------------------------------------------------------------------------
# plan_resize: every refusal is a typed, machine-readable degradation
# ---------------------------------------------------------------------------

def _plan(dead, *, n=4, policy="keep_global", batch=BATCH, cursor=True,
          min_survivors=2):
    return elastic.plan_resize(
        _mesh(n), "data", dead,
        elastic_cfg=ElasticConfig(enabled=True, batch_policy=policy,
                                  min_survivors=min_survivors),
        global_batch=batch, have_cursor=cursor)


def test_plan_resize_happy_path():
    plan = _plan((1, 3))
    assert (plan.old_size, plan.new_size) == (4, 2)
    assert plan.topology_label == "elastic_4to2"
    assert plan.lr_scale == 1.0
    assert elastic.survivor_ranks(plan) == (0, 2)


def test_plan_resize_degradations():
    cases = [
        (dict(dead=()), "unidentified_ranks"),
        (dict(dead=(4,)), "rank_out_of_range"),
        (dict(dead=(0, 1, 2)), "too_few_survivors"),  # all-but-one dead
        (dict(dead=(1,), batch=10), "indivisible_global_batch"),
        (dict(dead=(1,), cursor=False), "no_resumable_ingest"),
    ]
    for kwargs, reason in cases:
        with pytest.raises(ElasticDegraded) as exc:
            _plan(**kwargs)
        assert exc.value.reason == reason, kwargs


def test_shrink_mesh_preserves_survivor_order(devices8):
    plan = _plan((1,))
    small = elastic.shrink_mesh(_mesh(4), "data", plan)
    assert small.shape["data"] == 3
    assert list(small.devices.ravel()) == [devices8[0], devices8[2],
                                           devices8[3]]


# ---------------------------------------------------------------------------
# kill-switch: mesh.elastic.enabled=false IS the r18 stop path
# ---------------------------------------------------------------------------

def test_kill_switch_off_is_r18_stop_path(tmp_path):
    """With `mesh.elastic.enabled` false (the default), a rank-targeted
    preemption behaves exactly like the untargeted r18 `preempt@N`:
    checkpoint, preempt event, stop — same stop step, same final state, no
    elastic events in the stream."""
    cfg_ranked = _cfg(tmp_path / "a", elastic_on=False,
                      faults=f"preempt@rank1:{PREEMPT_AT}")
    cfg_plain = _cfg(tmp_path / "b", elastic_on=False,
                     faults=f"preempt@{PREEMPT_AT}")
    rec_a, state_a, _ = _run_fit(cfg_ranked, 4)
    rec_b, state_b, _ = _run_fit(cfg_plain, 4)
    for recs in (rec_a, rec_b):
        (pre,) = _events(recs, "preempt")
        assert pre["step"] == PREEMPT_AT
        assert not _events(recs, "elastic_resize")
        assert not _events(recs, "elastic_degraded")
        assert all("elastic" not in r for r in recs
                   if r.get("event") == "train")
    assert int(jax.device_get(state_a.step)) == PREEMPT_AT
    for a, b in zip(jax.tree.leaves(jax.device_get(state_a.params)),
                    jax.tree.leaves(jax.device_get(state_b.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# the chaos grid: resize-and-continue == restart-from-checkpoint control
# ---------------------------------------------------------------------------

def _restart_control(cfg, survivors: int):
    """The r18 path the elastic trajectory is pinned against: run with the
    same preemption but elastic OFF (checkpoint + stop), then restart a
    fresh trainer on the survivor mesh from that checkpoint. `cfg` must
    carry its OWN checkpoint dir — the elastic run's final save would
    otherwise pre-seed the stop run past the preemption point."""
    off = dataclasses.replace(
        cfg, mesh=dataclasses.replace(
            cfg.mesh, elastic=ElasticConfig(enabled=False)))
    rec_stop, _, _ = _run_fit(off, 4)
    (pre,) = _events(rec_stop, "preempt")
    assert pre["step"] == PREEMPT_AT and pre["checkpointed"]
    resumed = dataclasses.replace(
        off, train=dataclasses.replace(off.train, fault_injection=""))
    return _run_fit(resumed, survivors)


# The default (tier-1) loop runs the two extremes of the grid: plain dp
# with k=1 (the cheapest cell) and bucketed zero2 with k=2 (every
# converter stage — retopology + bucket receipts — under the deepest
# shrink). The four interior cells ride the `slow` lane, same split as
# test_comm_buckets' MiniNet-default / real-model-slow precedent: each
# cell is ~3 full fits (elastic run + stop run + resumed control), too
# hot for the single-core tier-1 budget.
@pytest.mark.parametrize(
    "sharding,k",
    [("dp", 1),
     pytest.param("dp", 2, marks=pytest.mark.slow),
     pytest.param("zero1", 1, marks=pytest.mark.slow),
     pytest.param("zero1", 2, marks=pytest.mark.slow),
     pytest.param("zero2_bucketed", 1, marks=pytest.mark.slow),
     ("zero2_bucketed", 2)])
def test_resize_matches_restart_control(tmp_path, sharding, k):
    """The tentpole pin: for every gradient-exchange layout and k in
    {1, 2}, preempting k of 4 ranks with elastic ON continues on the
    survivor mesh with a loss trajectory EQUAL to the
    restart-from-checkpoint control on the same survivor count — same
    state conversion, same cursor handoff, zero replayed batches."""
    zero1 = sharding != "dp"
    zero2 = sharding == "zero2_bucketed"
    bucket_mb = 0.25 if zero2 else 0.0
    ranks = "1" if k == 1 else "1+3"
    kw = dict(zero1=zero1, zero2=zero2, bucket_mb=bucket_mb,
              faults=f"preempt@rank{ranks}:{PREEMPT_AT}")
    cfg = _cfg(tmp_path / "el", **kw)

    rec_el, state_el, tr_el = _run_fit(cfg, 4)
    (resize,) = _events(rec_el, "elastic_resize")
    assert resize["topology"] == f"elastic_4to{4 - k}"
    assert resize["dead_ranks"] == ([1] if k == 1 else [1, 3])
    # zero replayed batches: the cursor restore receipt rides the event
    assert resize["cursor"]["replayed_batches"] == 0
    assert resize["cursor"]["cursor"] == PREEMPT_AT
    assert int(jax.device_get(state_el.step)) == STEPS
    assert tr_el.mesh.shape["data"] == 4 - k
    # the survivor windows carry the schema-valid elastic JSONL block
    post = [r for r in rec_el if r.get("event") == "train"
            and r["step"] > PREEMPT_AT]
    assert post and all(
        r["elastic"]["topology"] == f"elastic_4to{4 - k}"
        and r["elastic"]["resizes"] == 1 for r in post)
    errors: list = []
    schema.validate_elastic_block(post[-1]["elastic"], "row", errors)
    assert errors == []
    assert post[-1]["elastic"]["downtime_ns"] > 0

    rec_ct, state_ct, _ = _restart_control(_cfg(tmp_path / "ctl", **kw),
                                           4 - k)

    el_losses, ct_losses = _losses(rec_el), _losses(rec_ct)
    for step in range(PREEMPT_AT + 1, STEPS + 1):
        assert el_losses[step] == ct_losses[step], (
            f"step {step}: elastic loss {el_losses[step]} != restart "
            f"control {ct_losses[step]} — the resize forked the "
            "trajectory")
    for a, b in zip(jax.tree.leaves(jax.device_get(state_el.params)),
                    jax.tree.leaves(jax.device_get(state_ct.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scale_lr_policy_rescales_and_receipts(tmp_path):
    """`scale_lr`: survivors keep their own rows (global batch shrinks),
    the LR is rescaled by N'/N, and the schedule receipt is logged."""
    cfg = _cfg(tmp_path / "ck", policy="scale_lr",
               faults=f"preempt@rank2:{PREEMPT_AT}")
    records, state, trainer = _run_fit(cfg, 4)
    (resize,) = _events(records, "elastic_resize")
    assert resize["batch_policy"] == "scale_lr"
    assert resize["lr_scale"] == pytest.approx(3 / 4)
    (rescale,) = _events(records, "elastic_lr_rescale")
    assert rescale["lr_scale"] == pytest.approx(3 / 4)
    assert rescale["new_global_batch"] == BATCH * 3 // 4
    assert int(jax.device_get(state.step)) == STEPS
    # the wrapped schedule really evaluates to scale * base
    from distributed_vgg_f_tpu.train.schedule import build_optimizer
    _, base_sched = build_optimizer(cfg)
    probe = STEPS - 1
    assert float(base_sched(probe)) > 0
    assert float(trainer.schedule(probe)) == pytest.approx(
        float(base_sched(probe)) * 3 / 4)
    post = [r for r in records if r.get("event") == "train"
            and r["step"] > PREEMPT_AT]
    assert post[-1]["elastic"]["lr_scale"] == pytest.approx(3 / 4)


# ---------------------------------------------------------------------------
# degradation: refused resize -> named flight class, r18 stop
# ---------------------------------------------------------------------------

def test_all_but_one_dead_degrades_with_named_flight_class(tmp_path):
    """3 of 4 dead leaves one survivor < min_survivors: the resize is
    REFUSED, the run checkpoints and stops on the r18 path, and the flight
    black box names `elastic_degraded_restart` — never
    `unhandled_exception`."""
    from distributed_vgg_f_tpu.telemetry.flight import get_flight
    get_flight().clear()
    cfg = _cfg(tmp_path / "ck",
               faults=f"preempt@rank0+1+2:{PREEMPT_AT}")
    records, state, _ = _run_fit(cfg, 4)
    (deg,) = _events(records, "elastic_degraded")
    assert deg["reason"] == "too_few_survivors"
    (pre,) = _events(records, "preempt")
    assert pre["step"] == PREEMPT_AT
    assert not _events(records, "elastic_resize")
    assert int(jax.device_get(state.step)) == PREEMPT_AT
    # the black box on disk carries the named class and schema-validates
    (bb,) = _events(records, "flight_black_box")
    with open(bb["path"]) as f:
        box = json.load(f)
    assert box["reason"] == "elastic_degraded_restart"
    assert "too_few_survivors" in box["reason_detail"]
    assert schema.validate_flight_record(box) == []


# ---------------------------------------------------------------------------
# typed geometry-receipt error (satellite: checkpoint/retopology.py)
# ---------------------------------------------------------------------------

def test_geometry_receipt_error_is_typed_and_distinguishable():
    """A mismatched opt-layout receipt must read as WRONG LAYOUT, not as a
    corrupt checkpoint: `GeometryReceiptError` subclasses ValueError (the
    pre-r19 contract) but is distinguishable from
    `CheckpointIntegrityError` by type."""
    from distributed_vgg_f_tpu.parallel.buckets import layout_from_receipt
    from distributed_vgg_f_tpu.resilience.errors import (
        CheckpointIntegrityError)
    params = {"w": np.zeros((4, 4), np.float32)}
    struct = jax.eval_shape(lambda p: p, params)
    with pytest.raises(GeometryReceiptError, match="kind"):
        layout_from_receipt(struct, {"kind": "martian"})
    assert issubclass(GeometryReceiptError, ValueError)
    assert not issubclass(GeometryReceiptError, CheckpointIntegrityError)


# ---------------------------------------------------------------------------
# schema + sentinel surfaces
# ---------------------------------------------------------------------------

def test_elastic_block_schema_rejects_drift():
    good = {"topology": "elastic_4to3", "batch_policy": "keep_global",
            "resizes": 1, "downtime_ns": 10, "evacuated_shards": 0,
            "reassigned_data_shards": 1, "lr_scale": 1.0}
    errors: list = []
    schema.validate_elastic_block(good, "t", errors)
    assert errors == []
    for key, bad in [("topology", "elastic_x"), ("batch_policy", "zeus"),
                     ("resizes", -1), ("downtime_ns", 1.5),
                     ("lr_scale", 0)]:
        errors = []
        schema.validate_elastic_block({**good, key: bad}, "t", errors)
        assert errors, (key, bad)


def test_elastic_row_contract():
    row = {"mode": "elastic_bench", "topology": "elastic_4to3",
           "batch_policy": "keep_global", "downtime_seconds": 0.5,
           "restart_seconds": 5.0, "speedup_vs_restart": 10.0,
           "replayed_batches": 0, "resizes": 1}
    errors: list = []
    schema.validate_elastic_row(row, "t", errors)
    assert errors == []
    errors = []
    schema.validate_elastic_row({**row, "speedup_vs_restart": 2.0}, "t",
                                errors)
    assert any(">= 3x" in e for e in errors)
    errors = []
    schema.validate_elastic_row({**row, "replayed_batches": 3}, "t",
                                errors)
    assert any("zero replay" in e for e in errors)
    # _check_decode_row dispatches on mode and checks the topology basis
    errors = []
    schema._check_decode_row({"mode": "elastic_bench",
                              "topology": "diagonal"}, "t", errors)
    assert any("topology" in e for e in errors)


def test_basis_topology_key():
    from distributed_vgg_f_tpu.telemetry.regress import Basis, row_basis
    basis = row_basis({"wire": "u8", "topology": "elastic_4to3"})
    assert basis.topology == "elastic_4to3"
    # pre-r19 rows (no topology key) stay on their committed basis
    assert row_basis({"wire": "u8"}).topology == "static"
    assert Basis("u8", False, "noise", (320, 256),
                 False).describe()["topology"] == "static"
