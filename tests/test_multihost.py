"""Two-OS-process distributed training test (SURVEY.md §4: the standard JAX
answer to testing multi-node without a cluster is fake devices — this goes one
step further and runs TWO real processes with Gloo CPU collectives, covering
`jax.distributed.initialize`, per-process data sharding, and the cross-process
gradient pmean that fake-device single-process tests cannot)."""

import json
import os
import socket
import subprocess
import sys

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training_stays_in_sync(tmp_path):
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "TF_CPP_MIN_LOG_LEVEL": "3",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    outs = [str(tmp_path / f"result_{i}.json") for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, CHILD, str(port), "2", str(i), outs[i]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            assert p.returncode == 0, out.decode(errors="replace")[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = [json.load(open(o)) for o in outs]
    assert all(r["step"] == 3 for r in results)
    # Synchronous replicated DP: params must be bit-identical across processes.
    assert results[0]["fingerprint"] == results[1]["fingerprint"]
    # The eval psum spans the global batch from both processes' shards.
    assert all(r["eval_count"] == 16 for r in results)
    # Exact eval under uneven host shards (21 vs 9 examples): both processes
    # must agree on exactly 30 scored examples — the early-exhausting host fed
    # padding batches instead of stranding the collective.
    assert all(r["exact_eval_examples"] == 30 for r in results)
    # ZeRO-1 over real processes: reduce-scatter/all-gather rode the
    # cross-process backend and the re-gathered params are bit-identical.
    assert all(r["zero1_step"] == 2 for r in results)
    assert results[0]["zero1_fingerprint"] == results[1]["zero1_fingerprint"]
    # Sequence parallelism across the real process boundary: einsum ring and
    # ring × flash (interpreted Pallas kernels) both exact, flash backward's
    # traveling dK/dV accumulators finite.
    assert all(r["ring_ok"] for r in results)
    assert all(r["ring_flash_ok"] for r in results)
    assert all(r["ring_flash_grad_finite"] for r in results)
    # and the Ulysses all-to-all layout (a different Gloo collective),
    # forward and backward (the grad path sends the inverse all_to_alls)
    assert all(r["ulysses_ok"] for r in results)
    assert all(r["ulysses_grads_ok"] for r in results)
    # Flight recorder across real processes: the injected crash produced a
    # schema-valid black box PER RANK (reason=injected_crash, own windows).
    assert all(r["flight_crashed"] for r in results)
    assert all(r["flight_ok"] for r in results)
