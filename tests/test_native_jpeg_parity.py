"""Golden-parity gates for the native decode path (native/jpeg_loader.cc).

SIMD half ("resample kernels"): the AVX2 and scalar paths must produce
BYTE-IDENTICAL output — f32 AND bf16 — across crop modes, dtypes, pack4,
odd source widths, and the grayscale/CMYK promotion edge cases. Both paths
are built from the same single-rounded IEEE ops (std::fmaf mirrors vfmadd
lane for lane), so this is equality, not a tolerance: any drift is a
dispatch bug, never an acceptable rounding difference.

libjpeg half (r7, DCT-scaled + partial decode): two gates.
 - scale 8/8 stays BYTE-IDENTICAL: wherever the chooser picks full
   resolution (every source here smaller than 2x the output), the partial
   crop+skip path must equal the full-decode fallback exactly — the
   context-margin contract (jpeg_loader.cc kMargin; the seed-era partial
   decode was off by up to ~38/255 on the crop's edge columns).
 - reduced scales are TOLERANCE-gated, not byte-pinned: an M/8 DCT
   downscale is a different (box-filter-exact) resample of the same image
   than full-decode + bilinear, so the suite asserts per-channel mean/max
   error bounds and a PSNR floor against the full-scale reference across
   crop modes, dtypes, odd sizes and grayscale — on natural-image-class
   (low-pass) sources, where the comparison is meaningful.

The suite drives every dispatch pair in ONE process via `set_simd` /
`set_scaled` (process-wide atomics the decoder re-reads per image) and
restores the defaults afterwards so no other test inherits a forced path.
"""

import io

import numpy as np
import pytest

from distributed_vgg_f_tpu.data.native_jpeg import (  # noqa: E402
    NativeJpegTrainIterator,
    decode_single_image,
    load_native_jpeg,
    partial_supported,
    reencode_restart,
    restart_kind,
    restart_stats,
    restart_supported,
    scaled_kind,
    scaled_supported,
    set_restart,
    set_restart_fanout,
    set_scaled,
    set_simd,
    simd_kind,
)

if load_native_jpeg() is None:  # pragma: no cover — g++/libjpeg exist here
    pytest.skip("native jpeg loader unavailable", allow_module_level=True)

MEAN = np.array([123.68, 116.78, 103.94], np.float32)
STD = np.array([58.393, 57.12, 57.375], np.float32)


def _simd_available() -> bool:
    lib = load_native_jpeg()
    return bool(lib.dvgg_jpeg_simd_supported())


requires_simd = pytest.mark.skipif(
    not _simd_available(),
    reason="AVX2+FMA not available — scalar is the only path; nothing to "
           "compare (the scalar path itself is covered by "
           "test_native_jpeg.py)")


@pytest.fixture(autouse=True)
def _restore_dispatch():
    """Every test leaves the process-wide dispatches as it found them."""
    before = simd_kind()
    before_scaled = scaled_kind()
    before_restart = restart_kind()
    yield
    set_simd(before != "scalar")
    set_scaled(before_scaled == "scaled")
    set_restart(before_restart == "restart")
    set_restart_fanout(1)


def _jpeg_bytes(arr: np.ndarray, mode: str = None) -> bytes:
    from PIL import Image
    img = Image.fromarray(arr) if mode is None \
        else Image.fromarray(arr, mode=mode)
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=90)
    return buf.getvalue()


@pytest.fixture(scope="module")
def sources():
    """(name, jpeg bytes): RGB at bench shape, odd-dimension RGB, tiny RGB
    (upscale path), and a grayscale that libjpeg promotes to RGB."""
    rng = np.random.default_rng(7)
    srcs = {
        "rgb_320x256": _jpeg_bytes(
            rng.integers(0, 256, size=(320, 256, 3)).astype(np.uint8)),
        "rgb_odd_97x131": _jpeg_bytes(
            rng.integers(0, 256, size=(97, 131, 3)).astype(np.uint8)),
        "rgb_tiny_9x13": _jpeg_bytes(
            rng.integers(0, 256, size=(9, 13, 3)).astype(np.uint8)),
        "gray_101x67": _jpeg_bytes(
            rng.integers(0, 256, size=(101, 67)).astype(np.uint8)),
    }
    return srcs


def _decode_both(data, **kw):
    assert set_simd(False) == "scalar"
    ref = decode_single_image(data, mean=MEAN, std=STD, **kw)
    assert set_simd(True) == "avx2"
    out = decode_single_image(data, mean=MEAN, std=STD, **kw)
    return ref, out


@requires_simd
@pytest.mark.parametrize("image_dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("eval_mode", [False, True])
@pytest.mark.parametrize("pack4", [False, True])
def test_single_image_parity(sources, image_dtype, eval_mode, pack4):
    """Byte-identical across every (source, crop mode, dtype, pack) cell —
    several RNG seeds per train-mode cell so flips and varied crop windows
    are exercised, plus out sizes that hit both the odd-tail and the
    pair-loop paths of the horizontal kernel."""
    for name, data in sources.items():
        for out_size in (64, 96) if pack4 else (64, 97):
            for seed in (0, 1, 2) if not eval_mode else (0,):
                kw = dict(out_size=out_size, image_dtype=image_dtype,
                          pack4=pack4, eval_mode=eval_mode, rng_seed=seed)
                ref, out = _decode_both(data, **kw)
                assert ref is not None and out is not None, (name, kw)
                a = ref.view(np.uint16 if image_dtype == "bfloat16"
                             else np.float32)
                b = out.view(np.uint16 if image_dtype == "bfloat16"
                             else np.float32)
                np.testing.assert_array_equal(
                    a, b, err_msg=f"SIMD/scalar drift: {name} {kw}")


@requires_simd
def test_grayscale_promotion_parity(sources):
    """Grayscale→RGB promotion happens inside libjpeg (out_color_space =
    JCS_RGB), upstream of the resample kernels — before normalize the three
    channels are one gray value, and both paths must agree exactly."""
    ref, out = _decode_both(sources["gray_101x67"], out_size=64,
                            eval_mode=True)
    np.testing.assert_array_equal(ref, out)
    # un-normalize: the per-channel pixels must all be the same gray value
    gray = ref * STD + MEAN
    np.testing.assert_allclose(gray[..., 0], gray[..., 1], atol=1e-3)
    np.testing.assert_allclose(gray[..., 0], gray[..., 2], atol=1e-3)


@requires_simd
def test_cmyk_behaves_identically():
    """CMYK JPEGs: libjpeg has no CMYK→RGB conversion, so the decode fails
    upstream of the kernels and the caller zero-fills — what matters here
    is that BOTH paths report the same outcome (and identical bytes if a
    future libjpeg starts converting)."""
    rng = np.random.default_rng(11)
    data = _jpeg_bytes(
        rng.integers(0, 256, size=(57, 43, 4)).astype(np.uint8), mode="CMYK")
    assert set_simd(False) == "scalar"
    ref = decode_single_image(data, 64, MEAN, STD, eval_mode=True)
    assert set_simd(True) == "avx2"
    out = decode_single_image(data, 64, MEAN, STD, eval_mode=True)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)


@requires_simd
def test_batch_loader_parity(tmp_path):
    """The threaded batch loader end-to-end: same files, same seed, scalar
    vs SIMD — byte-identical batches in both dtypes. Each iterator lives
    entirely under one dispatch setting (the ring decodes ahead, so the
    flip happens only between closed iterators)."""
    from PIL import Image
    rng = np.random.default_rng(3)
    files, labels = [], []
    for i in range(12):
        p = str(tmp_path / f"img_{i}.jpg")
        Image.fromarray(rng.integers(0, 256, size=(80, 100, 3))
                        .astype(np.uint8)).save(p, "JPEG", quality=90)
        files.append(p)
        labels.append(i % 5)
    for dtype in ("float32", "bfloat16"):
        batches = {}
        for kind, enable in (("scalar", False), ("avx2", True)):
            assert set_simd(enable) == kind
            it = NativeJpegTrainIterator(files, labels, 4, 64, seed=5,
                                         mean=MEAN, std=STD,
                                         image_dtype=dtype, num_threads=2)
            batches[kind] = [next(it) for _ in range(4)]
            it.close()
        for ref, out in zip(batches["scalar"], batches["avx2"]):
            np.testing.assert_array_equal(
                np.asarray(ref["image"]).view(np.uint16),
                np.asarray(out["image"]).view(np.uint16),
                err_msg=f"batch loader SIMD/scalar drift ({dtype})")
            np.testing.assert_array_equal(ref["label"], out["label"])


def test_runtime_dispatch_reporting():
    """`simd_kind` reflects reality: AVX2-capable hosts default to 'avx2'
    (unless DVGGF_DECODE_SIMD=0 pinned scalar at load), and `set_simd`
    round-trips — the bench's 'which path ran' line reads this."""
    import os
    kind = simd_kind()
    assert kind in ("scalar", "avx2")
    if _simd_available():
        if os.environ.get("DVGGF_DECODE_SIMD") != "0":
            assert set_simd(True) == "avx2"
        assert set_simd(False) == "scalar"
        assert simd_kind() == "scalar"
        assert set_simd(True) == "avx2"
    else:
        assert set_simd(True) == "scalar"  # no SIMD to enable

# ---------------------------------------------------------------------------
# r7: DCT-scaled + partial decode parity (ISSUE 3)
# ---------------------------------------------------------------------------

requires_scaled = pytest.mark.skipif(
    not scaled_supported(),
    reason="scaled decode compiled out (-DDVGGF_NO_SCALED) — only the "
           "full-resolution path exists; nothing to compare")


def _smooth_jpeg(h, w, seed=0, gray=False):
    """Natural-image-class source (low-pass noise): pure noise has energy
    at every DCT frequency, so a reduced-scale decode of it diverges from a
    full-scale bilinear by construction — the quality gate is defined on
    the image class the pipeline actually serves. The blur radius scales
    with source size the way natural-photo spectra do (~1/f): a 1024px
    photo does not carry Nyquist-limited detail the way 1024px noise
    would, and WITHOUT that scaling the full-scale bilinear reference
    itself aliases under the 3-4x decimation (the comparison would grade
    the reference's aliasing, not the scaled decode)."""
    from PIL import Image, ImageFilter
    rng = np.random.default_rng(seed)
    shape = (h, w) if gray else (h, w, 3)
    img = Image.fromarray(rng.integers(0, 256, size=shape).astype(np.uint8))
    img = img.filter(ImageFilter.GaussianBlur(1.2 * max(1.0,
                                                        min(h, w) / 512.0)))
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=90)
    return buf.getvalue()


def _decode_both_strategies(data, **kw):
    assert set_scaled(False) == "full"
    ref = decode_single_image(data, mean=MEAN, std=STD, **kw)
    assert set_scaled(True) == "scaled"
    out = decode_single_image(data, mean=MEAN, std=STD, **kw)
    return ref, out


@requires_scaled
@pytest.mark.parametrize("image_dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("eval_mode", [False, True])
@pytest.mark.parametrize("pack4", [False, True])
def test_scale8_partial_vs_full_byte_identical(sources, image_dtype,
                                               eval_mode, pack4):
    """At scale 8/8 the partial (crop+skip, context-margin) decode must be
    BYTE-IDENTICAL to the full-decode fallback: the 'scale=8/8 byte-parity
    stays green' acceptance gate, and exactly the test that catches a
    missing fancy-upsampling context margin (the seed-era edge-column
    drift). Output sizes are chosen per source so NO crop can trigger a
    reduced scale (a crop is at most min(W, H) wide, so out > min(W, H)/2
    forces the chooser to 8/8 — pinned via expected_scale_denom)."""
    from distributed_vgg_f_tpu.data.native_jpeg import expected_scale_denom

    if not partial_supported():
        pytest.skip("libjpeg lacks jpeg_crop_scanline/jpeg_skip_scanlines "
                    "(not libjpeg-turbo?) — partial and full paths are the "
                    "same code; nothing to compare")
    out_sizes = {  # per source: both > min(W, H)/2
        "rgb_320x256": (144, 160) if pack4 else (144, 161),
        "rgb_odd_97x131": (64, 96) if pack4 else (64, 97),
        "rgb_tiny_9x13": (64, 96) if pack4 else (64, 97),
        "gray_101x67": (64, 96) if pack4 else (64, 97),
    }
    min_side = {"rgb_320x256": 256, "rgb_odd_97x131": 97,
                "rgb_tiny_9x13": 9, "gray_101x67": 67}
    for name, data in sources.items():
        for out_size in out_sizes[name]:
            # the premise itself, pinned: the largest possible crop still
            # maps to a full-resolution decode
            assert expected_scale_denom(min_side[name], min_side[name],
                                        out_size) == 8, (name, out_size)
            for seed in (0, 1, 2) if not eval_mode else (0,):
                kw = dict(out_size=out_size, image_dtype=image_dtype,
                          pack4=pack4, eval_mode=eval_mode, rng_seed=seed)
                ref, out = _decode_both_strategies(data, **kw)
                assert ref is not None and out is not None, (name, kw)
                np.testing.assert_array_equal(
                    np.asarray(ref).view(np.uint16 if image_dtype ==
                                         "bfloat16" else np.float32),
                    np.asarray(out).view(np.uint16 if image_dtype ==
                                         "bfloat16" else np.float32),
                    err_msg=f"partial/full drift at scale 8/8: {name} {kw}")


def _unnormalize(img):
    return np.asarray(img, np.float32).reshape(-1, 3) * STD + MEAN


def _psnr(ref, out):
    mse = float(((_unnormalize(ref) - _unnormalize(out)) ** 2).mean())
    if mse == 0:
        return float("inf")
    import math
    return 10.0 * math.log10(255.0 ** 2 / mse)


#: Quality floor for reduced-scale decodes vs the full-scale reference on
#: low-pass sources (measured ~35 dB at 4/8 and 2/8 on this class; pure
#: noise sits far lower BY CONSTRUCTION and is not a quality statement).
#: A failing floor means the scaled path is decoding the wrong window or
#: scale, not that JPEG math changed.
PSNR_FLOOR_DB = 28.0
MEAN_ERR_CEIL = 8.0    # per-image mean abs error, raw 0..255 levels
MAX_ERR_CEIL = 96.0    # pointwise ceiling: catches window misalignment


@requires_scaled
@pytest.mark.parametrize("image_dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("eval_mode", [False, True])
def test_scaled_decode_tolerance_vs_full_reference(image_dtype, eval_mode):
    """Reduced-scale cells (>=2x-output sources → 4/8 and 2/8 decodes):
    per-channel mean/max error + PSNR floor against the full-resolution
    reference, across crop modes, dtypes, odd output sizes, and a
    grayscale source. Alignment errors (off-by-one crop window, wrong
    scale) blow the max-error ceiling immediately; gentle DCT-vs-bilinear
    resample differences stay inside it."""
    cells = [
        ("rgb_512", _smooth_jpeg(512, 512, seed=1), 224),
        ("rgb_odd_515x488", _smooth_jpeg(515, 488, seed=2), 211),
        ("rgb_1024", _smooth_jpeg(1024, 1024, seed=3), 224),
        ("gray_512", _smooth_jpeg(512, 512, seed=4, gray=True), 224),
    ]
    for name, data, out_size in cells:
        for seed in (0, 1) if not eval_mode else (0,):
            kw = dict(out_size=out_size, image_dtype=image_dtype,
                      eval_mode=eval_mode, rng_seed=seed)
            ref, out = _decode_both_strategies(data, **kw)
            assert ref is not None and out is not None, (name, kw)
            err = np.abs(_unnormalize(ref) - _unnormalize(out))
            assert float(err.mean()) < MEAN_ERR_CEIL, (name, kw)
            assert float(err.max()) < MAX_ERR_CEIL, (name, kw)
            assert _psnr(ref, out) > PSNR_FLOOR_DB, \
                (name, kw, _psnr(ref, out))


@requires_scaled
def test_scaled_cmyk_behaves_identically():
    """CMYK fails upstream of the scale decision in both strategies — the
    outcomes must agree (mirrors the SIMD CMYK gate)."""
    rng = np.random.default_rng(11)
    data = _jpeg_bytes(
        rng.integers(0, 256, size=(57, 43, 4)).astype(np.uint8), mode="CMYK")
    ref, out = _decode_both_strategies(data, out_size=64, eval_mode=True)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)


def test_scaled_runtime_dispatch_reporting():
    """`scaled_kind` reflects reality and `set_scaled` round-trips — the
    decode bench's receipt reads this (mirrors the SIMD dispatch test)."""
    import os
    kind = scaled_kind()
    assert kind in ("full", "scaled")
    if scaled_supported():
        if os.environ.get("DVGGF_DECODE_SCALED") != "0":
            assert set_scaled(True) == "scaled"
        assert set_scaled(False) == "full"
        assert scaled_kind() == "full"
        assert set_scaled(True) == "scaled"
    else:
        assert set_scaled(True) == "full"  # nothing to enable


# ---------------------------------------------------------------------------
# Restart-marker entropy half (r9): the excerpt decode — headers copied, SOF
# dims patched, RSTn renumbered, only the crop band's segments parsed — must
# be BYTE-IDENTICAL to the sequential entropy decode of the same stream, at
# every scale, dtype, crop mode and fan-out width. Both entropy paths run
# the same IDCT/upsample/color/resample code on the same coefficients; the
# excerpt keeps every used row/column >= the context margin away from a
# synthetic edge, so this is equality, not a tolerance.

requires_restart = pytest.mark.skipif(
    not restart_supported(),
    reason="restart decode compiled out (-DDVGGF_NO_RESTART)")


@pytest.fixture(scope="module")
def marked_sources():
    """(name, marker-bearing jpeg bytes) via the lossless transcoder: a
    row-interval layout (one RSTn per MCU row — rows trimmable), a
    sub-row interval (columns trimmable too), a >=448px textured source
    (the acceptance class), an odd-dimension source, and a grayscale."""
    out = {}
    out["tex448_rows"] = reencode_restart(_smooth_jpeg(448, 448, seed=1), 0)
    out["tex448_cols"] = reencode_restart(_smooth_jpeg(448, 448, seed=1), 7)
    out["rgb_odd_rows"] = reencode_restart(
        _jpeg_bytes(np.random.default_rng(5)
                    .integers(0, 256, size=(197, 131, 3)).astype(np.uint8)),
        0)
    out["rgb_320_cols"] = reencode_restart(
        _jpeg_bytes(np.random.default_rng(6)
                    .integers(0, 256, size=(320, 256, 3)).astype(np.uint8)),
        5)
    out["gray_rows"] = reencode_restart(_smooth_jpeg(256, 224, seed=2,
                                                     gray=True), 0)
    assert all(v for v in out.values())
    return out


def _decode_both_entropy(data, **kw):
    assert set_restart(False) == "sequential"
    ref = decode_single_image(data, mean=MEAN, std=STD, **kw)
    assert set_restart(True) == "restart"
    out = decode_single_image(data, mean=MEAN, std=STD, **kw)
    return ref, out


@requires_restart
@pytest.mark.parametrize("image_dtype", ["float32", "bfloat16", "uint8"])
@pytest.mark.parametrize("eval_mode", [False, True])
def test_restart_vs_sequential_byte_identical(marked_sources, image_dtype,
                                              eval_mode):
    """Golden gate: restart-excerpt decode == sequential decode, byte for
    byte, on marker-bearing sources across dtypes, crop modes, out sizes
    (both DCT scales engage at 448px), and several train-crop seeds."""
    from distributed_vgg_f_tpu.data.native_jpeg import wire_u8_enabled
    if image_dtype == "uint8" and not wire_u8_enabled():
        pytest.skip("u8 wire unavailable on this build")
    for name, data in marked_sources.items():
        for out_size in (64, 224):
            for seed in (0, 1, 2, 3) if not eval_mode else (0,):
                ref, out = _decode_both_entropy(
                    data, out_size=out_size, image_dtype=image_dtype,
                    eval_mode=eval_mode, rng_seed=seed)
                a = np.asarray(ref)
                b = np.asarray(out)
                if a.dtype != np.uint8:
                    a, b = a.view(np.uint16), b.view(np.uint16)
                np.testing.assert_array_equal(
                    a, b, err_msg=f"restart/sequential drift "
                                  f"({name}, out={out_size}, seed={seed}, "
                                  f"{image_dtype}, eval={eval_mode})")


@requires_restart
def test_restart_engages_and_skips_segments(marked_sources):
    """The parity above would pass vacuously if the excerpt path never
    ran: pin that marker-bearing train crops actually engage it and that
    segments were SKIPPED (the entropy work the feature exists to avoid)."""
    assert set_restart(True) == "restart"
    before = restart_stats()
    for seed in range(6):
        decode_single_image(marked_sources["tex448_rows"], 224,
                            MEAN, STD, rng_seed=seed)
    after = restart_stats()
    assert after["images"] > before["images"]
    assert after["segments_skipped"] > before["segments_skipped"]
    assert after["excerpt_fallbacks"] == before["excerpt_fallbacks"]


@requires_restart
def test_restart_fanout_parity(marked_sources):
    """Fan-out width > 1 splits the band across the chunk pool — output
    must stay byte-identical and the fan-out must be receipted."""
    set_restart_fanout(3)
    before = restart_stats()
    for name in ("tex448_rows", "tex448_cols"):
        for seed in (0, 1):
            ref, out = _decode_both_entropy(
                marked_sources[name], out_size=224, rng_seed=seed)
            np.testing.assert_array_equal(
                ref, out, err_msg=f"fan-out drift ({name}, seed={seed})")
    after = restart_stats()
    assert after["fanout_images"] > before["fanout_images"]
    assert after["fanout_width_max"] >= 3


@requires_restart
def test_restart_batch_loader_parity(tmp_path):
    """The threaded batch loader end-to-end on marker-bearing files: same
    seed, restart vs sequential — byte-identical batches (mirrors the
    SIMD batch-parity gate)."""
    from PIL import Image
    rng = np.random.default_rng(11)
    files, labels = [], []
    for i in range(10):
        p = str(tmp_path / f"m_{i}.jpg")
        buf = io.BytesIO()
        Image.fromarray(rng.integers(0, 256, size=(160, 120, 3))
                        .astype(np.uint8)).save(buf, "JPEG", quality=90)
        with open(p, "wb") as f:
            f.write(reencode_restart(buf.getvalue(), 0))
        files.append(p)
        labels.append(i % 3)
    batches = {}
    for kind, enable in (("sequential", False), ("restart", True)):
        assert set_restart(enable) == kind
        it = NativeJpegTrainIterator(files, labels, 4, 64, seed=9,
                                     mean=MEAN, std=STD, num_threads=2)
        batches[kind] = [next(it) for _ in range(4)]
        it.close()
    for ref, out in zip(batches["sequential"], batches["restart"]):
        np.testing.assert_array_equal(ref["image"], out["image"])
        np.testing.assert_array_equal(ref["label"], out["label"])


@requires_restart
def test_markerless_sources_fall_through(sources):
    """Sources without restart markers must ride the sequential path with
    a marker_absent receipt — never an error, never different pixels."""
    assert set_restart(True) == "restart"
    before = restart_stats()
    out = decode_single_image(sources["rgb_320x256"], 64, MEAN, STD,
                              rng_seed=1)
    set_restart(False)
    ref = decode_single_image(sources["rgb_320x256"], 64, MEAN, STD,
                              rng_seed=1)
    np.testing.assert_array_equal(ref, out)
    after = restart_stats()
    assert after["marker_absent"] > before["marker_absent"]
    assert after["images"] == before["images"]


def test_restart_runtime_dispatch_reporting():
    """`restart_kind` reflects reality and `set_restart` round-trips —
    the decode bench's receipt reads this (mirrors the SIMD/scaled
    dispatch tests)."""
    import os
    kind = restart_kind()
    assert kind in ("sequential", "restart")
    if restart_supported():
        if os.environ.get("DVGGF_DECODE_RESTART") != "0":
            assert set_restart(True) == "restart"
        assert set_restart(False) == "sequential"
        assert restart_kind() == "sequential"
        assert set_restart(True) == "restart"
    else:
        assert set_restart(True) == "sequential"  # nothing to enable
    assert set_restart_fanout(4) == 4
    assert set_restart_fanout(0) == 1   # clamped
    assert set_restart_fanout(1) == 1
