"""Golden-parity gate for the SIMD decode path (native/jpeg_loader.cc
"resample kernels"): the AVX2 and scalar paths must produce BYTE-IDENTICAL
output — f32 AND bf16 — across crop modes, dtypes, pack4, odd source
widths, and the grayscale/CMYK promotion edge cases. Both paths are built
from the same single-rounded IEEE ops (std::fmaf mirrors vfmadd lane for
lane), so this is equality, not a tolerance: any drift is a dispatch bug,
never an acceptable rounding difference.

The suite drives both paths in ONE process via `set_simd` (the dispatch is
a process-wide atomic the kernels re-read per decode) and restores the
default afterwards so no other test inherits a forced-scalar decoder.
"""

import io

import numpy as np
import pytest

from distributed_vgg_f_tpu.data.native_jpeg import (  # noqa: E402
    NativeJpegTrainIterator,
    decode_single_image,
    load_native_jpeg,
    set_simd,
    simd_kind,
)

if load_native_jpeg() is None:  # pragma: no cover — g++/libjpeg exist here
    pytest.skip("native jpeg loader unavailable", allow_module_level=True)

MEAN = np.array([123.68, 116.78, 103.94], np.float32)
STD = np.array([58.393, 57.12, 57.375], np.float32)


def _simd_available() -> bool:
    lib = load_native_jpeg()
    return bool(lib.dvgg_jpeg_simd_supported())


requires_simd = pytest.mark.skipif(
    not _simd_available(),
    reason="AVX2+FMA not available — scalar is the only path; nothing to "
           "compare (the scalar path itself is covered by "
           "test_native_jpeg.py)")


@pytest.fixture(autouse=True)
def _restore_dispatch():
    """Every test leaves the process-wide dispatch as it found it."""
    before = simd_kind()
    yield
    set_simd(before != "scalar")


def _jpeg_bytes(arr: np.ndarray, mode: str = None) -> bytes:
    from PIL import Image
    img = Image.fromarray(arr) if mode is None \
        else Image.fromarray(arr, mode=mode)
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=90)
    return buf.getvalue()


@pytest.fixture(scope="module")
def sources():
    """(name, jpeg bytes): RGB at bench shape, odd-dimension RGB, tiny RGB
    (upscale path), and a grayscale that libjpeg promotes to RGB."""
    rng = np.random.default_rng(7)
    srcs = {
        "rgb_320x256": _jpeg_bytes(
            rng.integers(0, 256, size=(320, 256, 3)).astype(np.uint8)),
        "rgb_odd_97x131": _jpeg_bytes(
            rng.integers(0, 256, size=(97, 131, 3)).astype(np.uint8)),
        "rgb_tiny_9x13": _jpeg_bytes(
            rng.integers(0, 256, size=(9, 13, 3)).astype(np.uint8)),
        "gray_101x67": _jpeg_bytes(
            rng.integers(0, 256, size=(101, 67)).astype(np.uint8)),
    }
    return srcs


def _decode_both(data, **kw):
    assert set_simd(False) == "scalar"
    ref = decode_single_image(data, mean=MEAN, std=STD, **kw)
    assert set_simd(True) == "avx2"
    out = decode_single_image(data, mean=MEAN, std=STD, **kw)
    return ref, out


@requires_simd
@pytest.mark.parametrize("image_dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("eval_mode", [False, True])
@pytest.mark.parametrize("pack4", [False, True])
def test_single_image_parity(sources, image_dtype, eval_mode, pack4):
    """Byte-identical across every (source, crop mode, dtype, pack) cell —
    several RNG seeds per train-mode cell so flips and varied crop windows
    are exercised, plus out sizes that hit both the odd-tail and the
    pair-loop paths of the horizontal kernel."""
    for name, data in sources.items():
        for out_size in (64, 96) if pack4 else (64, 97):
            for seed in (0, 1, 2) if not eval_mode else (0,):
                kw = dict(out_size=out_size, image_dtype=image_dtype,
                          pack4=pack4, eval_mode=eval_mode, rng_seed=seed)
                ref, out = _decode_both(data, **kw)
                assert ref is not None and out is not None, (name, kw)
                a = ref.view(np.uint16 if image_dtype == "bfloat16"
                             else np.float32)
                b = out.view(np.uint16 if image_dtype == "bfloat16"
                             else np.float32)
                np.testing.assert_array_equal(
                    a, b, err_msg=f"SIMD/scalar drift: {name} {kw}")


@requires_simd
def test_grayscale_promotion_parity(sources):
    """Grayscale→RGB promotion happens inside libjpeg (out_color_space =
    JCS_RGB), upstream of the resample kernels — before normalize the three
    channels are one gray value, and both paths must agree exactly."""
    ref, out = _decode_both(sources["gray_101x67"], out_size=64,
                            eval_mode=True)
    np.testing.assert_array_equal(ref, out)
    # un-normalize: the per-channel pixels must all be the same gray value
    gray = ref * STD + MEAN
    np.testing.assert_allclose(gray[..., 0], gray[..., 1], atol=1e-3)
    np.testing.assert_allclose(gray[..., 0], gray[..., 2], atol=1e-3)


@requires_simd
def test_cmyk_behaves_identically():
    """CMYK JPEGs: libjpeg has no CMYK→RGB conversion, so the decode fails
    upstream of the kernels and the caller zero-fills — what matters here
    is that BOTH paths report the same outcome (and identical bytes if a
    future libjpeg starts converting)."""
    rng = np.random.default_rng(11)
    data = _jpeg_bytes(
        rng.integers(0, 256, size=(57, 43, 4)).astype(np.uint8), mode="CMYK")
    assert set_simd(False) == "scalar"
    ref = decode_single_image(data, 64, MEAN, STD, eval_mode=True)
    assert set_simd(True) == "avx2"
    out = decode_single_image(data, 64, MEAN, STD, eval_mode=True)
    if ref is None or out is None:
        assert ref is None and out is None
    else:
        np.testing.assert_array_equal(ref, out)


@requires_simd
def test_batch_loader_parity(tmp_path):
    """The threaded batch loader end-to-end: same files, same seed, scalar
    vs SIMD — byte-identical batches in both dtypes. Each iterator lives
    entirely under one dispatch setting (the ring decodes ahead, so the
    flip happens only between closed iterators)."""
    from PIL import Image
    rng = np.random.default_rng(3)
    files, labels = [], []
    for i in range(12):
        p = str(tmp_path / f"img_{i}.jpg")
        Image.fromarray(rng.integers(0, 256, size=(80, 100, 3))
                        .astype(np.uint8)).save(p, "JPEG", quality=90)
        files.append(p)
        labels.append(i % 5)
    for dtype in ("float32", "bfloat16"):
        batches = {}
        for kind, enable in (("scalar", False), ("avx2", True)):
            assert set_simd(enable) == kind
            it = NativeJpegTrainIterator(files, labels, 4, 64, seed=5,
                                         mean=MEAN, std=STD,
                                         image_dtype=dtype, num_threads=2)
            batches[kind] = [next(it) for _ in range(4)]
            it.close()
        for ref, out in zip(batches["scalar"], batches["avx2"]):
            np.testing.assert_array_equal(
                np.asarray(ref["image"]).view(np.uint16),
                np.asarray(out["image"]).view(np.uint16),
                err_msg=f"batch loader SIMD/scalar drift ({dtype})")
            np.testing.assert_array_equal(ref["label"], out["label"])


def test_runtime_dispatch_reporting():
    """`simd_kind` reflects reality: AVX2-capable hosts default to 'avx2'
    (unless DVGGF_DECODE_SIMD=0 pinned scalar at load), and `set_simd`
    round-trips — the bench's 'which path ran' line reads this."""
    import os
    kind = simd_kind()
    assert kind in ("scalar", "avx2")
    if _simd_available():
        if os.environ.get("DVGGF_DECODE_SIMD") != "0":
            assert set_simd(True) == "avx2"
        assert set_simd(False) == "scalar"
        assert simd_kind() == "scalar"
        assert set_simd(True) == "avx2"
    else:
        assert set_simd(True) == "scalar"  # no SIMD to enable
