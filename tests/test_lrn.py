"""LRN numerics vs a NumPy oracle and vs torch.nn.LocalResponseNorm
(SURVEY.md §4 numerical parity tests)."""

import numpy as np
import jax.numpy as jnp

from distributed_vgg_f_tpu.ops.lrn import local_response_norm


def _numpy_lrn(x, depth_radius=2, bias=2.0, alpha=1e-4, beta=0.75,
               alpha_scaled=False):
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha
    out = np.empty_like(x)
    C = x.shape[-1]
    for c in range(C):
        lo, hi = max(0, c - depth_radius), min(C, c + depth_radius + 1)
        s = np.sum(x[..., lo:hi] ** 2, axis=-1)
        out[..., c] = x[..., c] / (bias + a * s) ** beta
    return out


def test_lrn_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 5, 16), dtype=np.float32)
    got = np.asarray(local_response_norm(jnp.asarray(x)))
    want = _numpy_lrn(x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lrn_matches_torch():
    import torch

    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 7, 7, 8), dtype=np.float32) * 3.0
    # torch LRN: NCHW, size=n, denom = (k + alpha/n * sum)^beta  → alpha_scaled.
    n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
    t = torch.nn.LocalResponseNorm(size=n, alpha=alpha, beta=beta, k=k)
    want = t(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy().transpose(0, 2, 3, 1)
    got = np.asarray(local_response_norm(
        jnp.asarray(x), depth_radius=2, bias=k, alpha=alpha, beta=beta,
        alpha_scaled=True))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_lrn_bf16_input_preserves_dtype():
    x = jnp.ones((1, 2, 2, 8), jnp.bfloat16)
    y = local_response_norm(x)
    assert y.dtype == jnp.bfloat16


def test_matmul_vjp_forward_matches_oracle():
    from distributed_vgg_f_tpu.ops.lrn import local_response_norm_matmul_vjp

    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 5, 5, 32), dtype=np.float32)
    got = np.asarray(local_response_norm_matmul_vjp(jnp.asarray(x)))
    np.testing.assert_allclose(got, _numpy_lrn(x), rtol=1e-5, atol=1e-6)


def test_matmul_vjp_gradient_matches_autodiff_oracle():
    """The hand-derived backward (the default training path) against autodiff
    of the reduce_window oracle, f32."""
    import jax

    from distributed_vgg_f_tpu.ops.lrn import local_response_norm_matmul_vjp

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 6, 6, 64), dtype=np.float32))
    cot = jnp.asarray(rng.standard_normal((2, 6, 6, 64), dtype=np.float32))

    g_oracle = jax.grad(lambda v: (local_response_norm(v) * cot).sum())(x)
    g_vjp = jax.grad(lambda v: (local_response_norm_matmul_vjp(v) * cot).sum())(x)
    np.testing.assert_allclose(np.asarray(g_vjp), np.asarray(g_oracle),
                               rtol=1e-4, atol=1e-6)


def test_shift_vjp_matches_oracle_fwd_and_bwd():
    """The shifted-slice form (kept as a measured TPU non-win / oracle
    cross-check) must still be numerically exact."""
    import jax

    from distributed_vgg_f_tpu.ops.lrn import local_response_norm_shift_vjp

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 5, 5, 48), dtype=np.float32))
    cot = jnp.asarray(rng.standard_normal((2, 5, 5, 48), dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(local_response_norm_shift_vjp(x)),
        np.asarray(local_response_norm(x)), rtol=1e-5, atol=1e-6)
    g_oracle = jax.grad(lambda v: (local_response_norm(v) * cot).sum())(x)
    g_shift = jax.grad(
        lambda v: (local_response_norm_shift_vjp(v) * cot).sum())(x)
    np.testing.assert_allclose(np.asarray(g_shift), np.asarray(g_oracle),
                               rtol=1e-4, atol=1e-6)


def test_dispatcher_default_is_custom_vjp():
    import jax

    from distributed_vgg_f_tpu.ops import lrn as lrn_mod

    # The default impl must be differentiable under jit (the train step is
    # grad-of-jitted) and numerically match the oracle.
    x = jnp.asarray(np.random.default_rng(4).standard_normal(
        (1, 4, 4, 16), dtype=np.float32))
    g = jax.jit(jax.grad(lambda v: lrn_mod.lrn(v).sum()))(x)
    g_o = jax.grad(lambda v: local_response_norm(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_o), rtol=1e-4,
                               atol=1e-6)


def test_matmul_vjp_bf16_band_within_tolerance_of_oracle():
    """The bf16 band-matmul path (bf16 operands, fp32 MXU accumulation —
    VERDICT r2 #8): window-sum error ~2^-8 relative enters the normalizer
    scaled by alpha≈1e-4 against the O(1) bias, so forward AND backward stay
    within bf16 representation error of the fp32 oracle."""
    import jax

    from distributed_vgg_f_tpu.ops.lrn import local_response_norm_matmul_vjp

    rng = np.random.default_rng(3)
    x32 = rng.standard_normal((2, 5, 5, 64), dtype=np.float32) * 2.0
    x16 = jnp.asarray(x32, jnp.bfloat16)
    # compare against the oracle ON THE SAME (bf16-rounded) inputs so the
    # measured error is the bf16 PATH's, not the input rounding's
    x_rounded = np.asarray(x16, np.float32)

    got = np.asarray(local_response_norm_matmul_vjp(x16), np.float32)
    want = _numpy_lrn(x_rounded)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)

    def f16(v):
        return jnp.sum(local_response_norm_matmul_vjp(v) ** 2)

    def f32(v):
        return jnp.sum(local_response_norm(v) ** 2)

    g16 = np.asarray(jax.grad(f16)(x16), np.float32)
    g32 = np.asarray(jax.grad(f32)(jnp.asarray(x_rounded)))
    np.testing.assert_allclose(g16, g32, rtol=5e-2, atol=5e-2)
