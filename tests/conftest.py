"""Test environment: force an 8-device virtual CPU platform BEFORE jax imports,
so every test exercises real mesh construction and cross-replica collectives
without TPU hardware (SURVEY.md §4 fake-multi-device strategy)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402
import pytest  # noqa: E402

# A sitecustomize hook on this machine registers the single-TPU tunnel backend at
# interpreter start and overrides jax_platforms, so the env var alone is not
# enough; backends initialize lazily, so forcing the config here still wins.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: identical jitted computations (the same
# VGG-F train/eval steps rebuilt by many tests) compile once per machine, not
# once per test — the single biggest lever on suite wall-time (without it the
# suite blows the tier-1 870 s budget). The dir is keyed by the host's CPU
# fingerprint (_child_bootstrap.default_cache_dir): XLA:CPU entries are AOT
# machine code, and executing another machine's cached code after a VM
# migration miscomputes (r3: cached train step returned loss=nan; SIGILL is
# the other documented outcome). A second jaxlib-0.4.x hazard (resilience
# PR): reloading a cached executable with DONATED buffers after an Orbax
# restore corrupts the glibc heap ("corrupted double-linked list" aborts
# killing the whole run mid-suite; reproduced 5/5 with donation+cache, 0/5
# with either removed) — which is why train/step.py only donates on
# non-CPU backends.
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _child_bootstrap import default_cache_dir  # noqa: E402

jax.config.update("jax_compilation_cache_dir", default_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
