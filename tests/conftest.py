"""Test environment: force an 8-device virtual CPU platform BEFORE jax imports,
so every test exercises real mesh construction and cross-replica collectives
without TPU hardware (SURVEY.md §4 fake-multi-device strategy)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402
import pytest  # noqa: E402

# A sitecustomize hook on this machine registers the single-TPU tunnel backend at
# interpreter start and overrides jax_platforms, so the env var alone is not
# enough; backends initialize lazily, so forcing the config here still wins.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
