"""Checkpoint save/restore of sharded state + kill-and-restart resume semantics
(SURVEY.md §4 fake-device distributed tests, §5 failure detection)."""

import dataclasses
import io

import jax
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _cfg(ckpt_dir, steps=4):
    return ExperimentConfig(
        name="ckpt_test",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        train=TrainConfig(steps=steps, log_every=100, seed=0,
                          checkpoint_every_steps=2,
                          checkpoint_dir=str(ckpt_dir)),
    )


def _quiet():
    return MetricLogger(stream=io.StringIO())


def test_save_restore_roundtrip(devices8, tmp_path):
    cfg = _cfg(tmp_path / "ckpt")
    tr = Trainer(cfg, logger=_quiet())
    state = tr.fit()
    assert int(jax.device_get(state.step)) == 4
    assert tr.checkpoints.all_steps()  # saved during fit

    # fresh trainer = restarted process (SURVEY.md §3.5 restart path)
    tr2 = Trainer(cfg, logger=_quiet())
    restored = tr2.restore_or_init()
    assert int(jax.device_get(restored.step)) == 4
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_continues_training(devices8, tmp_path):
    cfg = _cfg(tmp_path / "ckpt2", steps=3)
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()

    # "restart" with a longer horizon: resumes at 3, ends at 6
    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, steps=6))
    tr2 = Trainer(cfg2, logger=_quiet())
    state = tr2.fit()
    assert int(jax.device_get(state.step)) == 6
    assert tr2.checkpoints.latest_step() == 6


def test_restore_extra_metadata(devices8, tmp_path):
    cfg = _cfg(tmp_path / "ckpt3", steps=2)
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()
    tr2 = Trainer(cfg, logger=_quiet())
    template = tr2.init_state()
    state, extra = tr2.checkpoints.restore(template)
    assert extra["examples_seen"] == 2 * 16


@pytest.mark.slow
def test_resume_fast_forward_matches_uninterrupted(devices8, tmp_path):
    """Deterministic data resume (SURVEY.md §5 data-iterator state): 4 steps +
    crash + resume-to-8 with fast-forward must equal an uninterrupted 8-step
    run bit-for-bit — the replayed iterator reproduces the exact stream."""
    def ff(cfg):
        return dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train,
                                           resume_data_fast_forward=True))

    # interrupted: 4 steps, then a fresh trainer resumes to 8
    cfg_a = ff(_cfg(tmp_path / "ff_a", steps=4))
    Trainer(cfg_a, logger=_quiet()).fit()
    cfg_a8 = dataclasses.replace(
        cfg_a, train=dataclasses.replace(cfg_a.train, steps=8))
    resumed = Trainer(cfg_a8, logger=_quiet()).fit()

    # uninterrupted: 8 straight steps
    cfg_b = ff(_cfg(tmp_path / "ff_b", steps=8))
    straight = Trainer(cfg_b, logger=_quiet()).fit()

    assert int(jax.device_get(resumed.step)) == 8
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(resumed.params)),
            jax.tree_util.tree_leaves(jax.device_get(straight.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
