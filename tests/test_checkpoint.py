"""Checkpoint save/restore of sharded state + kill-and-restart resume semantics
(SURVEY.md §4 fake-device distributed tests, §5 failure detection)."""

import dataclasses
import io
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _cfg(ckpt_dir, steps=4):
    return ExperimentConfig(
        name="ckpt_test",
        model=ModelConfig(name="vggf", num_classes=10, dropout_rate=0.0,
                          compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        train=TrainConfig(steps=steps, log_every=100, seed=0,
                          checkpoint_every_steps=2,
                          checkpoint_dir=str(ckpt_dir)),
    )


def _quiet():
    return MetricLogger(stream=io.StringIO())


def test_save_restore_roundtrip(devices8, tmp_path):
    cfg = _cfg(tmp_path / "ckpt")
    tr = Trainer(cfg, logger=_quiet())
    state = tr.fit()
    assert int(jax.device_get(state.step)) == 4
    assert tr.checkpoints.all_steps()  # saved during fit

    # fresh trainer = restarted process (SURVEY.md §3.5 restart path)
    tr2 = Trainer(cfg, logger=_quiet())
    restored = tr2.restore_or_init()
    assert int(jax.device_get(restored.step)) == 4
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_resume_continues_training(devices8, tmp_path):
    cfg = _cfg(tmp_path / "ckpt2", steps=3)
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()

    # "restart" with a longer horizon: resumes at 3, ends at 6
    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, steps=6))
    tr2 = Trainer(cfg2, logger=_quiet())
    state = tr2.fit()
    assert int(jax.device_get(state.step)) == 6
    assert tr2.checkpoints.latest_step() == 6


@pytest.mark.slow
def test_restore_extra_metadata(devices8, tmp_path):
    cfg = _cfg(tmp_path / "ckpt3", steps=2)
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()
    tr2 = Trainer(cfg, logger=_quiet())
    template = tr2.init_state()
    state, extra = tr2.checkpoints.restore(template)
    assert extra["examples_seen"] == 2 * 16


@pytest.mark.slow
def test_branched_run_replaces_colliding_steps(devices8, tmp_path):
    """ADVICE r2 #1: a run branched from an earlier checkpoint
    (train.restore_from_best) re-reaches step numbers the stale chain already
    holds. Orbax never overwrites a step, so without replacement the final
    forced save is silently dropped and a later restore returns pre-branch
    state. With replace_on_collision the latest checkpoint must hold the
    BRANCHED weights."""
    cfg = _cfg(tmp_path / "branch", steps=4)  # checkpoints at steps 2 and 4
    tr = Trainer(cfg, logger=_quiet())
    stale_final = tr.fit()
    assert {2, 4} <= set(tr.checkpoints.all_steps())
    assert tr.checkpoints.latest_step() == 4

    # plant the best slot at step 2 — the branch point
    state2, _ = tr.checkpoints.restore(tr.init_state(), step=2)
    best = tr._make_best_manager()
    assert best.save(state2, force=True,
                     extra={"eval_top1": 0.9, "step": 2},
                     metrics={"eval_top1": 0.9})
    best.wait()

    # branched run: restores step 2, trains 3..4 on a DIFFERENT data stream
    # (seed only affects the synthetic data order here — params come from the
    # restore, dropout is off), so its end state differs from the stale one
    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, seed=123, restore_from_best=True))
    branched = Trainer(cfg2, logger=_quiet()).fit()
    assert int(jax.device_get(branched.step)) == 4

    restored = Trainer(cfg, logger=_quiet()).restore_or_init()
    branched_leaves = jax.tree_util.tree_leaves(
        jax.device_get(branched.params))
    stale_leaves = jax.tree_util.tree_leaves(
        jax.device_get(stale_final.params))
    restored_leaves = jax.tree_util.tree_leaves(
        jax.device_get(restored.params))
    assert any(not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(branched_leaves, stale_leaves)), \
        "test premise broken: branched run converged to the stale state"
    for a, b in zip(restored_leaves, branched_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_branch_truncates_stale_steps_ahead(devices8, tmp_path):
    """Mid-branch crash safety (code-review r3): TRAINING from the best slot
    deletes stale steps AHEAD of the branch point up front — otherwise a
    crash before the branch re-reaches them leaves latest_step() resolving
    to pre-branch state."""
    cfg = _cfg(tmp_path / "trunc", steps=4)
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()
    assert 4 in tr.checkpoints.all_steps()

    state2, _ = tr.checkpoints.restore(tr.init_state(), step=2)
    best = tr._make_best_manager()
    assert best.save(state2, force=True,
                     extra={"eval_top1": 0.9, "step": 2},
                     metrics={"eval_top1": 0.9})
    best.wait()

    # branch trains ONE step (to 3) — stale step 4 must be gone immediately,
    # not merely replaced when the branch eventually reaches it
    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, steps=3, seed=123, restore_from_best=True))
    tr2 = Trainer(cfg2, logger=_quiet())
    branched = tr2.fit()
    assert int(jax.device_get(branched.step)) == 3
    steps = tr2.checkpoints.all_steps()
    assert 4 not in steps
    assert tr2.checkpoints.latest_step() == 3
    restored = Trainer(cfg, logger=_quiet()).restore_or_init()
    assert int(jax.device_get(restored.step)) == 3


@pytest.mark.slow
def test_periodic_save_replaces_stale_step_in_branch_overlap(devices8,
                                                             tmp_path):
    """A branched run's PERIODIC (non-forced) cadence save inside the stale
    chain's step range must also replace — Orbax's should_save suppresses
    step <= latest BEFORE its existence check, so without overlap detection
    the save silently drops and a hard crash (SIGKILL, no forced save) would
    resume from pre-branch state."""
    cfg = _cfg(tmp_path / "overlap", steps=4)  # cadence 2 → stale chain has 4
    tr = Trainer(cfg, logger=_quiet())
    stale_final = tr.fit()
    assert tr.checkpoints.latest_step() == 4

    branched = stale_final.replace(params=jax.tree.map(
        lambda x: x + 1.0, stale_final.params))
    # the branch runs in a FRESH process — a new manager, whose cadence
    # (periodic, NOT forced) save inside the overlap must replace
    from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager
    mgr2 = CheckpointManager(cfg.train.checkpoint_dir, max_to_keep=3,
                             save_interval_steps=2)
    assert mgr2.save(
        branched, extra={"examples_seen": 64}, replace_on_collision=True)
    mgr2.wait()
    restored, _ = mgr2.restore(tr.init_state())
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(restored.params)),
                    jax.tree_util.tree_leaves(jax.device_get(branched.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # off-cadence steps inside the overlap stay skipped (interval semantics)
    odd = branched.replace(step=jnp.asarray(3, jnp.int32))
    assert not mgr2.save(odd, replace_on_collision=True)


@pytest.mark.slow
def test_best_slot_staged_replacement_never_leaves_gap(devices8, tmp_path):
    """ADVICE r2 #2: replacing the best slot on step-number collision must not
    pass through a state with NO best checkpoint on disk. A best-metric
    manager's replace_on_collision stages the replacement at an unused index;
    Orbax's best-metric GC removes the loser only after the new save is
    durable."""
    from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager

    cfg = _cfg(tmp_path / "ckpt_slot", steps=2)
    tr = Trainer(cfg, logger=_quiet())
    state = tr.fit()  # step == 2

    slot = CheckpointManager(str(tmp_path / "best_slot"), max_to_keep=1,
                             save_interval_steps=1, best_metric="eval_top1")
    assert slot.save(state, force=True, extra={"eval_top1": 0.5, "step": 2},
                     metrics={"eval_top1": 0.5})
    slot.wait()
    # a RESUMED run (fresh manager) re-reaches the slot's step with a better
    # score; plain save must refuse the collision...
    slot2 = CheckpointManager(str(tmp_path / "best_slot"), max_to_keep=1,
                              save_interval_steps=1, best_metric="eval_top1")
    assert not slot2.save(state, force=True,
                          extra={"eval_top1": 0.8, "step": 2},
                          metrics={"eval_top1": 0.8})
    assert slot2.all_steps() == [2]  # the durable best is untouched
    # ...and replace_on_collision stages at an unused index (never a gap)
    assert slot2.save(state, force=True, extra={"eval_top1": 0.8, "step": 2},
                      metrics={"eval_top1": 0.8}, replace_on_collision=True)
    # old entry GC'd only after the replacement became durable; score wins
    assert slot2.all_steps() == [3]
    assert slot2.latest_extra()["eval_top1"] == 0.8


@pytest.mark.slow
def test_forced_save_after_same_session_cadence_save_is_noop(devices8,
                                                            tmp_path):
    """The end-of-run forced save often lands on the step the cadence save
    just persisted. That collision is a re-save of IDENTICAL state and must
    NOT delete-and-rewrite the only durable copy (a crash inside that window
    would lose the end state) — it reports success and leaves the file
    untouched (code-review r3)."""
    cfg = _cfg(tmp_path / "dedup", steps=4)  # cadence 2: step 4 saved twice
    tr = Trainer(cfg, logger=_quiet())
    state = tr.fit()  # internally: cadence save at 4, then forced save at 4

    assert tr.checkpoints.latest_step() == 4
    # the deduped forced re-save reported success (no checkpoint_save_dropped)
    restored, extra = tr.checkpoints.restore(tr.init_state())
    assert int(jax.device_get(restored.step)) == 4
    assert extra["examples_seen"] == 4 * 16

    def newest_mtime():
        return max(os.stat(os.path.join(root, f)).st_mtime_ns
                   for root, _, files in os.walk(str(tmp_path / "dedup"))
                   for f in files)

    # direct re-save of the same step via the same manager: True, no rewrite
    before = newest_mtime()
    assert tr.checkpoints.save(state, force=True, replace_on_collision=True)
    assert newest_mtime() == before  # nothing was rewritten


@pytest.mark.slow
def test_resume_fast_forward_matches_uninterrupted(devices8, tmp_path):
    """Deterministic data resume (SURVEY.md §5 data-iterator state): 4 steps +
    crash + resume-to-8 with fast-forward must equal an uninterrupted 8-step
    run bit-for-bit — the replayed iterator reproduces the exact stream."""
    def ff(cfg):
        return dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train,
                                           resume_data_fast_forward=True))

    # interrupted: 4 steps, then a fresh trainer resumes to 8
    cfg_a = ff(_cfg(tmp_path / "ff_a", steps=4))
    Trainer(cfg_a, logger=_quiet()).fit()
    cfg_a8 = dataclasses.replace(
        cfg_a, train=dataclasses.replace(cfg_a.train, steps=8))
    resumed = Trainer(cfg_a8, logger=_quiet()).fit()

    # uninterrupted: 8 straight steps
    cfg_b = ff(_cfg(tmp_path / "ff_b", steps=8))
    straight = Trainer(cfg_b, logger=_quiet()).fit()

    assert int(jax.device_get(resumed.step)) == 8
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(resumed.params)),
            jax.tree_util.tree_leaves(jax.device_get(straight.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_fit_with_explicit_state_never_truncates(devices8, tmp_path):
    """Truncation must fire only on an ACTUAL best-slot restore — a fit()
    handed an explicit state (fresh init here), even with
    restore_from_best=True configured, must leave the durable chain intact
    (code-review r3: the config-flag gate deleted the whole chain)."""
    cfg = _cfg(tmp_path / "notrunc", steps=4)
    tr = Trainer(cfg, logger=_quiet())
    tr.fit()
    chain = set(tr.checkpoints.all_steps())
    assert 4 in chain

    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, restore_from_best=True, steps=2))
    tr2 = Trainer(cfg2, logger=_quiet())
    tr2.fit(tr2.init_state())  # explicit state: nothing was restored
    # chain ahead of step 0 survives (the 2-step run's own saves may add)
    assert chain <= set(tr2.checkpoints.all_steps())
