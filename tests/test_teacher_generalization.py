"""Teacher-task generalization evidence (data/teacher.py; VERDICT r2 #3).

Two layers of coverage:
- fast dataset-mechanics tests: determinism, train/val index disjointness,
  label-noise rate, clean eval labels, class balance — the properties the
  generalization claim rests on;
- an artifact regression band over the committed run
  (benchmarks/runs/teacher_gen/summary.json): val top-1 well above chance,
  below the clean train score, with the curve actually rising. The run
  itself is ~30 CPU-minutes (benchmarks/teacher_generalization.py), so the
  band pins the committed artifact rather than retraining per test run.
"""

import json
import os

import numpy as np
import pytest

from distributed_vgg_f_tpu.config import DataConfig
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.data.teacher import Teacher, _raw_images

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SUMMARY = os.path.join(REPO, "benchmarks", "runs", "teacher_gen",
                       "summary.json")


def _cfg(**kw):
    kw.setdefault("num_train_examples", 512)
    kw.setdefault("num_eval_examples", 256)
    return DataConfig(name="teacher", image_size=32, global_batch_size=32,
                      **kw)


def test_train_stream_is_deterministic():
    a = build_dataset(_cfg(), "train", seed=3)
    b = build_dataset(_cfg(), "train", seed=3)
    for _ in range(3):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])


def test_val_split_is_disjoint_and_clean():
    """Eval images come from indices ≥ num_train (disjoint by construction)
    and carry the teacher's CLEAN label for the clean image."""
    cfg = _cfg()
    ev = build_dataset(cfg, "eval", seed=0)
    teacher = Teacher(32, 10, seed=7)
    n = 0
    for batch in iter(ev):
        idx = np.arange(cfg.num_train_examples + n,
                        cfg.num_train_examples + n + len(batch["label"]))
        clean = _raw_images(idx, 32, base_seed=11)
        np.testing.assert_array_equal(batch["label"], teacher.label(clean))
        # eval inputs are the normalized CLEAN images (no augmentation)
        np.testing.assert_allclose(
            np.asarray(batch["image"], np.float32),
            (clean - 127.5) / 64.0, rtol=1e-5, atol=1e-5)
        n += len(batch["label"])
    assert n == cfg.num_eval_examples


def test_fixed_eval_index_base_is_train_size_invariant():
    """data.eval_index_base pins the held-out SET independent of the train
    size (code-review r4: without it, a train-size sweep scores each arm on
    a different val sample — noise the same order as the effect). Identical
    eval batches for 4k and 8k train arms; overlap with the train range
    raises."""
    import pytest

    a = build_dataset(_cfg(num_train_examples=4096, eval_index_base=65536),
                      "eval", seed=0)
    b = build_dataset(_cfg(num_train_examples=8192, eval_index_base=65536),
                      "eval", seed=0)
    for ba, bb in zip(iter(a), iter(b)):
        np.testing.assert_array_equal(ba["image"], bb["image"])
        np.testing.assert_array_equal(ba["label"], bb["label"])
    with pytest.raises(ValueError, match="overlaps the train range"):
        next(iter(build_dataset(
            _cfg(num_train_examples=8192, eval_index_base=4096), "eval",
            seed=0)))


def test_label_noise_rate_matches_design():
    """~10 % of train labels differ from the teacher's clean label (the
    noise draw may coincide with the true label, so the observed rate is
    slightly under 0.10 × (1 − 1/num_classes) ≈ 0.09)."""
    cfg = _cfg(num_train_examples=2048)
    ds = build_dataset(cfg, "train", seed=0)
    teacher = Teacher(32, 10, seed=7)
    flips = total = 0
    seen_order = ds._order  # iterate via the dataset's own index order
    ds._rng.shuffle(seen_order)
    for start in range(0, 2048, 256):
        idx = seen_order[start:start + 256]
        clean = _raw_images(idx, 32, base_seed=11)
        noisy = ds._noisy_labels(teacher.label(clean), idx)
        flips += int((noisy != teacher.label(clean)).sum())
        total += len(idx)
    assert 0.05 < flips / total < 0.14


def test_teacher_labels_are_roughly_balanced():
    idx = np.arange(4096)
    teacher = Teacher(32, 10, seed=7)
    labs = teacher.label(_raw_images(idx, 32, base_seed=11))
    counts = np.bincount(labs, minlength=10)
    assert counts.min() > 0.03 * len(idx)
    assert counts.max() < 0.25 * len(idx)


def test_committed_train_size_sweep_monotone():
    """The controlled train-size sweep (4k → 8k → 16k examples, identical
    epoch-based schedule, ONE fixed far-offset val set): val top-1 must
    rise monotonically with train size — the known-good data lever doing
    what real generalization does. (The clean-train/val GAP is not
    asserted monotone: the committed arms show it can widen while both
    scores rise — clean-train accuracy climbs faster than val at these
    sizes.)"""
    arms = []
    for name in ("teacher_gen_ctrl_4k", "teacher_gen_ctrl_8k",
                 "teacher_gen_ctrl_16k"):
        path = os.path.join(REPO, "benchmarks", "runs", name, "summary.json")
        assert os.path.exists(path), f"missing committed sweep arm: {name}"
        with open(path) as f:
            arms.append(json.load(f))
    # every arm scored the same held-out set
    assert {a["eval_index_base"] for a in arms} == {65536}
    assert {a["num_eval_examples"] for a in arms} == {4096}
    sizes = [a["num_train_examples"] for a in arms]
    assert sizes == [4096, 8192, 16384]
    vals = [a["val_top1_final"] for a in arms]
    assert vals[0] < vals[1] < vals[2], vals
    # every arm keeps a real (positive) clean-train/val gap — no arm is
    # secretly scoring its own training distribution
    for a in arms:
        assert a["train_clean_top1_final"] > a["val_top1_final"]
    # regression floor for the strongest arm (measured 2026-07-31; leave
    # headroom for run-to-run noise if ever re-trained)
    assert vals[2] >= 0.52


def test_committed_generalization_run_band():
    """The committed curve must show genuine generalization: DISJOINT-split
    top-1 ≥ 3× chance, strictly below the clean train-split score
    (a real gap), and a rising curve — retiring 'every committed run
    saturates at 1.0' as the only learning evidence."""
    assert os.path.exists(SUMMARY), \
        "missing committed run: python benchmarks/teacher_generalization.py"
    with open(SUMMARY) as f:
        s = json.load(f)
    assert s["generalizes"] is True
    assert s["val_top1_final"] >= 0.30
    assert s["val_top1_final"] >= 3 * s["chance"]
    assert s["val_top1_final"] < s["train_clean_top1_final"]
    curve = s["val_top1_curve"]
    assert curve[0] < 0.2 and max(curve) >= 0.30
    assert s["val_top5_final"] >= 0.75
