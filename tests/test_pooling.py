"""ops/pooling.py: ceil-mode max pool + the custom-VJP backward (a measured
TPU non-win kept in-tree — it must stay numerically correct regardless)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.ops import pooling as P


@pytest.fixture(autouse=True)
def _reset_impl():
    yield
    P.set_maxpool_impl(None)


def test_ceil_mode_output_sizes():
    # (input H) -> ceil((H-3)/2)+1
    for h, expect in [(56, 28), (13, 6), (14, 7), (27, 13),
                      (6, 3), (3, 1), (2, 1)]:
        x = jnp.zeros((1, h, h, 4))
        assert P.maxpool_3x3s2_ceil(x).shape[1] == expect, h


@pytest.mark.parametrize("shape", [(2, 13, 13, 8), (2, 14, 14, 8),
                                   (3, 7, 9, 16), (1, 3, 3, 4), (1, 2, 2, 4)])
def test_custom_vjp_matches_autodiff(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    P.set_maxpool_impl("autodiff")
    ref = P.maxpool_3x3s2_ceil(x)
    P.set_maxpool_impl("custom_vjp")
    got = P.maxpool_3x3s2_ceil(x)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    cot = jnp.asarray(rng.standard_normal(ref.shape).astype(np.float32))
    P.set_maxpool_impl("autodiff")
    g_ref = jax.grad(lambda v: (P.maxpool_3x3s2_ceil(v) * cot).sum())(x)
    P.set_maxpool_impl("custom_vjp")
    g_got = jax.grad(lambda v: (P.maxpool_3x3s2_ceil(v) * cot).sum())(x)
    # identical winners; tiny diffs only from summation order when one input
    # wins several overlapping windows
    np.testing.assert_allclose(np.asarray(g_ref), np.asarray(g_got),
                               rtol=1e-5, atol=1e-6)


def test_custom_vjp_tie_semantics_match_select_and_scatter():
    """A 9-way tie inside a window: the custom backward must pick the same
    (first, row-major) winner select_and_scatter picks."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(np.repeat(
        rng.standard_normal((1, 7, 7, 1)).astype(np.float32), 4, axis=3))
    x = x.at[0, 2:5, 2:5, :].set(1.0)
    cot = jnp.asarray(rng.standard_normal((1, 3, 3, 4)).astype(np.float32))
    P.set_maxpool_impl("autodiff")
    g_ref = jax.grad(lambda v: (P.maxpool_3x3s2_ceil(v) * cot).sum())(x)
    P.set_maxpool_impl("custom_vjp")
    g_got = jax.grad(lambda v: (P.maxpool_3x3s2_ceil(v) * cot).sum())(x)
    np.testing.assert_allclose(np.asarray(g_ref), np.asarray(g_got),
                               rtol=1e-5, atol=1e-6)


def test_bf16_dtype_preserved():
    x = jnp.ones((1, 13, 13, 8), jnp.bfloat16)
    assert P.maxpool_3x3s2_ceil(x).dtype == jnp.bfloat16
    P.set_maxpool_impl("custom_vjp")
    assert P.maxpool_3x3s2_ceil(x).dtype == jnp.bfloat16
