"""ZeRO-3 parameter sharding (ISSUE 18 / r21, mesh.shard_params —
parallel/buckets.py gather_param_tree + train/step.py just-in-time
gather): the config ladder validation, the kill-switch lowered-text
identity (shard_params off ≡ the zero2 step, byte-identical), the CPU
loss-trajectory EQUALITY grid zero3 vs zero2 across {bucketed on/off} x
{grad_accum 1,2} (MiniNet here, the model zoo on the trainer lane below),
the lowered-HLO gather witnesses (gathers == buckets + a dependency-free
(all_gather, conv/dot) pair), comm telemetry (`comm/gathers`,
`comm/gather_wire_bytes`), checkpoint retopology across zero2 ↔ zero3 and
the zero1-era parity gate, the typed GeometryReceiptError refusals, and
the live elastic k=1 resize cell under zero3."""

import io
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ElasticConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
    get_config,
)
from distributed_vgg_f_tpu.parallel.buckets import (
    build_bucket_layout,
    hlo_overlap_report,
    sharding_basis,
)
from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.parallel.zero import (
    flat_param_count,
    padded_flat_size,
    train_state_specs,
)
from distributed_vgg_f_tpu.resilience.errors import GeometryReceiptError
from distributed_vgg_f_tpu.train.state import TrainState
from distributed_vgg_f_tpu.train.step import build_train_step

from test_comm_buckets import _batches, _mesh8, _MiniNet


# ------------------------------------------------------------------- config
def test_config_zero3_ladder():
    """`mesh.shard_params` rides the cumulative ladder: it requires the
    ZeRO-2 frame, labels as zero3, and the flagship deliberately keeps
    shipping zero2 (the honest claim at VGG-F scale is the structural
    receipts, not a flagship win)."""
    assert MeshConfig(shard_opt_state=True, shard_gradients=True,
                      shard_params=True).sharding_label == "zero3"
    with pytest.raises(ValueError, match="shard_params"):
        MeshConfig(shard_opt_state=True, shard_params=True)
    with pytest.raises(ValueError, match="shard_params"):
        MeshConfig(shard_params=True)
    # shard_gradients without zero1 DOWNGRADES (the trainer precedent),
    # and the downgrade cascades through the whole ladder label
    assert MeshConfig(shard_gradients=True).sharding_label == "dp"
    assert get_config("vggf_imagenet_dp").mesh.shard_params is False
    assert get_config("vggf_imagenet_dp").mesh.sharding_label == "zero2"
    # the single source both the config label and the step receipt use
    assert sharding_basis(True, True, True) == "zero3"
    assert sharding_basis(True, True, False) == "zero2"


def test_state_create_rejects_shard_params_without_zero1():
    import optax
    model = _MiniNet()
    with pytest.raises(ValueError, match="shard_params"):
        TrainState.create(model, optax.sgd(0.1), jax.random.key(0),
                          jnp.zeros((1, 16, 16, 3), jnp.float32),
                          shard_params=True)


def test_step_rejects_shard_params_without_zero2():
    import optax
    model = _MiniNet()
    mesh = build_mesh(MeshSpec(("data",), (0,)))
    with pytest.raises(ValueError, match="shard_params"):
        build_train_step(model, optax.sgd(0.1), mesh, weight_decay=0.0,
                         zero1=True, shard_gradients=False,
                         shard_params=True)


# ------------------------------------------------- step builders for grids
def _build(mesh, model, *, zero3=False, bucket_mb=0.0, accum=1,
           reduce_dtype="float32", clip=0.0, ema=0.0, sample_hw=16):
    """The zero2/zero3 pair builder: identical to test_comm_buckets._build
    at the ZeRO-2 basis, plus the shard_params layer when zero3=True."""
    import optax
    tx = optax.sgd(0.05, momentum=0.9)
    sample = jnp.zeros((1, sample_hw, sample_hw, 3), jnp.float32)
    shapes = jax.eval_shape(
        lambda r: TrainState.create(model, tx, r, sample, zero1_shards=8),
        jax.random.key(0))
    p_struct = shapes.params
    layout = None
    if bucket_mb > 0:
        layout = build_bucket_layout(p_struct, 8,
                                     int(bucket_mb * 1024 * 1024))
        padded = layout.total_padded
    else:
        padded = padded_flat_size(flat_param_count(p_struct), 8)

    def create(r):
        return TrainState.create(model, tx, r, sample, zero1_shards=8,
                                 bucket_layout=layout, shard_params=zero3,
                                 ema=ema > 0)

    specs = train_state_specs(jax.eval_shape(create, jax.random.key(0)),
                              padded, "data", shard_params=zero3)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    state = jax.jit(create, out_shardings=shardings)(jax.random.key(0))
    step = build_train_step(model, tx, mesh, weight_decay=1e-4, zero1=True,
                            state_specs=specs, grad_accum_steps=accum,
                            shard_gradients=True, shard_params=zero3,
                            params_struct=p_struct if zero3 else None,
                            comm_bucket_mb=bucket_mb,
                            reduce_dtype=reduce_dtype, grad_clip_norm=clip,
                            ema_decay=ema)
    return state, step, p_struct, layout


def _run(mesh, model, batches, base, n=3, **kw):
    state, step, p_struct, layout = _build(mesh, model, **kw)
    losses = []
    for b in batches[:n]:
        state, m = step(state, b, base)
        losses.append(float(jax.device_get(m["loss"])))
    return losses, state, step, p_struct, layout


def _tree_of(state, p_struct, layout, leaf):
    """Host-side flat-shard -> tree inversion (what trainer.params_tree
    does), for comparing zero3 state against zero2's trees."""
    from distributed_vgg_f_tpu.parallel.zero import _unflatten_like
    vec = jnp.asarray(jax.device_get(leaf))
    if layout is not None:
        return jax.device_get(layout.from_global(vec))
    n = flat_param_count(p_struct)
    return jax.device_get(_unflatten_like(vec[:n], p_struct))


# ----------------------------------------------- loss-trajectory EQUALITY
def test_equality_grid_zero3_vs_zero2_mininet(devices8):
    """The r21 acceptance grid at MiniNet scale: zero3 produces the
    BITWISE-equal loss trajectory of the matching zero2 cell across
    {bucketed on/off} x {grad_accum 1,2} — the gather-once design runs
    literally zero2's math on the gathered tree (DESIGN.md §18), so the
    pin is equality, not tolerance. EMA rides the flat shard and inverts
    to exactly zero2's EMA tree."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh)
    base = jax.jit(lambda: jax.random.key(1))()
    for bucket_mb in (0.0, 0.0005):
        for accum in (1, 2):
            kw = dict(bucket_mb=bucket_mb, accum=accum, ema=0.9,
                      clip=1.0)
            ref, st2, _, p_struct, layout = _run(mesh, model, batches,
                                                 base, **kw)
            l3, st3, _, _, _ = _run(mesh, model, batches, base,
                                    zero3=True, **kw)
            assert l3 == ref, \
                f"bucket={bucket_mb} accum={accum}: {l3} != {ref}"
            # params persisted as the 1/N flat vector, inverted exactly
            assert st3.params.ndim == 1
            t3 = _tree_of(st3, p_struct, layout, st3.params)
            for a, b in zip(jax.tree.leaves(jax.device_get(st2.params)),
                            jax.tree.leaves(t3)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            e3 = _tree_of(st3, p_struct, layout, st3.ema_params)
            for a, b in zip(
                    jax.tree.leaves(jax.device_get(st2.ema_params)),
                    jax.tree.leaves(e3)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------ kill-switch identity
def test_zero3_kill_switch_lowered_text_identity(devices8):
    """`mesh.shard_params` unset lowers to EXACTLY the zero2 step — the
    off-identity pin every kill-switch in this repo carries; the zero3
    build must differ (it had better be gathering something)."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    for bucket_mb in (0.0, 0.0005):
        state, off, _, _ = _build(mesh, model, bucket_mb=bucket_mb)
        text_off = off.lower(state, batches[0], base).as_text()
        _, z2, _, _ = _build(mesh, model, bucket_mb=bucket_mb)
        assert text_off == z2.lower(state, batches[0], base).as_text(), \
            "zero2 step stopped being deterministic"
        st3, on, _, _ = _build(mesh, model, zero3=True,
                                  bucket_mb=bucket_mb)
        assert on.lower(st3, batches[0], base).as_text() != text_off


# ------------------------------------------------- lowered-HLO assertions
def test_hlo_zero3_bucketed_gather_witness(devices8):
    """r21 acceptance: the bucketed zero3 lowering carries one param
    all_gather PER BUCKET and a committed dependency-free (all_gather,
    conv/dot) pair — each gather depends only on the param-shard step
    input, so the overlap license is structural."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    state, step, _, _ = _build(mesh, model, zero3=True, bucket_mb=0.0005)
    rep = hlo_overlap_report(step.lower(state, batches[0], base).as_text())
    assert step.comm_meta["sharding"] == "zero3"
    assert step.comm_meta["bucketed"] is True
    assert step.comm_meta["buckets"] >= 2
    assert step.comm_meta["gathers"] == step.comm_meta["buckets"]
    assert rep["gathers"] == step.comm_meta["buckets"]
    assert rep["gather_overlap_capable"] is True
    assert rep["gather_witness"] is not None
    # the scatter side keeps its r14 witness too
    assert rep["collective_counts"]["reduce_scatter"] \
        == step.comm_meta["buckets"]
    assert rep["overlap_capable"] is True


def test_hlo_zero3_monolithic_single_gather(devices8):
    """The unbucketed zero3 exchange gathers ONCE — and that one gather
    feeds all compute, so it is honestly NOT overlap-capable (the same
    monolithic-vs-bucketed story the scatter told in r14)."""
    mesh = _mesh8(devices8)
    model = _MiniNet()
    batches = _batches(mesh=mesh, n=1)
    base = jax.jit(lambda: jax.random.key(1))()
    state, step, _, _ = _build(mesh, model, zero3=True)
    rep = hlo_overlap_report(step.lower(state, batches[0], base).as_text())
    assert step.comm_meta["gathers"] == 1
    assert rep["gathers"] == 1
    assert rep["gather_overlap_capable"] is False
    # zero2's trailing re-sync gather exists but is NOT gather-capable
    # either (it depends on the whole update) — gathers == 1 there too
    st2, z2, _, _ = _build(mesh, model)
    rep2 = hlo_overlap_report(z2.lower(st2, batches[0], base).as_text())
    assert z2.comm_meta["gathers"] == 1
    assert rep2["gather_overlap_capable"] is False


# --------------------------------------------------------------- telemetry
def test_zero3_comm_counters_and_meta(devices8):
    from distributed_vgg_f_tpu import telemetry
    from distributed_vgg_f_tpu.telemetry import schema
    telemetry.configure(enabled=True)
    try:
        mesh = _mesh8(devices8)
        model = _MiniNet()
        batches = _batches(mesh=mesh, n=2)
        base = jax.jit(lambda: jax.random.key(1))()
        state, step, _, _ = _build(mesh, model, zero3=True,
                                      bucket_mb=0.0005)
        reg = telemetry.get_registry()
        reg.delta("z3_test")
        for b in batches:
            state, _ = step(state, b, base)
        delta = reg.delta("z3_test")
        meta = step.comm_meta
        assert meta["sharding"] == "zero3" and meta["bucketed"] is True
        assert meta["gathers"] == meta["buckets"]
        assert delta.get("comm/gathers") == 2 * meta["gathers"]
        assert delta.get("comm/gather_wire_bytes") \
            == 2 * meta["gather_bytes"]
        # the per-window JSONL block schema-validates with the r21 fields
        errors = []
        schema.validate_comm_block(dict(meta), "t", errors)
        assert errors == []
    finally:
        telemetry.reset()


# ------------------------------------------------ typed receipt refusals
def _fake_manager(opt_meta, p_meta, extra):
    return types.SimpleNamespace(
        best_step=lambda: 1,
        state_metadata=lambda step: {"opt_state": opt_meta,
                                     "params": p_meta},
        extra_at=lambda step: extra,
        restore=lambda template, step: (_ for _ in ()).throw(
            AssertionError("restore reached before the receipt check")))


def test_geometry_receipt_refusals(devices8):
    """A wrong `param_layout` receipt refuses with the TYPED class before
    a single array is read — never a shape error (the r21 contract)."""
    import optax
    mesh = _mesh8(devices8)
    model = _MiniNet()
    tx = optax.sgd(0.05, momentum=0.9)
    sample = jnp.zeros((1, 16, 16, 3), jnp.float32)
    shapes = jax.eval_shape(
        lambda r: TrainState.create(model, tx, r, sample, zero1_shards=8),
        jax.random.key(0))
    p_struct = shapes.params
    padded = padded_flat_size(flat_param_count(p_struct), 8)
    flat = jax.ShapeDtypeStruct((padded,), jnp.float32)
    opt_meta = jax.eval_shape(tx.init, flat)

    def create():
        return TrainState.create(model, tx, jax.random.key(0), sample,
                                 zero1_shards=8, shard_params=True)
    specs = train_state_specs(jax.eval_shape(create), padded, "data",
                              shard_params=True)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    template = jax.jit(create, out_shardings=shardings)()

    from distributed_vgg_f_tpu.checkpoint.retopology import (
        restore_any_topology)
    # (a) receipt length disagrees with the saved vector
    mgr = _fake_manager(opt_meta, flat, {
        "param_layout": {"kind": "canonical_flat", "num_shards": 8,
                         "total_padded": padded + 8}})
    with pytest.raises(GeometryReceiptError, match="total_padded"):
        restore_any_topology(mgr, template, tx, opt_shardings=None,
                             target_padded=padded,
                             params_tree_struct=p_struct)
    # (b) bucketed_flat kind with no opt receipt naming the geometry
    mgr = _fake_manager(opt_meta, flat, {
        "param_layout": {"kind": "bucketed_flat", "num_shards": 8,
                         "total_padded": padded}})
    with pytest.raises(GeometryReceiptError, match="bucket"):
        restore_any_topology(mgr, template, tx, opt_shardings=None,
                             target_padded=padded,
                             params_tree_struct=p_struct)
    # (c) receipt present but the saved params are a TREE
    mgr = _fake_manager(opt_meta, p_struct, {
        "param_layout": {"kind": "canonical_flat", "num_shards": 8,
                         "total_padded": padded}})
    with pytest.raises(GeometryReceiptError, match="tree"):
        restore_any_topology(mgr, template, tx, opt_shardings=None,
                             target_padded=padded,
                             params_tree_struct=p_struct)


# ------------------------------------------------------- trainer-level
def _trainer_cfg(model="vggf", steps=3, ema=0.0, ckpt=None, **mesh_kw):
    tr = TrainConfig(steps=steps, seed=0, ema_decay=ema)
    if ckpt is not None:
        import dataclasses
        tr = dataclasses.replace(tr, checkpoint_dir=str(ckpt),
                                 checkpoint_every_steps=1)
    return ExperimentConfig(
        name="zero3_grid",
        model=ModelConfig(name=model, num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          momentum=0.9, weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=32,
                        global_batch_size=16, num_train_examples=64),
        mesh=MeshConfig(num_data=8, **mesh_kw),
        train=tr,
    )


Z2 = dict(shard_opt_state=True, shard_gradients=True, comm_bucket_mb=0.25)
Z3 = dict(Z2, shard_params=True)


def _trainer_run(cfg, n_steps=3):
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = trainer.restore_or_init()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=cfg.data.global_batch_size,
                          image_size=32, num_classes=10, seed=0)
    losses = []
    for _ in range(n_steps):
        state, m = trainer.train_step(state, trainer.shard(next(ds)), rng)
        losses.append(float(jax.device_get(m["loss"])))
    return trainer, state, losses


@pytest.mark.parametrize("model", [
    "vggf",
    pytest.param("vgg16", marks=pytest.mark.slow),
    pytest.param("resnet50", marks=pytest.mark.slow),
    pytest.param("vit_s16", marks=pytest.mark.slow),
])
def test_equality_grid_real_models_zero3(model):
    """The zoo lane of the r21 acceptance grid: each model's zero3 CPU
    loss trajectory EQUALS its zero2 one, bucketed and monolithic (vggf
    rides the default loop as the canary; the rest are slow-lane)."""
    for extra in ({}, {"comm_bucket_mb": 0.0}):
        ref = _trainer_run(_trainer_cfg(model, **dict(Z2, **extra)))[2]
        l3 = _trainer_run(_trainer_cfg(model, **dict(Z3, **extra)))[2]
        assert l3 == ref, f"{model} {extra}: {l3} != {ref}"


@pytest.mark.slow
def test_zero3_checkpoint_retopology(tmp_path):
    """The r21 any-geometry restore gates: (a) zero3 roundtrip, (b) zero3
    checkpoint -> zero2 trainer (flat -> tree), (c) zero2 checkpoint ->
    zero3 trainer (tree -> flat), (d) the ZERO1-ERA parity gate — a
    checkpoint written before shard_gradients/shard_params existed (tree
    params + canonical flat opt) restores into the bucketed zero3 run
    with exactly equal per-parameter values."""
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    def params_of(tr, state, leaf=None):
        return jax.tree.leaves(jax.device_get(
            tr.params_tree(state.params if leaf is None else leaf)))

    # (a) + (b): zero3 write, zero3 + zero2 reads
    tr3, st3, _ = _trainer_run(_trainer_cfg(ema=0.9, ckpt=tmp_path / "z3",
                                            **Z3), n_steps=2)
    tr3.checkpoints.save(st3, force=True, extra=tr3._opt_layout_extra())
    tr3.checkpoints.wait()
    assert tr3._opt_layout_extra()["param_layout"]["kind"] \
        == "bucketed_flat"
    r3 = tr3.restore_or_init()
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(st3.params)),
        np.asarray(jax.device_get(r3.params)))
    tr2 = Trainer(_trainer_cfg(ema=0.9, ckpt=tmp_path / "z3", **Z2),
                  logger=MetricLogger(stream=io.StringIO()))
    r2 = tr2.restore_or_init()
    for a, b in zip(params_of(tr3, st3),
                    jax.tree.leaves(jax.device_get(r2.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(params_of(tr3, st3, st3.ema_params),
                    jax.tree.leaves(jax.device_get(r2.ema_params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # (c): zero2 write, zero3 read
    tr2b, st2b, _ = _trainer_run(_trainer_cfg(ckpt=tmp_path / "z2", **Z2),
                                 n_steps=2)
    tr2b.checkpoints.save(st2b, force=True,
                          extra=tr2b._opt_layout_extra())
    tr2b.checkpoints.wait()
    tr3c = Trainer(_trainer_cfg(ckpt=tmp_path / "z2", **Z3),
                   logger=MetricLogger(stream=io.StringIO()))
    r3c = tr3c.restore_or_init()
    for a, b in zip(jax.tree.leaves(jax.device_get(st2b.params)),
                    params_of(tr3c, r3c)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # (d): zero1-era write (tree params, canonical flat opt), zero3 read
    tr1, st1, _ = _trainer_run(
        _trainer_cfg(ckpt=tmp_path / "z1", shard_opt_state=True),
        n_steps=2)
    tr1.checkpoints.save(st1, force=True)
    tr1.checkpoints.wait()
    tr3d = Trainer(_trainer_cfg(ckpt=tmp_path / "z1", **Z3),
                   logger=MetricLogger(stream=io.StringIO()))
    r3d = tr3d.restore_or_init()
    assert r3d.params.ndim == 1
    for a, b in zip(jax.tree.leaves(jax.device_get(st1.params)),
                    params_of(tr3d, r3d)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_elastic_resize_under_zero3(tmp_path):
    """The r21 elastic cell: preempt k=1 of 4 under bucketed zero3 —
    the live reshard re-interleaves the flat param/EMA vectors onto 3
    shards and the trajectory EQUALS the restart-from-checkpoint control
    (the r19 pin, extended to the zero3 layout)."""
    import json
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    def cfg_of(ckpt, *, elastic_on=True, faults="", steps=5):
        import dataclasses
        cfg = _trainer_cfg(ckpt=ckpt, **Z3)
        cfg = dataclasses.replace(
            cfg,
            data=dataclasses.replace(cfg.data, global_batch_size=12,
                                     num_train_examples=48),
            optim=dataclasses.replace(cfg.optim, reference_batch_size=12),
            mesh=dataclasses.replace(
                cfg.mesh, num_data=0,
                elastic=ElasticConfig(enabled=elastic_on,
                                      batch_policy="keep_global")),
            train=dataclasses.replace(cfg.train, steps=steps, log_every=1,
                                      checkpoint_every_steps=100,
                                      eval_every_steps=10_000,
                                      fault_injection=faults))
        return cfg

    def run_fit(cfg, n):
        mesh = build_mesh(MeshSpec(("data",), (n,)),
                          devices=jax.devices()[:n])
        stream = io.StringIO()
        logger = MetricLogger(stream=io.StringIO())
        logger._file = stream
        tr = Trainer(cfg, mesh=mesh, logger=logger)
        state = tr.fit()
        recs = [json.loads(ln) for ln in stream.getvalue().splitlines()]
        return recs, state

    def losses(recs):
        return {r["step"]: r["loss"] for r in recs
                if r.get("event") == "train"}

    recs, state = run_fit(cfg_of(tmp_path / "el",
                                 faults="preempt@rank1:2"), 4)
    resizes = [r for r in recs if r.get("event") == "elastic_resize"]
    assert resizes and resizes[0]["topology"] == "elastic_4to3"
    assert state.params.ndim == 1  # still the flat shard on 3 survivors
    el = losses(recs)
    recs_s, _ = run_fit(cfg_of(tmp_path / "stop", elastic_on=False,
                               faults="preempt@rank1:2"), 4)
    recs_r, _ = run_fit(cfg_of(tmp_path / "stop"), 3)
    ctrl = {**losses(recs_s), **losses(recs_r)}
    for s in sorted(el):
        assert el[s] == ctrl[s], (s, el[s], ctrl[s])
