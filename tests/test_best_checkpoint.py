"""Best-eval checkpoint tracking (train.track_best_eval): fit() keeps the
best-top1 checkpoint in a single replaced slot under <checkpoint_dir>/best,
with the score in its metadata; a resumed run must not regress the durable
best; restorable by pointing checkpoint_dir at best/."""

import io
import json
import os

import pytest

from distributed_vgg_f_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrainConfig,
)


def _cfg(tmp_path, steps=30, **train_kw):
    return ExperimentConfig(
        name="best_ckpt_test",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.02, reference_batch_size=16),
        data=DataConfig(name="cifar10", image_size=32, global_batch_size=16,
                        num_train_examples=64, num_eval_examples=64),
        mesh=MeshConfig(num_data=0),
        train=TrainConfig(steps=steps, seed=0, log_every=10,
                          eval_every_steps=10,
                          checkpoint_dir=str(tmp_path / "ckpt"),
                          checkpoint_every_steps=10, **train_kw),
    )


def _events(path):
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


@pytest.mark.slow
def test_best_checkpoint_tracks_max_eval(tmp_path):
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = _cfg(tmp_path)
    jsonl = str(tmp_path / "metrics.jsonl")
    trainer = Trainer(cfg, logger=MetricLogger(jsonl_path=jsonl,
                                               stream=io.StringIO()))
    # created lazily by fit(): a trainer used only for eval/predict must not
    # litter best/ directories
    assert trainer.best_checkpoints is None
    eval_ds = build_dataset(cfg.data, "eval", seed=0)
    trainer.fit(eval_dataset=eval_ds)
    assert trainer.best_checkpoints is not None

    evals = [e for e in _events(jsonl) if e["event"] == "eval"]
    assert len(evals) == 3
    best_seen = max(e["eval_top1"] for e in evals)
    extra = trainer.best_checkpoints.latest_extra()
    assert extra is not None
    # the single best slot records exactly the max observed eval score,
    # at the step where it was first achieved
    assert extra["eval_top1"] == best_seen
    first_best = next(e for e in evals if e["eval_top1"] == best_seen)
    assert extra["step"] == first_best["step"]
    assert len(trainer.best_checkpoints.all_steps()) == 1

    # restorable via the documented flag: train.restore_from_best
    import dataclasses
    best_cfg = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, restore_from_best=True))
    t2 = Trainer(best_cfg, logger=MetricLogger(stream=io.StringIO()))
    state = t2.restore_or_init()
    import jax
    assert int(jax.device_get(state.step)) == extra["step"]
    # the restore path must not have created a nested best/best/
    assert not os.path.isdir(os.path.join(cfg.train.checkpoint_dir,
                                          "best", "best"))


@pytest.mark.slow
def test_resume_does_not_regress_best(tmp_path):
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = _cfg(tmp_path, steps=20)
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    eval_ds = build_dataset(cfg.data, "eval", seed=0)
    trainer.fit(eval_dataset=eval_ds)
    best_before = trainer.best_checkpoints.latest_extra()

    # continue for a few more steps in a fresh Trainer (simulated restart);
    # the slot may only change if a later eval STRICTLY beats the durable
    # best — this is what the latest_extra() seeding guarantees
    jsonl2 = str(tmp_path / "metrics2.jsonl")
    cfg2 = _cfg(tmp_path, steps=30)
    t2 = Trainer(cfg2, logger=MetricLogger(jsonl_path=jsonl2,
                                           stream=io.StringIO()))
    t2.fit(eval_dataset=eval_ds)
    best_after = t2.best_checkpoints.latest_extra()
    assert best_after["eval_top1"] >= best_before["eval_top1"]
    run2_evals = [e["eval_top1"] for e in _events(jsonl2)
                  if e["event"] == "eval"]
    if max(run2_evals) > best_before["eval_top1"]:
        assert best_after["eval_top1"] == max(run2_evals)
    else:
        # nothing beat the durable best — the slot must be UNCHANGED (a
        # broken seeding would overwrite it with run 2's first eval)
        assert best_after == best_before


@pytest.mark.slow
def test_track_best_disabled(tmp_path):
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg = _cfg(tmp_path, steps=10, track_best_eval=False)
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    assert trainer.best_checkpoints is None
    trainer.fit(eval_dataset=build_dataset(cfg.data, "eval", seed=0))
    # even a fit with periodic eval creates neither manager nor directory
    assert trainer.best_checkpoints is None
    assert not os.path.isdir(os.path.join(cfg.train.checkpoint_dir, "best"))
