"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py): the layout
swap — sequence-sharded in, head-sharded for the local attention, sequence-
sharded out — must be exactly full attention, in both masking modes, for
both local kernels, including gradients (beyond reference parity; the
all-to-all half of the SP story next to tests/test_ring_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.parallel.ring_attention import (
    full_attention_reference,
    ring_attention,
)
from distributed_vgg_f_tpu.parallel.ulysses import ulysses_attention


def _qkv(dtype=jnp.float32, b=2, t=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32).astype(dtype)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention_fp32(devices8, causal):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv()
    got = np.asarray(ulysses_attention(q, k, v, mesh, causal=causal))
    want = np.asarray(full_attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ulysses_matches_full_attention_bf16(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(jnp.bfloat16)
    got = np.asarray(ulysses_attention(q, k, v, mesh), np.float32)
    want = np.asarray(full_attention_reference(q, k, v), np.float32)
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_kernel(devices8, causal):
    """The flash local kernel (interpreted on CPU) through the all-to-all
    sandwich — the long-T configuration."""
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=128, seed=5)
    got = np.asarray(ulysses_attention(q, k, v, mesh, causal=causal,
                                       kernel="flash", interpret=True))
    want = np.asarray(full_attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_ulysses_gradients(devices8, n):
    """all_to_all transposes to its inverse, so grads must equal the
    oracle's — this layer is for TRAINING, same bar as the ring."""
    mesh = build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])
    q, k, v = _qkv(t=32, seed=7)

    g_uly = jax.grad(lambda *a: jnp.sum(
        ulysses_attention(*a, mesh, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ulysses_flash_gradients(devices8):
    mesh = build_mesh(MeshSpec(("data",), (4,)), devices=jax.devices()[:4])
    q, k, v = _qkv(t=64, seed=9)

    g_uly = jax.grad(lambda *a: jnp.sum(
        ulysses_attention(*a, mesh, kernel="flash", interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ulysses_agrees_with_ring(devices8):
    """Two independent SP layouts computing the same mathematical object —
    disagreement means one of them is wrong."""
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(seed=13)
    uly = np.asarray(ulysses_attention(q, k, v, mesh, causal=True))
    ring = np.asarray(ring_attention(q, k, v, mesh, causal=True))
    np.testing.assert_allclose(uly, ring, rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_bad_shapes(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=60)                  # T not divisible by 8
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, mesh)
    q, k, v = _qkv(h=4)                   # H=4 < axis size 8
    with pytest.raises(ValueError, match="use the ring"):
        ulysses_attention(q, k, v, mesh)
