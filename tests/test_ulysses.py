"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py): the layout
swap — sequence-sharded in, head-sharded for the local attention, sequence-
sharded out — must be exactly full attention, in both masking modes, for
both local kernels, including gradients (beyond reference parity; the
all-to-all half of the SP story next to tests/test_ring_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
from distributed_vgg_f_tpu.parallel.ring_attention import (
    full_attention_reference,
    ring_attention,
)
from distributed_vgg_f_tpu.parallel.ulysses import ulysses_attention


def _qkv(dtype=jnp.float32, b=2, t=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32).astype(dtype)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention_fp32(devices8, causal):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv()
    got = np.asarray(ulysses_attention(q, k, v, mesh, causal=causal))
    want = np.asarray(full_attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ulysses_matches_full_attention_bf16(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(jnp.bfloat16)
    got = np.asarray(ulysses_attention(q, k, v, mesh), np.float32)
    want = np.asarray(full_attention_reference(q, k, v), np.float32)
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_kernel(devices8, causal):
    """The flash local kernel (interpreted on CPU) through the all-to-all
    sandwich — the long-T configuration."""
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=128, seed=5)
    got = np.asarray(ulysses_attention(q, k, v, mesh, causal=causal,
                                       kernel="flash", interpret=True))
    want = np.asarray(full_attention_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_ulysses_gradients(devices8, n):
    """all_to_all transposes to its inverse, so grads must equal the
    oracle's — this layer is for TRAINING, same bar as the ring."""
    mesh = build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])
    q, k, v = _qkv(t=32, seed=7)

    g_uly = jax.grad(lambda *a: jnp.sum(
        ulysses_attention(*a, mesh, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ulysses_flash_gradients(devices8):
    mesh = build_mesh(MeshSpec(("data",), (4,)), devices=jax.devices()[:4])
    q, k, v = _qkv(t=64, seed=9)

    g_uly = jax.grad(lambda *a: jnp.sum(
        ulysses_attention(*a, mesh, kernel="flash", interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(lambda *a: jnp.sum(
        full_attention_reference(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ulysses_agrees_with_ring(devices8):
    """Two independent SP layouts computing the same mathematical object —
    disagreement means one of them is wrong."""
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(seed=13)
    uly = np.asarray(ulysses_attention(q, k, v, mesh, causal=True))
    ring = np.asarray(ring_attention(q, k, v, mesh, causal=True))
    np.testing.assert_allclose(uly, ring, rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_bad_shapes(devices8):
    mesh = build_mesh(MeshSpec(("data",), (8,)))
    q, k, v = _qkv(t=60)                  # T not divisible by 8
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, mesh)


@pytest.mark.parametrize("h,n", [(6, 4), (6, 8), (4, 8)])
def test_ulysses_indivisible_heads_pad(devices8, h, n):
    """H that doesn't divide the axis (ViT-S/16's H=6 on n=4/8 — VERDICT
    r4 weak #5) zero-pads to ceil(H/n)·n per shard and slices back: exact
    vs full attention, both masking modes."""
    mesh = build_mesh(MeshSpec(("data",), (n,)), devices=jax.devices()[:n])
    for causal in (False, True):
        q, k, v = _qkv(t=8 * n, h=h, seed=17 + h + n)
        got = np.asarray(ulysses_attention(q, k, v, mesh, causal=causal))
        want = np.asarray(full_attention_reference(q, k, v, causal=causal))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5,
                                   err_msg=f"h={h} n={n} causal={causal}")


def test_ulysses_indivisible_heads_gradients(devices8):
    """The VERDICT r4 #7 'done' case verbatim: H=6, n=4, exact incl.
    grads (einsum and flash local kernels)."""
    mesh = build_mesh(MeshSpec(("data",), (4,)), devices=jax.devices()[:4])
    q, k, v = _qkv(t=32, h=6, seed=23)
    for kernel in ("einsum", "flash"):
        g_u = jax.grad(lambda *a: jnp.sum(
            ulysses_attention(*a, mesh, causal=True, kernel=kernel,
                              interpret=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_full = jax.grad(lambda *a: jnp.sum(
            full_attention_reference(*a, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for g, r, name in zip(g_u, g_full, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), rtol=5e-5, atol=5e-5,
                err_msg=f"d{name} kernel={kernel}")


def test_ulysses_comm_model_charges_padding():
    """The comm model stays honest about head padding: H=6 on n=4 charges
    8/6 on wire bytes AND compute; the ring comparison keeps true H."""
    from distributed_vgg_f_tpu.utils.scaling_model import (
        ring_attention_comm_model, ulysses_comm_model)

    u = ulysses_comm_model(1024, 4, heads=6)
    assert u.heads_effective == 8
    assert u.padding_overhead == pytest.approx(8 / 6)
    s_pad = 1 * 1024 * 8 * 64 * 2
    assert u.a2a_bytes == pytest.approx(s_pad * 3 / 4)
    s_true = 1 * 1024 * 6 * 64 * 2
    assert u.ring_wire_bytes == pytest.approx(2 * s_true * 3)
    r = ring_attention_comm_model(1024, 4, heads=6)
    assert u.compute_s == pytest.approx(4 * r.hop_compute_s * 8 / 6)
    # divisible H: no padding, identical to the pre-padding model
    u8 = ulysses_comm_model(1024, 8)
    assert u8.heads_effective == 8 and u8.padding_overhead == 1.0
