"""ZeRO-1 optimizer-state sharding (parallel/zero.py): numerical equivalence
with plain replicated DP, physical sharding of the opt state, and checkpoint
round-trip — all on the 8-virtual-device CPU mesh (SURVEY.md §4)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_vgg_f_tpu.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
from distributed_vgg_f_tpu.train.trainer import Trainer
from distributed_vgg_f_tpu.utils.logging import MetricLogger


def _cfg(shard_opt_state: bool, **optim_kw) -> ExperimentConfig:
    return ExperimentConfig(
        name="zero1_test",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=16,
                          momentum=0.9, weight_decay=1e-4, **optim_kw),
        data=DataConfig(name="synthetic", image_size=32, global_batch_size=16,
                        num_train_examples=64),
        mesh=MeshConfig(num_data=8, shard_opt_state=shard_opt_state),
        train=TrainConfig(steps=3, seed=0),
    )


def _run_steps(cfg, n_steps=3):
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = trainer.init_state()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=cfg.data.global_batch_size, image_size=32,
                          num_classes=10, seed=0)
    metrics = {}
    for _ in range(n_steps):
        state, metrics = trainer.train_step(state, trainer.shard(next(ds)), rng)
    return trainer, state, jax.device_get(metrics)


@pytest.mark.slow
@pytest.mark.parametrize("optim_kw", [{}, {"grad_clip_norm": 0.05}],
                         ids=["sgd_momentum", "with_global_clip"])
def test_zero1_matches_replicated_dp(optim_kw):
    _, state_rep, m_rep = _run_steps(_cfg(False, **optim_kw))
    _, state_z1, m_z1 = _run_steps(_cfg(True, **optim_kw))

    flat_rep = jax.tree.leaves(jax.device_get(state_rep.params))
    flat_z1 = jax.tree.leaves(jax.device_get(state_z1.params))
    for a, b in zip(flat_rep, flat_z1):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    assert m_rep["loss"] == pytest.approx(m_z1["loss"], rel=1e-5)
    assert m_rep["grad_norm"] == pytest.approx(m_z1["grad_norm"], rel=1e-4)


def test_zero1_opt_state_is_physically_sharded():
    trainer, state, _ = _run_steps(_cfg(True), n_steps=1)
    from distributed_vgg_f_tpu.parallel.zero import (
        flat_param_count, padded_flat_size)
    padded = padded_flat_size(flat_param_count(state.params), 8)

    vector_leaves = [l for l in jax.tree.leaves(state.opt_state)
                     if getattr(l, "ndim", 0) >= 1 and l.shape[0] == padded]
    assert vector_leaves, "expected a sharded momentum trace"
    for leaf in vector_leaves:
        assert leaf.sharding.spec == P("data")
        # each device holds exactly 1/8 of the vector
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(padded // 8,)}


@pytest.mark.slow
def test_zero1_checkpoint_roundtrip(tmp_path):
    import dataclasses
    cfg = _cfg(True)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train,
                                       checkpoint_dir=str(tmp_path / "ckpt"),
                                       checkpoint_every_steps=1))
    trainer, state, _ = _run_steps(cfg, n_steps=2)
    assert trainer.checkpoints is not None
    trainer.checkpoints.save(state, force=True)
    trainer.checkpoints.wait()

    restored = trainer.restore_or_init()
    assert int(jax.device_get(restored.step)) == 2
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(state.opt_state),
                    jax.tree.leaves(restored.opt_state)):
        assert a.sharding == b.sharding
        np.testing.assert_allclose(jax.device_get(a), jax.device_get(b))
