"""Model shape/param-count tests (SURVEY.md §4 unit tests: VGG-F ≈ 61M params)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_vgg_f_tpu.config import ModelConfig
from distributed_vgg_f_tpu.models import build_model


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def test_vggf_imagenet_shapes_and_params():
    model = build_model(ModelConfig(name="vggf", num_classes=1000,
                                    compute_dtype="float32"))
    x = jnp.zeros((2, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(lambda: model.init(jax.random.key(0), x,
                                                  train=False))
    logits_shape = jax.eval_shape(
        lambda v: model.apply(v, x, train=False), variables)
    assert logits_shape.shape == (2, 1000)
    n = _param_count(variables["params"])
    # CNN-F (Chatfield et al. 2014): ~61M parameters.
    assert 59e6 < n < 63e6, f"VGG-F param count {n}"


def test_vggf_small_input_forward():
    model = build_model(ModelConfig(name="vggf", num_classes=10,
                                    compute_dtype="float32"))
    x = jnp.zeros((4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_vggf_dropout_train_vs_eval():
    model = build_model(ModelConfig(name="vggf", num_classes=10,
                                    compute_dtype="float32"))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    eval_logits = model.apply(variables, x, train=False)
    train_logits = model.apply(variables, x, train=True,
                               rngs={"dropout": jax.random.key(2)})
    # dropout must make train-mode differ from eval-mode
    assert not np.allclose(np.asarray(eval_logits), np.asarray(train_logits))


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_vggf_compute_dtype_output_fp32(dtype):
    model = build_model(ModelConfig(name="vggf", num_classes=10,
                                    compute_dtype=dtype))
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32
    # params stay fp32 regardless of compute dtype
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32


def test_conv1_space_to_depth_matches_plain_conv():
    """The s2d stem (models/vggf.py Conv1SpaceToDepth) must match the plain
    11x11/4 VALID conv it replaces (up to summation-order rounding), for both
    the 224 (s2d path) and a non-multiple-of-4 size (fallback path)."""
    from jax import lax

    from distributed_vgg_f_tpu.models.vggf import Conv1SpaceToDepth

    mod = Conv1SpaceToDepth(features=64, compute_dtype=jnp.float32)
    for size in (224, 32, 50):  # 50 % 4 != 0 → fallback path
        x = jax.random.normal(jax.random.key(size), (2, size, size, 3),
                              jnp.float32)
        variables = mod.init(jax.random.key(0), x)
        got = mod.apply(variables, x)
        k = variables["params"]["kernel"]
        want = lax.conv_general_dilated(
            x, k, window_strides=(4, 4), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + variables["params"]["bias"]
        assert variables["params"]["kernel"].shape == (11, 11, 3, 64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
