"""Per-step stall attribution: turn "throughput dropped" into a named cause.

The trainer's step loop spends its wall time in exactly four places a host
can do something about, and each leaves a distinct telemetry signature:

- **infeed_bound** — the consumer blocked on `next(ds)`: the host pipeline
  (decode, storage, prefetch) is not keeping up. Signature: high
  "infeed"-category span occupancy / `host_wait` fraction, prefetch queue
  depth pinned at 0.
- **checkpoint_bound** — the loop blocked on checkpoint machinery (forced
  saves, collision replacement, manifest flushes). Signature:
  "checkpoint"-category span occupancy.
- **guard_stalled** — steps are completing but the non-finite guard is
  discarding their updates: wall time is being spent, training is not
  happening. Signature: `resilience/nonfinite_skips` incremented in the
  window.
- **compute_bound** — none of the above: the device is the bottleneck,
  which for a throughput paper is the GOOD verdict.

Two input paths produce the same verdict record:

- `classify(...)` takes the trainer's own accumulated wall/wait seconds
  (exact, zero extra cost — the trainer already times its feed waits);
- `occupancy_from_spans(...)` + `StallAttributor.window_from_spans(...)`
  derive the same fractions from the span ring buffer (telemetry/spans.py),
  for consumers that only have the trace — tests, offline analysis of an
  exported Chrome trace, the chaos suite's synthetic-iterator check.

Priority when signatures overlap: guard_stalled first — a run skipping
every update is broken no matter how fast its pipeline is. Between
checkpoint_bound and infeed_bound the LARGER blocked fraction wins, with
checkpoint winning exact ties (a checkpoint stall usually ALSO starves the
infeed queue, so at equal evidence the deeper cause is named); a window
that is 60% infeed-blocked and 30% checkpoint-blocked is infeed_bound.
compute_bound is the residual — and for a throughput paper, the GOOD
verdict.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional, Sequence, Tuple

#: The verdict taxonomy (README "Observability"). guard_stalled outranks
#: everything; checkpoint vs infeed is decided by the larger blocked
#: fraction (checkpoint wins ties); compute_bound is the residual.
VERDICTS = ("guard_stalled", "checkpoint_bound", "infeed_bound",
            "compute_bound")

#: Span categories that count toward each attributable bucket.
INFEED_CATEGORIES = ("infeed",)
CHECKPOINT_CATEGORIES = ("checkpoint",)


def classify(wall_s: float, infeed_wait_s: float = 0.0,
             checkpoint_wait_s: float = 0.0, guard_skips: int = 0, *,
             infeed_threshold: float = 0.25,
             checkpoint_threshold: float = 0.25,
             queue_depth: Optional[float] = None) -> Dict[str, object]:
    """One verdict record for a logged interval.

    `wall_s` is the interval's wall-clock span; the wait inputs are the time
    the CONSUMER was blocked in each bucket inside it. `queue_depth` (the
    prefetch queue's last observed depth) rides along as corroboration: an
    infeed_bound verdict with a full queue is suspicious and worth seeing.
    """
    wall = max(float(wall_s), 1e-9)
    infeed_fraction = min(1.0, max(0.0, float(infeed_wait_s)) / wall)
    ckpt_fraction = min(1.0, max(0.0, float(checkpoint_wait_s)) / wall)
    # Candidacy is per-bucket (each fraction against ITS OWN threshold);
    # only between two qualified candidates does the larger fraction win
    # (checkpoint taking ties). An unqualified competitor must not veto a
    # qualified one — with asymmetric thresholds, infeed 0.35 under a 0.4
    # threshold must not drag checkpoint 0.30 (over its 0.25 threshold)
    # down to compute_bound (code-review r8).
    ckpt_candidate = ckpt_fraction >= checkpoint_threshold
    infeed_candidate = infeed_fraction >= infeed_threshold
    if guard_skips > 0:
        verdict = "guard_stalled"
    elif ckpt_candidate and (not infeed_candidate
                             or ckpt_fraction >= infeed_fraction):
        verdict = "checkpoint_bound"
    elif infeed_candidate:
        verdict = "infeed_bound"
    else:
        verdict = "compute_bound"
    record: Dict[str, object] = {
        "verdict": verdict,
        "infeed_fraction": round(infeed_fraction, 4),
        "checkpoint_fraction": round(ckpt_fraction, 4),
    }
    if guard_skips:
        record["guard_skips"] = int(guard_skips)
    if queue_depth is not None:
        record["queue_depth"] = queue_depth
    return record


def occupancy_from_spans(spans: Iterable[Sequence],
                         start_ns: int, end_ns: int) -> Dict[str, float]:
    """Per-category busy seconds inside [start_ns, end_ns) from span tuples
    (telemetry/spans.py shape). Overlapping spans of the SAME category are
    merged (union, not sum) — two threads both blocked on the infeed at the
    same instant is one stalled instant, and double-counting would push a
    fraction past 1.0."""
    window = max(0, int(end_ns) - int(start_ns))
    by_cat: Dict[str, list] = {}
    for name, cat, s0, dur, *_rest in spans:
        s1 = s0 + dur
        lo, hi = max(s0, start_ns), min(s1, end_ns)
        if hi > lo:
            by_cat.setdefault(cat, []).append((lo, hi))
    out: Dict[str, float] = {}
    for cat, ivals in by_cat.items():
        ivals.sort()
        busy = 0
        cur_lo, cur_hi = ivals[0]
        for lo, hi in ivals[1:]:
            if lo <= cur_hi:
                cur_hi = max(cur_hi, hi)
            else:
                busy += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
        busy += cur_hi - cur_lo
        out[cat] = min(busy, window) / 1e9
    return out


class StallAttributor:
    """Stateful helper binding the classification to the live registry and
    span recorder: `window(...)` for callers with their own accumulated
    waits (the trainer), `window_from_spans(...)` for callers that only
    bracketed the interval (tests, offline traces)."""

    def __init__(self, registry=None, recorder=None, *,
                 infeed_threshold: float = 0.25,
                 checkpoint_threshold: float = 0.25):
        self._registry = registry
        self._recorder = recorder
        self.infeed_threshold = float(infeed_threshold)
        self.checkpoint_threshold = float(checkpoint_threshold)

    def _queue_depth(self) -> Optional[float]:
        if self._registry is None:
            return None
        # direct gauge read — a snapshot() here would sweep every poller
        # (native ctypes calls) per log window just for one number
        return self._registry.gauge("prefetch/queue_depth")

    def window(self, *, wall_s: float, infeed_wait_s: float = 0.0,
               checkpoint_wait_s: float = 0.0,
               guard_skips: int = 0) -> Dict[str, object]:
        return classify(wall_s, infeed_wait_s, checkpoint_wait_s,
                        guard_skips,
                        infeed_threshold=self.infeed_threshold,
                        checkpoint_threshold=self.checkpoint_threshold,
                        queue_depth=self._queue_depth())

    def window_from_spans(self, start_ns: int, end_ns: int,
                          guard_skips: int = 0) -> Dict[str, object]:
        """Verdict from span overlaps alone: the interval's infeed /
        checkpoint occupancy is computed from the recorder's ring buffer.
        Requires the recorder to still hold the window (ring capacity)."""
        if self._recorder is None:
            raise ValueError("window_from_spans needs a recorder")
        occ = occupancy_from_spans(self._recorder.snapshot(),
                                   start_ns, end_ns)
        wall_s = max(1e-9, (end_ns - start_ns) / 1e9)
        infeed = sum(occ.get(c, 0.0) for c in INFEED_CATEGORIES)
        ckpt = sum(occ.get(c, 0.0) for c in CHECKPOINT_CATEGORIES)
        return classify(wall_s, infeed, ckpt, guard_skips,
                        infeed_threshold=self.infeed_threshold,
                        checkpoint_threshold=self.checkpoint_threshold,
                        queue_depth=self._queue_depth())
