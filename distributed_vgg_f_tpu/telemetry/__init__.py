"""Unified telemetry spine: span tracing + counter registry + per-step
stall attribution (the observability layer the tf.data / TF-system papers
treat as core infrastructure, PAPERS.md).

Three pieces, one namespace:

- `spans` — thread-safe bounded ring buffer of host-side spans with Chrome
  trace-event export (Perfetto-loadable), cheap enough to stay on outside
  `jax.profiler` windows;
- `registry` — process-wide counters/gauges plus pull pollers that fold the
  native decoder's `decode_stats`, prefetch queue depth/wait, resilience
  events, and checkpoint timings into one `<subsystem>/<metric>` namespace;
- `stall` — classifies each logged interval as infeed_bound /
  compute_bound / checkpoint_bound / guard_stalled from the waits, span
  overlaps, and queue-depth gauges, emitted in the trainer's step log.

Plus the r10 live-observability plane over the same state (imported on
demand, not at package import):

- `exporter` — config-gated per-process HTTP server: /metrics (Prometheus
  text), /healthz (heartbeat liveness), /stallz (verdict history), /trace
  (live Chrome-trace snapshot);
- `flight` — always-on crash flight recorder: last-N-windows ring, dumped
  as a schema-validated black box on diagnosed aborts;
- `regress` — receipt-driven perf regression sentinel over the committed
  HOST_DECODE_RATE_R* trajectory (benchmarks/regression_sentinel.py CLI);
- `schema` — record validators, now carrying SCHEMA_VERSION for trainer
  JSONL records, bench artifacts, black boxes, and the trajectory file.

IMPORT CONTRACT: importing this package (or any submodule) pulls in neither
TensorFlow, nor jax, nor the native `.so`s — stdlib only. Wired call sites
(data/prefetch.py, train/trainer.py, checkpoint/manager.py, ...) import
telemetry, never the reverse; subsystems with native state hand the
registry a poller instead of being imported by it.
tests/test_telemetry.py pins this in a subprocess.
"""

from __future__ import annotations

import time
from typing import Iterator, Optional

from distributed_vgg_f_tpu.telemetry import schema  # noqa: F401 (re-export)
from distributed_vgg_f_tpu.telemetry.registry import (
    TelemetryRegistry,
    get_registry,
    inc,
    register_poller,
    set_gauge,
)
from distributed_vgg_f_tpu.telemetry.spans import (
    SpanRecorder,
    get_process_label,
    get_recorder,
    record,
    set_process_label,
    span,
)
from distributed_vgg_f_tpu.telemetry.stall import (
    VERDICTS,
    StallAttributor,
    classify,
    occupancy_from_spans,
)

__all__ = [
    "SpanRecorder", "TelemetryRegistry", "StallAttributor", "VERDICTS",
    "classify", "configure", "enabled", "get_process_label",
    "get_recorder", "get_registry", "inc", "instrument_iterator",
    "occupancy_from_spans", "record", "register_poller", "reset", "schema",
    "set_gauge", "set_process_label", "span",
]


def configure(*, enabled: Optional[bool] = None,
              span_capacity: Optional[int] = None,
              flight_windows: Optional[int] = None) -> None:
    """Flip the process-wide default recorder+registry from config
    (TelemetryConfig → Trainer.__init__). `enabled=False` is the
    kill-switch the overhead receipt measures against: record/inc become
    attribute-check-and-return."""
    if enabled is not None:
        get_recorder().enabled = bool(enabled)
        get_registry().enabled = bool(enabled)
    if span_capacity is not None:
        get_recorder().set_capacity(span_capacity)
    if flight_windows is not None:
        from distributed_vgg_f_tpu.telemetry.flight import get_flight
        get_flight().set_max_windows(flight_windows)


def enabled() -> bool:
    return get_recorder().enabled


def reset() -> None:
    """Clear the default recorder AND registry (tests — the defaults are
    process-global, so suites must re-baseline between cases)."""
    get_recorder().clear()
    get_registry().reset()


def instrument_iterator(source: Iterator, name: str = "next_batch",
                        category: str = "infeed",
                        counter: str = "prefetch/batches") -> Iterator:
    """Wrap a batch iterator with the per-batch telemetry the trainer's
    FULL feed path performs, op-for-op: the prefetch worker's two spans +
    source counter + queue-depth gauge, the consumer's wait span + batch/
    wait counters + queue-depth gauge, and the trainer loop's own infeed
    span + step-dispatch span/counter — 5 span records, 4 counter
    increments, 2 gauge sets per batch (data/prefetch.py + trainer loop +
    step wrapper). This is the instrumented side of the bench's
    telemetry-on-vs-off overhead receipt
    (benchmarks/host_pipeline_bench.py): the receipt must charge the 'on'
    column AT LEAST what training pays, never a lighter stand-in."""
    rec = get_recorder()
    reg = get_registry()
    it = iter(source)
    base = counter.rsplit("/", 1)[0]
    while True:
        t0 = time.monotonic_ns()
        try:
            batch = next(it)
        except StopIteration:
            return
        dt = time.monotonic_ns() - t0
        # worker side (prefetch.py _worker): source draw + device put
        rec.record("source_next", "infeed_source", t0, dt)
        rec.record("device_put", "infeed_source", t0 + dt, 0)
        reg.inc(f"{base}/source_batches")
        reg.set_gauge(f"{base}/queue_depth", 1)
        # consumer side (prefetch.py __next__)
        rec.record("prefetch_wait", category, t0, dt)
        reg.inc(counter)
        reg.inc(f"{base}/wait_ns", dt)
        reg.set_gauge(f"{base}/queue_depth", 0)
        # trainer loop's own infeed span + the jitted-step dispatch
        # wrapper (train/step.py)
        rec.record(name, category, t0, dt)
        rec.record("train_step_dispatch", "dispatch", t0 + dt, 0)
        reg.inc("step/dispatched")
        yield batch
