"""Always-on host-side span tracing — the cheap half of the observability
spine (tf.data-paper instrumentation model, PAPERS.md).

`jax.profiler` traces (utils/profiling.py StepProfiler) are the heavyweight
tool: device timelines, ICI collectives — but they cost enough that they run
for a 5-step window per run. This module is the complement: a thread-safe
bounded ring buffer of host-side spans (monotonic-ns start + duration,
category, thread id) cheap enough to leave on for the WHOLE run — one
`monotonic_ns()` pair and a deque append per span, no allocation beyond the
5-tuple. The buffer exports as Chrome trace-event JSON (`ph: "X"` complete
events), loadable in Perfetto / chrome://tracing next to (or instead of) a
profiler window.

Categories are the stall-attribution vocabulary (telemetry/stall.py):
"infeed" (consumer blocked on the input pipeline), "infeed_source" (the
prefetch worker's own source draw / H2D), "checkpoint" (save/restore/wait),
"dispatch" (host dispatch of the jitted step), "coord" (cross-process
barriers), "eval", "host" (everything else).

No numpy, no jax, no TF — importing this package must stay free of heavy
deps (tests/test_telemetry.py pins that).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

#: (name, category, start_ns, dur_ns, tid[, args]) — plain tuples, not
#: objects: recording must cost nanoseconds, not an allocation-heavy
#: dataclass. The 6th element (an args dict — trace-correlation ids for
#: cross-process stitching, telemetry/stitch.py) exists ONLY on spans that
#: passed one; the common path stays a 5-tuple, so consumers unpack with a
#: star (`name, cat, s0, dur, *rest = span`).
SpanTuple = Tuple[str, str, int, int, int]


class _Span:
    """Reusable context manager handed out by `SpanRecorder.span`."""

    __slots__ = ("_rec", "_name", "_cat", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, category: str):
        self._rec = rec
        self._name = name
        self._cat = category

    def __enter__(self) -> "_Span":
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc) -> bool:
        self._rec.record(self._name, self._cat, self._t0,
                         time.monotonic_ns() - self._t0)
        return False


class SpanRecorder:
    """Thread-safe bounded ring buffer of spans.

    The buffer is a `deque(maxlen=capacity)`: when full, the OLDEST span is
    evicted (and counted in `dropped`) — a long run keeps the most recent
    window, which is the one a stall diagnosis needs. `enabled=False` turns
    `record` into an attribute check + return (the kill-switch the overhead
    receipt measures against)."""

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._recorded = 0

    # ------------------------------------------------------------- recording
    def record(self, name: str, category: str, start_ns: int,
               dur_ns: int, args: Optional[dict] = None) -> None:
        """Append one completed span. Cheap enough for per-batch call sites;
        NOT meant for per-image granularity (the native decode stats cover
        that level through the registry pollers). `args` (a small JSON-able
        dict, e.g. a trace-correlation id) rides the span into the Chrome
        export; omitted, the stored tuple stays the allocation-free
        5-tuple."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._recorded += 1
            if args is None:
                self._buf.append((name, category, int(start_ns),
                                  int(dur_ns), tid))
            else:
                self._buf.append((name, category, int(start_ns),
                                  int(dur_ns), tid, args))

    def span(self, name: str, category: str = "host") -> _Span:
        """Context manager form: `with recorder.span("save", "checkpoint"):`"""
        return _Span(self, name, category)

    # --------------------------------------------------------------- reading
    def snapshot(self) -> List[SpanTuple]:
        """Copy of the current buffer contents, oldest first."""
        with self._lock:
            return list(self._buf)

    @property
    def recorded(self) -> int:
        """Total spans ever recorded (including since-evicted ones)."""
        with self._lock:
            return self._recorded

    @property
    def dropped(self) -> int:
        """Spans evicted by the ring bound — how much history the capacity
        lost. Dropped > 0 on a long run is expected, not an error."""
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0
            self._recorded = 0

    def set_capacity(self, capacity: int) -> None:
        """Resize the ring, keeping the newest spans that fit."""
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        with self._lock:
            self.capacity = int(capacity)
            self._buf = deque(self._buf, maxlen=self.capacity)

    # ---------------------------------------------------------------- export
    def to_chrome_trace(self, process_name: str | None = None) -> dict:
        """Chrome trace-event JSON object format: complete events (`ph: "X"`,
        timestamps/durations in MICROseconds — the format both Perfetto and
        chrome://tracing load). The monotonic-ns epoch is arbitrary but
        shared across every span in the process, so relative placement is
        exact.

        Metadata events (`ph: "M"`): `process_name` (the explicit param,
        else the module-level label from `set_process_label` — so a
        per-process sidecar reads `trainer_rank0` / `ingest_worker2` in
        Perfetto even before stitching) and one `thread_name` per live
        named thread whose ident appears in the buffer — captured at
        EXPORT time from threading.enumerate(), zero cost at record
        time."""
        pid = os.getpid()
        label = process_name or get_process_label()
        events = []
        if label:
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": label}})
        spans = self.snapshot()
        tids = {s[4] for s in spans}
        for t in threading.enumerate():
            if t.ident in tids and t.name:
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": t.ident,
                               "args": {"name": t.name}})
        for name, cat, start_ns, dur_ns, tid, *rest in spans:
            ev = {
                "name": name, "cat": cat, "ph": "X",
                "ts": start_ns / 1e3, "dur": dur_ns / 1e3,
                "pid": pid, "tid": tid,
            }
            if rest and rest[0]:
                ev["args"] = rest[0]
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"clock": "monotonic_ns",
                          "dropped_spans": self.dropped,
                          "recorded_spans": self.recorded},
        }

    def export_chrome_trace(self, path: str,
                            process_name: str | None = None) -> dict:
        """Write the Chrome trace JSON to `path`; returns the object written
        (so callers can log event counts without re-reading the file)."""
        trace = self.to_chrome_trace(process_name=process_name)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        return trace


# --------------------------------------------------------------------------
# Process-wide default recorder — the one every wired call site uses, so one
# export shows the whole host picture (infeed + checkpoint + dispatch).
# --------------------------------------------------------------------------

_default = SpanRecorder()

#: Role label for THIS process's trace exports ("" = unset, exports fall
#: back to whatever explicit process_name the caller passes). Set once at
#: process startup (trainer rank, ingest worker CLI, serving entry) so
#: every export from the process — the fit-finally sidecar AND the live
#: /trace endpoint — carries the same Perfetto process label.
_process_label = ""


def set_process_label(label: str) -> None:
    global _process_label
    _process_label = str(label or "")


def get_process_label() -> str:
    return _process_label


def get_recorder() -> SpanRecorder:
    return _default


def span(name: str, category: str = "host") -> _Span:
    """`with spans.span("next_batch", "infeed"):` on the default recorder."""
    return _default.span(name, category)


def record(name: str, category: str, start_ns: int, dur_ns: int,
           args: Optional[dict] = None) -> None:
    _default.record(name, category, start_ns, dur_ns, args)
