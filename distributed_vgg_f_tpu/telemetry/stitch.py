"""Cross-process trace stitching: N per-process Chrome traces → ONE
Perfetto-loadable multi-process trace with flow arrows across the wire.

Each process in the fleet exports its own Chrome trace (SpanRecorder
.export_chrome_trace / the exporter's /trace endpoint) — useful alone,
but a fetch that blocks the trainer lives in the TRAINER's trace while
the decode that caused it lives in a WORKER's trace, and nobody can see
the causality. The wire fixes half of this (r22): ingest `get` frames
and serving HTTP requests carry a client-generated trace-correlation id
in their existing JSON headers (wire-tolerant — an absent id is
byte-for-byte today's protocol), and both sides record their span with
the id in span args (`trace_id` on singles, `trace_ids` on a batched
server span, `flow: "out"` on the requesting side, `"in"` on the
serving side). This module does the other half offline:

- each input trace gets a DISTINCT Perfetto pid (1..N — the OS pid is
  useless here: in-process ingest workers share it) and keeps its
  `process_name` metadata under the remapped pid;
- every `trace_id` seen on both an "out" span and ≥1 "in" span becomes a
  Chrome flow (`ph:"s"` at the source span, `ph:"f", bp:"e"` at each
  destination) — Perfetto draws the arrow from the trainer's
  `service_get` to the owning worker's `service_decode`, from the
  serving request to the engine flush that carried it;
- timestamps are NOT rebased: every process's spans use the same
  CLOCK_MONOTONIC (single-host fleets — the receipt's case), so relative
  placement is already exact. Multi-host stitching would need a clock
  offset per input; `otherData.clock` says what the traces claim.

Output: one trace JSON + a manifest (inputs, flows, counts) validated by
schema.validate_stitch_manifest — the committed receipt's shape.

Stdlib-only leaf (telemetry import contract). CLI:

    python -m distributed_vgg_f_tpu.telemetry.stitch \
        --out fleet_trace.json --manifest fleet_trace.manifest.json \
        trainer_trace.json worker0_trace.json worker1_trace.json
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Sequence

from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION


def _load_trace(path: str) -> List[dict]:
    with open(path) as f:
        obj = json.load(f)
    events = obj.get("traceEvents") if isinstance(obj, dict) else obj
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome trace "
                         f"(no traceEvents list)")
    return [ev for ev in events if isinstance(ev, dict)]


def _ids_of(ev: dict) -> List[str]:
    """Correlation ids a span event carries: `trace_id` (one) or
    `trace_ids` (a batched server span — each id is a separate inbound
    edge)."""
    args = ev.get("args")
    if not isinstance(args, dict):
        return []
    one = args.get("trace_id")
    many = args.get("trace_ids")
    ids = [one] if isinstance(one, str) and one else []
    if isinstance(many, (list, tuple)):
        ids.extend(i for i in many if isinstance(i, str) and i)
    return ids


def stitch_traces(paths: Sequence[str]) -> Dict[str, dict]:
    """Merge per-process Chrome traces into one multi-process trace.

    Returns {"trace": <chrome trace object>, "manifest": <stitch
    manifest>}. Raises on unreadable/garbage inputs — a stitch receipt
    built from half the fleet is worse than no receipt."""
    if not paths:
        raise ValueError("stitch needs at least one input trace")
    merged: List[dict] = []
    inputs: List[dict] = []
    # trace_id → {"out": [(pid, ev)], "in": [(pid, ev)]}
    edges: Dict[str, Dict[str, list]] = {}
    for i, path in enumerate(paths):
        pid = i + 1  # distinct per INPUT — in-process workers share the
        #              OS pid, so the OS pid cannot be the Perfetto pid
        events = _load_trace(path)
        process_name = None
        ev_count = 0
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                args = ev.get("args")
                if isinstance(args, dict) and args.get("name"):
                    process_name = str(args["name"])
            merged.append(ev)
            ev_count += 1
            if ev.get("ph") != "X":
                continue
            args = ev.get("args")
            flow = args.get("flow") if isinstance(args, dict) else None
            for trace_id in _ids_of(ev):
                side = "out" if flow == "out" else "in"
                edges.setdefault(trace_id, {"out": [], "in": []})[
                    side].append((pid, ev))
        if process_name is None:
            # a trace exported without a label still needs a lane name
            process_name = os.path.splitext(os.path.basename(path))[0]
            merged.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": process_name}})
        inputs.append({"path": str(path), "pid": pid,
                       "process_name": process_name, "events": ev_count})
    flows: List[dict] = []
    flow_id = 0
    for trace_id in sorted(edges):
        outs, ins = edges[trace_id]["out"], edges[trace_id]["in"]
        if not outs or not ins:
            continue  # an unpaired id is a span whose peer's ring
            #            already evicted its half — not an error
        flow_id += 1
        src_pid, src = outs[0]
        # the flow step's ts must sit INSIDE its span for Perfetto to
        # attach the arrow to it — midpoint is safely inside both
        src_ts = float(src["ts"]) + float(src.get("dur", 0)) / 2.0
        flows.append({"id": flow_id, "trace_id": trace_id,
                      "src": {"pid": src_pid, "name": src["name"]},
                      "dst": [{"pid": p, "name": d["name"]}
                              for p, d in ins]})
        merged.append({"name": f"flow_{trace_id}", "cat": "flow",
                       "ph": "s", "id": flow_id, "ts": src_ts,
                       "pid": src_pid, "tid": src["tid"]})
        for dst_pid, dst in ins:
            dst_ts = float(dst["ts"]) + float(dst.get("dur", 0)) / 2.0
            merged.append({"name": f"flow_{trace_id}", "cat": "flow",
                           "ph": "f", "bp": "e", "id": flow_id,
                           "ts": dst_ts, "pid": dst_pid,
                           "tid": dst["tid"]})
    trace = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {"clock": "monotonic_ns",
                      "stitched_inputs": len(inputs),
                      "flows": len(flows)},
    }
    manifest = {
        "schema_version": SCHEMA_VERSION,
        "kind": "stitched_trace_manifest",
        "inputs": inputs,
        "flows": flows,
        "events_total": len(merged),
    }
    return {"trace": trace, "manifest": manifest}


def stitch_to_files(paths: Sequence[str], out_path: str,
                    manifest_path: Optional[str] = None) -> dict:
    """stitch_traces + write both artifacts; returns the manifest."""
    result = stitch_traces(paths)
    for target, obj in ((out_path, result["trace"]),
                        (manifest_path, result["manifest"])):
        if not target:
            continue
        parent = os.path.dirname(os.path.abspath(target))
        os.makedirs(parent, exist_ok=True)
        tmp = target + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, allow_nan=False)
        os.replace(tmp, target)
    return result["manifest"]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_vgg_f_tpu.telemetry.stitch",
        description="Merge per-process Chrome traces into one "
                    "multi-process trace with cross-process flow arrows.")
    parser.add_argument("traces", nargs="+",
                        help="per-process Chrome trace JSON files")
    parser.add_argument("--out", required=True,
                        help="stitched trace output path")
    parser.add_argument("--manifest", default="",
                        help="stitch manifest output path (default: "
                             "<out> with .manifest.json)")
    args = parser.parse_args(argv)
    manifest_path = args.manifest or (
        os.path.splitext(args.out)[0] + ".manifest.json")
    manifest = stitch_to_files(args.traces, args.out, manifest_path)
    print(json.dumps({"event": "stitched_trace", "out": args.out,
                      "manifest": manifest_path,
                      "inputs": len(manifest["inputs"]),
                      "flows": len(manifest["flows"]),
                      "events_total": manifest["events_total"]}),
          flush=True)
    return 0


if __name__ == "__main__":  # pragma: no cover — process entry point
    raise SystemExit(main())
